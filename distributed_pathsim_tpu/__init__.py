"""distributed_pathsim_tpu — TPU-native meta-path similarity framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
phamtheanhphu/Distributed-PathSim (Spark + GraphFrames PathSim over DBLP
HINs): typed-HIN data model, metapath compiler, and dense / sharded /
sparse / pallas execution backends computing commuting-matrix chains on
TPU meshes.
"""

__version__ = "0.1.0"

from .config import RunConfig  # noqa: F401
from .data.schema import HINGraph, HINSchema  # noqa: F401
from .data.encode import EncodedHIN, encode_hin  # noqa: F401
from .data.gexf import read_gexf  # noqa: F401
from .ops.metapath import MetaPath, compile_metapath  # noqa: F401
from .backends.base import available_backends, create_backend  # noqa: F401
from .driver import PathSimDriver  # noqa: F401
from .engine import build, load_dataset  # noqa: F401
