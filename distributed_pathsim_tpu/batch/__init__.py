"""Corpus-scale batch tier: campaigns over every row at once.

Everything landed so far — the DP metapath planner, the packed factor
formats, the centroid index, partitioned serving — was built to answer
one request at a time. This package points the same primitives at the
*whole corpus*: ``topk-all`` (top-k for every source row, a sharded
blocked GEMM sweep) and ``simjoin`` (every pair scoring ≥ τ, with
provably score-safe block pruning). Campaigns checkpoint per row block
through :class:`~..utils.checkpoint.CheckpointManager` and resume
bit-identically after preemption (DESIGN.md §31).
"""

from .campaign import (  # noqa: F401
    BatchEngine,
    CampaignResult,
    CampaignSpec,
    run_topk_campaign,
)
from .simjoin import run_simjoin_campaign  # noqa: F401
