"""``dpathsim batch`` — corpus-scale campaigns with checkpointed resume.

::

    dpathsim batch topk-all --dataset dblp/dblp_small.gexf \
        --metapath APVPA --k 10 --checkpoint-dir /tmp/ck \
        --out topk.npz --emit-pairs pairs.jsonl
    dpathsim batch simjoin --dataset dblp/dblp_small.gexf \
        --tau 0.4 --checkpoint-dir /tmp/ck2 --out pairs.jsonl
    dpathsim batch resume --dataset dblp/dblp_small.gexf \
        --checkpoint-dir /tmp/ck --out topk.npz

``topk-all`` computes top-k for EVERY source row; ``simjoin`` emits
every pair scoring ≥ τ. Both checkpoint per row block: SIGTERM →
flush-and-exit-75 (EX_TEMPFAIL, "re-run me"), and ``resume`` — or
simply re-running the original command — skips completed blocks and
produces byte-identical outputs. ``resume`` needs no campaign flags:
it reads the checkpoint manifest's stored identity config and refuses
a directory whose graph/parameters don't match (DESIGN.md §31).

``--workers N`` fans blocks across N subprocess replicas through the
batch block scheduler (router/batch.py) — same bytes, more hosts.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def _parse_dataset(spec: str):
    if spec.startswith("synthetic:"):
        from ..data.synthetic import synthetic_hin
        from ..router.cli import _parse_synthetic

        return synthetic_hin(**_parse_synthetic(spec))
    from ..engine import load_dataset

    return load_dataset(spec)


def build_batch_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dpathsim batch",
        description="corpus-scale top-k / similarity-join campaigns",
    )
    sub = p.add_subparsers(dest="action", required=True)

    def common(sp, mode: str):
        sp.add_argument("--dataset", required=True,
                        help="GEXF path or synthetic:authors=..,"
                        "papers=..,venues=..,seed=..")
        sp.add_argument("--metapath", default="APVPA")
        sp.add_argument("--variant", default="rowsum",
                        choices=("rowsum", "diagonal")
                        if mode == "topk" else ("rowsum",))
        sp.add_argument("--block-rows", type=int, default=None,
                        help="rows per sweep block (default: tuned, "
                        "snapped to the pow-2 ladder)")
        sp.add_argument("--factor-format", default=None,
                        help="packed factor format (default: tuned)")
        sp.add_argument("--checkpoint-dir", default=None,
                        help="per-block resume directory; omitting it "
                        "disables resume")
        sp.add_argument("--emit-pairs", default=None,
                        help="write (row, col, score) JSONL training "
                        "pairs here")
        sp.add_argument("--no-jax", action="store_true",
                        help="force the numpy GEMM arm")
        sp.add_argument("--workers", type=int, default=0,
                        help="fan blocks across N subprocess replicas "
                        "(0 = single-host)")

    t = sub.add_parser("topk-all", help="top-k for every source row")
    common(t, "topk")
    t.add_argument("--k", type=int, default=10)
    t.add_argument("--out", default=None,
                   help="write vals/idxs arrays to this .npz")

    s = sub.add_parser("simjoin", help="all pairs with PathSim >= tau")
    common(s, "simjoin")
    s.add_argument("--tau", type=float, required=True)
    s.add_argument("--grouping", default="degree",
                   choices=("natural", "degree", "centroid"),
                   help="row-block grouping for the prune bounds "
                   "(fleet runs require 'natural')")
    s.add_argument("--out", default=None,
                   help="write qualifying pairs to this JSONL")

    r = sub.add_parser("resume", help="continue a preempted campaign")
    r.add_argument("--dataset", required=True)
    r.add_argument("--checkpoint-dir", required=True)
    r.add_argument("--emit-pairs", default=None)
    r.add_argument("--no-jax", action="store_true")
    r.add_argument("--out", default=None)
    return p


def _engine(args, *, metapath=None, variant=None,
            block_rows=None, factor_format=None):
    from ..ops.metapath import compile_metapath
    from .campaign import BatchEngine

    hin = _parse_dataset(args.dataset)
    mp = compile_metapath(metapath or args.metapath, hin.schema)
    return BatchEngine(
        hin, mp,
        variant=variant or args.variant,
        factor_format=factor_format
        or getattr(args, "factor_format", None),
        block_rows=block_rows or getattr(args, "block_rows", None),
        use_jax=not args.no_jax,
    )


def _scheduler(args, engine):
    """``--workers N`` → a started BlockScheduler over N subprocess
    replicas serving the same dataset/metapath/variant."""
    if not getattr(args, "workers", 0):
        return None
    from ..router.batch import BlockScheduler
    from ..router.transport import SubprocessTransport

    argv_tail = [
        "--dataset", args.dataset,
        "--metapath", engine.metapath.name,
        "--variant", engine.variant,
        # batch campaigns are read-only: boot replicas WITHOUT update
        # headroom so their graph fingerprint matches the local
        # engine's raw parse (the serve parser defaults to 0.25,
        # which pads capacity and changes the token)
        "--headroom", "0",
    ]
    transports = {
        f"w{i}": SubprocessTransport(
            f"w{i}",
            [sys.executable, "-m", "distributed_pathsim_tpu.cli",
             "worker", "--worker-id", f"w{i}"] + argv_tail,
        )
        for i in range(int(args.workers))
    }
    sched = BlockScheduler(transports)
    sched.start()
    return sched


def _finish_topk(args, result) -> None:
    if args.out:
        np.savez(args.out, vals=result.vals, idxs=result.idxs)
    summary = {
        "mode": "topk",
        "n": int(result.vals.shape[0]),
        "k": int(result.vals.shape[1]),
        "blocks": result.blocks_total,
        "resumed": result.blocks_resumed,
        "rows_per_s": round(result.rows_per_s, 1),
        "bytes_read_per_row": round(result.bytes_read_per_row, 1),
        "backend": result.backend_mode,
    }
    print(json.dumps(summary))


def _finish_simjoin(args, result) -> None:
    out = getattr(args, "out", None)
    if out:
        with open(out, "w", encoding="utf-8") as f:
            for r, c, s in zip(result.rows, result.cols, result.scores):
                f.write(json.dumps(
                    {"row": int(r), "col": int(c), "score": float(s)}
                ) + "\n")
    summary = {
        "mode": "simjoin",
        "pairs": int(result.rows.shape[0]),
        "blocks": result.blocks_total,
        "resumed": result.blocks_resumed,
        "prune_ratio": round(result.prune_ratio, 4),
        "backend": result.backend_mode,
    }
    print(json.dumps(summary))


def batch_main(argv: list[str] | None = None) -> int:
    from ..resilience import (
        PREEMPTED_EXIT_CODE, Preempted, preemption_handler,
    )
    from .campaign import run_topk_campaign
    from .simjoin import run_simjoin_campaign

    args = build_batch_parser().parse_args(argv)
    installed = preemption_handler.install()
    sched = None
    try:
        if args.action == "resume":
            import pathlib

            mpath = pathlib.Path(args.checkpoint_dir) / "manifest.json"
            if not mpath.exists():
                raise FileNotFoundError(
                    f"no campaign manifest in {args.checkpoint_dir}"
                )
            cfg = json.loads(mpath.read_text()).get("__config__") or {}
            if not cfg:
                raise ValueError(
                    f"{args.checkpoint_dir} holds no campaign identity "
                    "config; was this directory written by "
                    "`dpathsim batch`?"
                )
            engine = _engine(
                args,
                metapath=cfg["metapath"], variant=cfg["variant"],
                block_rows=cfg["block_rows"],
                factor_format=cfg["factor_format"],
            )
            # the manifest config check inside the campaign refuses a
            # changed graph (base_fp/delta_seq mismatch) loudly
            if cfg.get("mode") == "simjoin":
                result = run_simjoin_campaign(
                    engine, cfg["tau"],
                    checkpoint_dir=args.checkpoint_dir,
                    grouping=cfg.get("grouping", "degree"),
                    emit_pairs=args.emit_pairs,
                )
                _finish_simjoin(args, result)
            else:
                result = run_topk_campaign(
                    engine, cfg["k"],
                    checkpoint_dir=args.checkpoint_dir,
                    emit_pairs=args.emit_pairs,
                )
                _finish_topk(args, result)
            return 0
        engine = _engine(args)
        sched = _scheduler(args, engine)
        if args.action == "topk-all":
            result = run_topk_campaign(
                engine, args.k,
                checkpoint_dir=args.checkpoint_dir,
                emit_pairs=args.emit_pairs,
                scheduler=sched,
            )
            _finish_topk(args, result)
        else:
            result = run_simjoin_campaign(
                engine, args.tau,
                checkpoint_dir=args.checkpoint_dir,
                grouping=args.grouping
                if not sched else "natural",
                emit_pairs=args.emit_pairs,
                scheduler=sched,
            )
            _finish_simjoin(args, result)
        return 0
    except Preempted as exc:
        print(f"preempted: {exc}", file=sys.stderr)
        return PREEMPTED_EXIT_CODE
    finally:
        if sched is not None:
            sched.close()
        if installed:
            preemption_handler.uninstall()
