"""The ``--emit-pairs`` JSONL contract: loader, split, negatives.

Schema (one JSON object per line, written by
:func:`~.campaign.export_pairs` and the simjoin runner — DESIGN.md
§31a):

- ``row``   int ≥ 0 — source node's dense row index;
- ``col``   int ≥ 0 — neighbor's dense row index (never == row);
- ``score`` finite float — the EXACT PathSim score of the pair, JSON
  shortest-repr so the f64 bytes round-trip exactly.

Unknown keys are rejected loudly: a producer drifting the schema must
fail the consumer's load, not silently train on half a record. These
helpers are the learned tier's data plumbing (the trainer distills
from this stream), kept in batch/ because the schema belongs to the
producer.
"""

from __future__ import annotations

import json

import numpy as np

PAIRS_FIELDS = ("row", "col", "score")


def load_pairs(path: str) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Read an ``--emit-pairs`` JSONL file → ``(rows, cols, scores)``
    (int64, int64, f64). Validates the schema per line with the line
    number in every error."""
    rows: list[int] = []
    cols: list[int] = []
    scores: list[float] = []
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                raise ValueError(
                    f"{path}:{lineno}: not JSON ({exc})"
                ) from exc
            if not isinstance(rec, dict) or set(rec) != set(PAIRS_FIELDS):
                raise ValueError(
                    f"{path}:{lineno}: expected exactly the fields "
                    f"{PAIRS_FIELDS}, got "
                    f"{sorted(rec) if isinstance(rec, dict) else rec!r}"
                )
            r, c, s = rec["row"], rec["col"], rec["score"]
            if not (isinstance(r, int) and isinstance(c, int)) or (
                isinstance(r, bool) or isinstance(c, bool)
            ):
                raise ValueError(
                    f"{path}:{lineno}: row/col must be integers"
                )
            if r < 0 or c < 0:
                raise ValueError(
                    f"{path}:{lineno}: negative node index ({r}, {c})"
                )
            s = float(s)
            if not np.isfinite(s):
                raise ValueError(
                    f"{path}:{lineno}: non-finite score {s!r}"
                )
            rows.append(r)
            cols.append(c)
            scores.append(s)
    return (
        np.asarray(rows, dtype=np.int64),
        np.asarray(cols, dtype=np.int64),
        np.asarray(scores, dtype=np.float64),
    )


def split_pairs(
    rows: np.ndarray, val_frac: float = 0.1, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Seeded, deterministic train/val split BY SOURCE ROW: every pair
    of one source lands on the same side, so validation measures
    ranking on sources the tower's hard-pool slates never drew — the
    honest distillation-quality number. Returns boolean masks
    ``(train_mask, val_mask)`` over the pair arrays."""
    rows = np.asarray(rows)
    if not 0.0 <= val_frac < 1.0:
        raise ValueError(f"val_frac must be in [0, 1), got {val_frac}")
    uniq = np.unique(rows)
    n_val = int(round(len(uniq) * val_frac))
    rng = np.random.default_rng(seed)
    val_sources = rng.permutation(uniq)[:n_val]
    val_mask = np.isin(rows, val_sources)
    return ~val_mask, val_mask


def sample_negatives(
    rows: np.ndarray,
    cols: np.ndarray,
    n_nodes: int,
    ratio: float = 1.0,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Draw ``ratio × len(rows)`` uniform negative pairs that collide
    with neither the positive set nor the diagonal. Deterministic for
    a seed; resampling is bounded (collisions are resampled a fixed
    number of rounds, then dropped — on a tiny dense graph the
    negative pool can be genuinely exhausted)."""
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    if n_nodes < 2:
        return (np.empty(0, np.int64), np.empty(0, np.int64))
    want = int(round(len(rows) * ratio))
    seen = set(zip(rows.tolist(), cols.tolist()))
    rng = np.random.default_rng(seed)
    out_r: list[int] = []
    out_c: list[int] = []
    for _ in range(8):  # bounded resampling
        need = want - len(out_r)
        if need <= 0:
            break
        nr = rng.integers(0, n_nodes, size=need)
        nc = rng.integers(0, n_nodes, size=need)
        for r, c in zip(nr.tolist(), nc.tolist()):
            if r == c or (r, c) in seen:
                continue
            seen.add((r, c))
            out_r.append(r)
            out_c.append(c)
    return (
        np.asarray(out_r, dtype=np.int64),
        np.asarray(out_c, dtype=np.int64),
    )
