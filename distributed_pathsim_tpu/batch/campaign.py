"""The batch campaign engine: top-k for EVERY row as a blocked sweep.

A campaign is the corpus-scale twin of the serving path: the same
plan-ordered half-chain fold (ops/planner.py, MP001), the same packed
factor surface (ops/packed.py, CF001), and the same f64 scoring /
tie-order primitives (ops/pathsim.py, DT002) — pointed at all N rows
instead of one request. Per row block ``[lo, hi)`` the sweep computes
``M[lo:hi, :] = C[lo:hi] @ Cᵀ`` as one fixed-shape GEMM (blocks are
padded to ``block_rows``, a pow-2 resolved through the tuning ladder,
so a campaign compiles exactly one device program and steady state
never recompiles), normalizes on host in f64, and selects through
``pathsim.topk_from_score_rows`` — bit-identical to the serving
oracle's ``backend.topk_rows`` because every count that enters the
division is an exact integer in f64 (< 2⁵³) under ANY association
order, and selection shares the (descending score, ascending column)
tie order.

Campaigns checkpoint per block through
:class:`~..utils.checkpoint.CheckpointManager`. The manifest's
identity config is content-addressed — keyed on ``(base_fp,
delta_seq, metapath, variant, k|τ, block_rows, factor_format)`` — so
resuming against a graph that absorbed a delta mid-campaign is
refused loudly (the manager's config mismatch), never silently mixed.
SIGTERM lands between blocks: the in-flight block's shard is already
durable when :func:`~..resilience.preemption.PreemptionHandler.check`
raises, so a resume skips completed blocks and re-produces
byte-identical shard outputs (DESIGN.md §31).

Block decode (the packed-chunk gather) runs on a prefetch thread,
double-buffered against the current block's GEMM, so decode overlaps
matmul without changing result bytes (the consumer drains blocks in
issue order).
"""

from __future__ import annotations

import dataclasses
import json
import queue
import threading
import time

import numpy as np

from ..obs.metrics import get_registry
from ..obs.trace import get_tracer
from ..ops import packed, pathsim, planner
from ..resilience import preemption_handler
from ..serving.cache import graph_fingerprint
from ..utils.checkpoint import CheckpointManager
from ..utils.logging import runtime_event

# The block-sweep doorway registry (analysis/BT001, the PROTOCOL_OPS /
# PACKED_SURFACE / COMPACTION_SURFACE pattern): these engine primitives
# skip the campaign layer's checkpoint manifest, stale-graph fencing,
# and preemption accounting. Calling them from anywhere but the
# campaign runners (batch/campaign.py, batch/simjoin.py) produces
# results no manifest owns — un-resumable, un-fenced, and invisible to
# the batch metrics — so the analyzer seals them inside this package.
BATCH_SURFACE = frozenset({
    "sweep_topk_block", "sweep_scores_block", "sweep_pair_block",
})

# CheckpointManager's on-disk format key: bumping it refuses stale
# directories from an incompatible layout instead of misreading them.
_MANIFEST_FORMAT = "batch-v1"


_jax_exact = pathsim.jax_exact


@dataclasses.dataclass(frozen=True)
class CampaignSpec:
    """A campaign's content-addressed identity — exactly the fields
    that change result bytes. This dict IS the checkpoint manifest's
    ``__config__``: two campaigns with equal specs over equal graphs
    produce byte-identical shards, and a resume against a different
    spec (a delta landed, a different k/τ, a re-tuned block size) is
    refused by the manager's config check."""

    mode: str                # "topk" | "simjoin"
    metapath: str
    variant: str
    base_fp: str
    delta_seq: int
    block_rows: int
    factor_format: str
    k: int | None = None
    tau: float | None = None
    grouping: str = "natural"   # simjoin row grouping: natural|degree|centroid

    def manifest_config(self) -> dict:
        cfg = {
            "format": _MANIFEST_FORMAT,
            "mode": self.mode,
            "metapath": self.metapath,
            "variant": self.variant,
            "base_fp": self.base_fp,
            "delta_seq": int(self.delta_seq),
            "block_rows": int(self.block_rows),
            "factor_format": self.factor_format,
        }
        if self.k is not None:
            cfg["k"] = int(self.k)
        if self.tau is not None:
            cfg["tau"] = float(self.tau)
        if self.mode == "simjoin":
            cfg["grouping"] = self.grouping
        return cfg


def block_ranges(n: int, block_rows: int) -> list[tuple[int, int]]:
    """The campaign's work units: contiguous ``[lo, hi)`` row ranges,
    every block ``block_rows`` wide except a short tail (which the
    engine pads back to full width before the GEMM)."""
    return [
        (lo, min(lo + block_rows, n)) for lo in range(0, n, block_rows)
    ]


class BatchEngine:
    """The campaign's compute core: one plan-ordered half-chain
    factor, its denominators, the resident ``Cᵀ`` GEMM operand, and
    the fixed-shape block primitives every campaign mode shares.

    An engine binds a SNAPSHOT of the graph: ``(base_fp, delta_seq)``
    at construction is the identity every shard and every fleet
    dispatch is fenced against."""

    def __init__(
        self,
        hin,
        metapath,
        variant: str = "rowsum",
        factor_format: str | None = None,
        block_rows: int | None = None,
        delta_seq: int = 0,
        use_jax: bool = True,
    ):
        if variant not in ("rowsum", "diagonal"):
            raise ValueError(f"unknown PathSim variant {variant!r}")
        self.hin = hin
        self.metapath = metapath
        self.variant = variant
        self.n = int(hin.type_size(metapath.source_type))
        if self.n < 2:
            raise ValueError("batch campaigns need at least two rows")
        self.delta_seq = int(delta_seq)
        self.base_fp = graph_fingerprint(hin)
        from .. import tuning

        fmt = factor_format
        if fmt is None:
            fmt = str(tuning.choose("factor_format", n=self.n, default="coo"))
        self.factor_format = fmt
        # The half-chain fold stays behind the planner doorway (MP001):
        # packed.fold_half delegates to planner.fold_half, so the
        # association order is the EvalPlan's DP order.
        self.plan = planner.plan_metapath(hin, metapath)
        self.factor = packed.fold_half(hin, metapath, fmt)
        self.v = int(self.factor.shape[1])
        g = packed.factor_colsum(self.factor)
        if variant == "rowsum":
            # d = C·g — row sums of M without materializing M (the
            # same identity the partition workers' denominators use)
            self.d = packed.factor_rowsums_weighted(self.factor, g)
        else:
            self.d = packed.factor_diag(self.factor)
        if block_rows is None:
            block_rows = int(tuning.choose(
                "batch_block_rows", n=self.n, default=256,
            ))
        from ..tuning.registry import resolve_ladder

        # snap to the pow-2 ladder: one block shape → one compiled
        # program → zero steady-state recompiles, by construction
        self.block_rows = int(
            resolve_ladder("pow2", max(int(block_rows), 1))[-1]
        )
        # COO arm: one row-sorted index built up front so arbitrary-row
        # gathers are O(nnz gathered), like the packed accessor's
        self._coo_order = None
        self._coo_indptr = None
        if not packed.is_packed(self.factor):
            c = packed.as_coo(self.factor)
            order = np.argsort(c.rows, kind="stable")
            self._coo_order = (
                c.rows[order], c.cols[order],
                np.asarray(c.weights, dtype=np.float64)[order],
            )
            self._coo_indptr = np.searchsorted(
                self._coo_order[0], np.arange(self.n + 1)
            )
        # The GEMM's right operand, resident once per campaign and
        # amortized over every block (the sweep's whole point: N/B
        # blocks share one decode of Cᵀ).
        self._ct = np.ascontiguousarray(
            self._gather_dense(np.arange(self.n, dtype=np.int64)).T
        )
        self._jax = _jax_exact() if use_jax else None
        self._ct_dev = self._jax.device_put(self._ct) if self._jax else None
        self.backend_mode = "jax" if self._jax is not None else "numpy"
        reg = get_registry()
        self._m_backend = reg.counter(
            "dpathsim_batch_score_backend_total",
            "batch block GEMMs by execution backend (numpy = counted "
            "fallback: no jax or no x64 mode)",
        )
        self._m_rows = reg.counter(
            "dpathsim_batch_rows_total", "campaign rows computed",
        )
        # honest read-volume accounting: decoded factor bytes (COO-
        # equivalent stream of the gathered block rows) + the resident
        # operand bytes each block's GEMM streams
        self.bytes_decoded = 0
        self.bytes_operand = 0
        runtime_event(
            "batch_engine_ready", echo=False,
            n=self.n, v=self.v, block_rows=self.block_rows,
            factor_format=fmt, backend=self.backend_mode,
            base_fp=self.base_fp, delta_seq=self.delta_seq,
        )

    # -- spec / identity ---------------------------------------------------

    def spec(
        self,
        mode: str,
        k: int | None = None,
        tau: float | None = None,
        grouping: str = "natural",
    ) -> CampaignSpec:
        return CampaignSpec(
            mode=mode, metapath=self.metapath.name, variant=self.variant,
            base_fp=self.base_fp, delta_seq=self.delta_seq,
            block_rows=self.block_rows, factor_format=self.factor_format,
            k=k, tau=tau, grouping=grouping,
        )

    def _gather_dense(self, rows: np.ndarray) -> np.ndarray:
        """Dense [len(rows), V] gather for ANY resident format: packed
        layouts go through the sanctioned accessor; the coo arm reads
        the row-sorted copy built at init. Same exact f64 integers
        either way (the packed round trip is property-tested)."""
        if packed.is_packed(self.factor):
            return packed.gather_rows_dense(self.factor, rows)
        crows, ccols, cw = self._coo_order
        indptr = self._coo_indptr
        starts = indptr[rows]
        counts = indptr[rows + 1] - starts
        out = np.zeros((rows.shape[0], self.v), dtype=np.float64)
        total = int(counts.sum())
        if total == 0:
            return out
        ridx = np.repeat(np.arange(rows.shape[0]), counts)
        cum = np.concatenate([[0], np.cumsum(counts)])
        flat = np.repeat(starts, counts) + (
            np.arange(total) - np.repeat(cum[:-1], counts)
        )
        out[ridx, ccols[flat]] = cw[flat]
        return out

    # -- block primitives (the BT001-sealed surface) -----------------------

    def decode_block(self, lo: int, hi: int):
        """Gather rows ``[lo, hi)`` dense and pad to ``block_rows`` by
        repeating the first row (the serving buckets' pad idiom: pad
        rows are sliced off before anything downstream sees them, so
        padding is semantically inert and shapes stay fixed)."""
        rows = np.arange(lo, hi, dtype=np.int64)
        bd = self._gather_dense(rows)
        self.bytes_decoded += int(np.count_nonzero(bd)) * 24
        if bd.shape[0] < self.block_rows:
            pad = np.broadcast_to(
                bd[:1], (self.block_rows - bd.shape[0], self.v)
            )
            bd = np.concatenate([bd, pad], axis=0)
        return rows, bd

    def _counts(self, bd: np.ndarray) -> np.ndarray:
        """``bd @ Cᵀ`` on the fastest exact path available. Both arms
        produce identical bytes: counts are exact integers in f64, so
        the device's summation order cannot move them."""
        if self._jax is not None:
            jnp = self._jax.numpy
            m = np.asarray(jnp.matmul(
                self._jax.device_put(bd), self._ct_dev
            ))
            self._m_backend.inc(backend="jax")
        else:
            m = bd @ self._ct
            self._m_backend.inc(backend="numpy")
        self.bytes_operand += int(self._ct.nbytes)
        return m

    def sweep_topk_block(self, lo: int, hi: int, k: int, decoded=None):
        """Top-k for rows ``[lo, hi)``: (values f64 [B, k'], indices
        int64 [B, k']) with k' = min(k, N−1), self pairs excluded —
        row-for-row bit-identical to ``backend.topk_rows`` (same
        integer counts, same f64 normalization, same tie order)."""
        rows, bd = decoded if decoded is not None else self.decode_block(
            lo, hi
        )
        m = self._counts(bd)[: rows.shape[0]]
        scores = pathsim.score_rows(m, self.d[rows], self.d, xp=np)
        scores[np.arange(rows.shape[0]), rows] = -np.inf
        vals, idxs = pathsim.topk_from_score_rows(
            scores, min(int(k), max(self.n - 1, 1))
        )
        self._m_rows.inc(float(rows.shape[0]))
        return vals, idxs

    def sweep_scores_block(self, lo: int, hi: int, decoded=None):
        """Raw f64 score rows for ``[lo, hi)`` (self pair INCLUDED,
        exactly as the oracle's score row has it) — the simjoin
        diagonal blocks and the parity harness read this."""
        rows, bd = decoded if decoded is not None else self.decode_block(
            lo, hi
        )
        m = self._counts(bd)[: rows.shape[0]]
        self._m_rows.inc(float(rows.shape[0]))
        return rows, pathsim.score_rows(m, self.d[rows], self.d, xp=np)

    def sweep_pair_block(self, rows_i: np.ndarray, cols_j: np.ndarray):
        """Exact score sub-block for arbitrary row/column sets — the
        simjoin exact-fallback path. Both index sets are padded to
        ``block_rows`` (repeat-first, sliced off afterwards) so every
        pair block shares ONE compiled program shape. Scores go
        through ``pathsim.score_candidates``, which is entry-for-entry
        bit-identical to the corresponding ``score_rows`` column."""
        rows_i = np.asarray(rows_i, dtype=np.int64)
        cols_j = np.asarray(cols_j, dtype=np.int64)
        bi, bj = int(rows_i.shape[0]), int(cols_j.shape[0])
        br = self.block_rows

        def _pad(ix):
            if ix.shape[0] >= br:
                return ix
            return np.concatenate(
                [ix, np.full(br - ix.shape[0], ix[0], dtype=np.int64)]
            )

        ri, cj = _pad(rows_i), _pad(cols_j)
        bd = self._gather_dense(ri)
        self.bytes_decoded += int(np.count_nonzero(bd)) * 24
        ct = np.ascontiguousarray(self._ct[:, cj])
        if self._jax is not None:
            jnp = self._jax.numpy
            m = np.asarray(jnp.matmul(
                self._jax.device_put(bd), self._jax.device_put(ct)
            ))
            self._m_backend.inc(backend="jax")
        else:
            m = bd @ ct
            self._m_backend.inc(backend="numpy")
        self.bytes_operand += int(ct.nbytes)
        m = m[:bi, :bj]
        d_cand = np.broadcast_to(self.d[cols_j], (bi, bj))
        return pathsim.score_candidates(m, self.d[rows_i], d_cand, xp=np)


@dataclasses.dataclass
class CampaignResult:
    """What a finished campaign hands back (topk mode: the assembled
    per-row arrays; simjoin mode: the pair lists — see simjoin.py)."""

    spec: CampaignSpec
    vals: np.ndarray | None
    idxs: np.ndarray | None
    blocks_total: int
    blocks_resumed: int
    rows_per_s: float
    elapsed_s: float
    bytes_decoded: int
    bytes_operand: int
    backend_mode: str

    @property
    def bytes_read_per_row(self) -> float:
        n = self.vals.shape[0] if self.vals is not None else 1
        return (self.bytes_decoded + self.bytes_operand) / max(n, 1)


def _block_key(lo: int, hi: int) -> str:
    return f"b{lo:09d}-{hi:09d}"


class _Prefetcher:
    """Decode-ahead thread: gathers block ``i+1`` while block ``i``
    matmuls. Bounded queue (one block in flight) keeps the resident
    transient at two decoded blocks; issue order is preserved, so the
    overlap cannot reorder — or change — a single output byte."""

    def __init__(self, engine: BatchEngine, blocks: list[tuple[int, int]]):
        self._engine = engine
        self._blocks = blocks
        self._q: queue.Queue = queue.Queue(maxsize=1)
        self._t = threading.Thread(
            target=self._run, name="pathsim-batch-prefetch", daemon=True,
        )
        self._t.start()

    def _run(self) -> None:
        try:
            for lo, hi in self._blocks:
                self._q.put((lo, hi, self._engine.decode_block(lo, hi)))
            self._q.put(None)
        except BaseException as exc:  # surface decode failures in order
            self._q.put(exc)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            if isinstance(item, BaseException):
                raise item
            yield item


def run_topk_campaign(
    engine: BatchEngine,
    k: int,
    checkpoint_dir: str | None = None,
    emit_pairs: str | None = None,
    on_block=None,
    scheduler=None,
) -> CampaignResult:
    """Top-k-for-every-row: sweep all blocks, checkpointing each as it
    completes. ``scheduler`` (router/batch.BlockScheduler) fans the
    pending blocks across a worker fleet via the ``batch_blocks`` wire
    op instead of computing locally; either way each block's shard is
    saved atomically before the next preemption check, so SIGTERM →
    resume skips completed blocks bit-identically.

    ``on_block(done, total)`` fires after every completed block — the
    smoke's preemption injection point, and a progress hook."""
    spec = engine.spec("topk", k=int(k))
    ck = (
        CheckpointManager(checkpoint_dir, config=spec.manifest_config())
        if checkpoint_dir else None
    )
    blocks = block_ranges(engine.n, engine.block_rows)
    mem: dict[str, dict] = {}
    resumed = 0
    pending: list[tuple[int, int]] = []
    for lo, hi in blocks:
        if ck is not None and ck.is_done(_block_key(lo, hi)):
            resumed += 1
        else:
            pending.append((lo, hi))
    reg = get_registry()
    g_total = reg.gauge(
        "dpathsim_batch_blocks", "campaign blocks by completion state",
    )
    g_total.set(float(len(blocks)), state="total")
    g_total.set(float(resumed), state="done")
    g_rate = reg.gauge(
        "dpathsim_batch_rows_per_s",
        "campaign throughput, rows/sec over this run's computed blocks",
    )
    tracer = get_tracer()
    t0 = time.perf_counter()
    done = resumed
    k_eff = min(int(k), max(engine.n - 1, 1))

    def _save(lo: int, hi: int, vals: np.ndarray, idxs: np.ndarray):
        nonlocal done
        key = _block_key(lo, hi)
        if ck is not None:
            ck.save_unit(key, vals=vals, idxs=idxs)
        else:
            mem[key] = {"vals": vals, "idxs": idxs}
        done += 1
        g_total.set(float(done), state="done")
        elapsed = time.perf_counter() - t0
        rows_done = done * engine.block_rows
        g_rate.set(rows_done / max(elapsed, 1e-9))
        if on_block is not None:
            on_block(done, len(blocks))
        preemption_handler.check(checkpoint_dir=checkpoint_dir)

    with tracer.span(
        "batch.campaign", mode="topk", k=k_eff,
        blocks=len(blocks), resumed=resumed,
    ):
        if scheduler is not None and pending:
            for lo, hi, result in scheduler.map_blocks(spec, pending):
                with tracer.span("batch.block", lo=lo, hi=hi):
                    vals = np.asarray(result["vals"], dtype=np.float64)
                    idxs = np.asarray(result["idxs"], dtype=np.int64)
                    _save(lo, hi, vals, idxs)
        else:
            for lo, hi, decoded in _Prefetcher(engine, pending):
                with tracer.span("batch.block", lo=lo, hi=hi):
                    vals, idxs = engine.sweep_topk_block(
                        lo, hi, k_eff, decoded=decoded
                    )
                    _save(lo, hi, vals, idxs)
    elapsed = time.perf_counter() - t0
    vals = np.full((engine.n, k_eff), -np.inf)
    idxs = np.zeros((engine.n, k_eff), dtype=np.int64)
    for lo, hi in blocks:
        unit = (
            ck.load_unit(_block_key(lo, hi)) if ck is not None
            else mem[_block_key(lo, hi)]
        )
        vals[lo:hi] = unit["vals"]
        idxs[lo:hi] = unit["idxs"]
    computed_rows = sum(hi - lo for lo, hi in pending)
    result = CampaignResult(
        spec=spec, vals=vals, idxs=idxs,
        blocks_total=len(blocks), blocks_resumed=resumed,
        rows_per_s=computed_rows / max(elapsed, 1e-9),
        elapsed_s=elapsed,
        bytes_decoded=engine.bytes_decoded,
        bytes_operand=engine.bytes_operand,
        backend_mode=(
            "fleet" if scheduler is not None else engine.backend_mode
        ),
    )
    if emit_pairs:
        export_pairs(emit_pairs, vals, idxs)
    runtime_event(
        "batch_campaign_done", echo=False, mode="topk",
        blocks=len(blocks), resumed=resumed,
        rows_per_s=round(result.rows_per_s, 1),
        elapsed_s=round(elapsed, 3),
    )
    return result


def export_pairs(path: str, vals: np.ndarray, idxs: np.ndarray) -> None:
    """The ``--emit-pairs`` training export (ROADMAP item 5's learned
    index distills from exactly this stream): one JSONL record per
    finite (row, neighbor, score) hit. JSON round-trips f64 exactly
    (shortest-repr), so a consumer reading these floats gets the
    campaign's bytes back."""
    with open(path, "w", encoding="utf-8") as f:
        for row in range(vals.shape[0]):
            for v, j in zip(vals[row], idxs[row]):
                if not np.isfinite(v):
                    continue
                f.write(json.dumps(
                    {"row": int(row), "col": int(j), "score": float(v)}
                ) + "\n")
