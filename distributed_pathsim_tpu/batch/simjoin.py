"""Threshold similarity join: every pair with PathSim ≥ τ.

Naively this is the full N×N score matrix. The campaign instead
certifies most BLOCK PAIRS away with bounds that can only ever
over-estimate a pair's score, so a pruned block provably contains no
qualifying pair — and computes the survivors exactly through the same
``pathsim.score_candidates`` primitive the serving tier uses, so every
emitted score is bit-identical to the oracle's.

The bound (rowsum variant only — the campaign refuses ``diagonal``
loudly). With ``M = C Cᵀ`` and ``d_x = Σ_y M[x,y]``, every ``M[x,y]``
is a non-negative term of both row sums, hence ``M[x,y] ≤ min(d_x,
d_y)``, giving::

    sim(x,y) = 2·M[x,y] / (d_x + d_y) ≤ 2·min(d_x, d_y) / (d_x + d_y)

A pair with either degree zero has ``M[x,y] = 0`` → score 0, so for
τ > 0 it never qualifies and the block bound only needs to cover pairs
where BOTH degrees are positive. For blocks I, J with degree maxima
``hI, hJ`` and positive-degree minima ``lI, lJ``::

    max over (x∈I, y∈J) sim(x,y) ≤ 2·min(hI, hJ) / (lI + lJ)

If that upper bound is < τ — or ``min(hI, hJ) = 0`` (one block is all
isolated rows) — the block pair is pruned, score-safe by construction.
A second independent certificate uses column-support signatures: each
block's bitset OR of its rows' factor supports. Disjoint signatures ⇒
``C[x]·C[y] = 0`` for every cross pair ⇒ all scores are 0 ⇒ pruned.
Grouping rows by degree (default) or by the PR-7 balanced-k-means
centroids tightens the intervals; soundness never depends on the
grouping because every bound is computed from the block's ACTUAL
degree stats. Uncertified block pairs fall back to exact computation,
counted (``dpathsim_batch_exact_fallback_total``).

Checkpointing is per row block I: one atomic unit holds all pairs
(I, J≥I) found for that block, so resume granularity, preemption
points, and the stale-graph fence are exactly the topk campaign's
(DESIGN.md §31).
"""

from __future__ import annotations

import dataclasses
import json
import time

import numpy as np

from ..obs.metrics import get_registry
from ..obs.trace import get_tracer
from ..resilience import preemption_handler
from ..utils.checkpoint import CheckpointManager
from ..utils.logging import runtime_event
from .campaign import BatchEngine, CampaignSpec, _block_key, block_ranges


@dataclasses.dataclass
class SimJoinResult:
    """All qualifying pairs, normalized ``row < col``, in deterministic
    block order (resume-independent: the assembly re-reads units in
    block order, so a preempted+resumed campaign emits the same
    bytes)."""

    spec: CampaignSpec
    rows: np.ndarray        # int64 [P]
    cols: np.ndarray        # int64 [P]
    scores: np.ndarray      # f64  [P]
    blocks_total: int
    blocks_resumed: int
    block_pairs_total: int
    block_pairs_pruned: int
    elapsed_s: float
    rows_per_s: float
    backend_mode: str

    @property
    def prune_ratio(self) -> float:
        return self.block_pairs_pruned / max(self.block_pairs_total, 1)


def _permutation(engine: BatchEngine, grouping: str) -> np.ndarray:
    """Row order the blocks are cut from. ``degree`` packs similar
    degrees together (tight [l, h] intervals → strong bounds);
    ``centroid`` clusters rows with the PR-7 balanced k-means over the
    dense factor rows (co-clustered supports → disjoint-signature
    prunes). ``natural`` keeps corpus order — the only grouping the
    fleet path uses, since workers address blocks by global row range."""
    if grouping == "natural":
        return np.arange(engine.n, dtype=np.int64)
    if grouping == "degree":
        return np.argsort(engine.d, kind="stable").astype(np.int64)
    if grouping == "centroid":
        from ..index.mips import balanced_kmeans

        emb = np.asarray(
            np.sqrt(np.maximum(engine._ct.T, 0.0)), dtype=np.float32
        )
        k = max(-(-engine.n // engine.block_rows), 1)
        _, assign = balanced_kmeans(
            emb, k=k, cap=engine.block_rows, seed=0,
        )
        return np.argsort(assign, kind="stable").astype(np.int64)
    raise ValueError(f"unknown simjoin grouping {grouping!r}")


def _block_stats(engine: BatchEngine, groups: list[np.ndarray]):
    """One decode pass over the corpus → per-block certificates:
    (dmax, positive-degree dmin, packed column-support bitset)."""
    hmax = np.zeros(len(groups))
    lmin = np.full(len(groups), np.inf)
    sigs = []
    for bi, rows in enumerate(groups):
        bd = engine._gather_dense(rows)
        engine.bytes_decoded += int(np.count_nonzero(bd)) * 24
        db = engine.d[rows]
        hmax[bi] = db.max() if db.size else 0.0
        pos = db[db > 0]
        if pos.size:
            lmin[bi] = pos.min()
        sigs.append(np.packbits((bd != 0).any(axis=0)))
    return hmax, lmin, np.stack(sigs)


def run_simjoin_campaign(
    engine: BatchEngine,
    tau: float,
    checkpoint_dir: str | None = None,
    grouping: str = "degree",
    emit_pairs: str | None = None,
    on_block=None,
    scheduler=None,
) -> SimJoinResult:
    """All pairs with ``sim ≥ τ``, block-pruned and checkpointed.

    Requires ``variant == "rowsum"`` (the prune bound is a rowsum
    identity) and ``τ > 0`` (zero-score pairs are pruned wholesale;
    a τ of 0 would make "every pair" the answer and no bound sound).
    With ``scheduler`` the campaign fans natural-order row blocks
    across the fleet via the ``batch_blocks`` wire op — workers
    compute their blocks exactly (no pruning server-side), so fleet
    results are bit-identical to a pruned single-host run."""
    if engine.variant != "rowsum":
        raise ValueError(
            "simjoin prune bounds are a rowsum identity; "
            f"variant {engine.variant!r} is not supported — run the "
            "topk campaign or score rows directly instead"
        )
    tau = float(tau)
    if not tau > 0.0:
        raise ValueError(f"simjoin requires tau > 0, got {tau}")
    if scheduler is not None and grouping != "natural":
        raise ValueError(
            "fleet simjoin addresses blocks by global row range; "
            f"use grouping='natural' (got {grouping!r})"
        )
    spec = engine.spec("simjoin", tau=tau, grouping=grouping)
    ck = (
        CheckpointManager(checkpoint_dir, config=spec.manifest_config())
        if checkpoint_dir else None
    )
    perm = _permutation(engine, grouping)
    blocks = block_ranges(engine.n, engine.block_rows)
    groups = [perm[lo:hi] for lo, hi in blocks]
    nb = len(blocks)
    mem: dict[str, dict] = {}
    reg = get_registry()
    g_blocks = reg.gauge(
        "dpathsim_batch_blocks", "campaign blocks by completion state",
    )
    g_prune = reg.gauge(
        "dpathsim_batch_prune_ratio",
        "fraction of simjoin block pairs pruned by certificates",
    )
    c_exact = reg.counter(
        "dpathsim_batch_exact_fallback_total",
        "simjoin block pairs no certificate could prune "
        "(computed exactly)",
    )
    c_pairs = reg.counter(
        "dpathsim_batch_pairs_total", "simjoin qualifying pairs emitted",
    )
    tracer = get_tracer()
    t0 = time.perf_counter()
    resumed = sum(
        1 for lo, hi in blocks
        if ck is not None and ck.is_done(_block_key(lo, hi))
    )
    g_blocks.set(float(nb), state="total")
    g_blocks.set(float(resumed), state="done")
    done = resumed
    pruned_bp = 0
    exact_bp = 0
    stats = None

    def _save(lo: int, hi: int, ii, jj, ss, meta):
        nonlocal done
        arrays = {
            "ii": np.asarray(ii, dtype=np.int64),
            "jj": np.asarray(jj, dtype=np.int64),
            "ss": np.asarray(ss, dtype=np.float64),
            "meta": np.asarray(meta, dtype=np.int64),
        }
        key = _block_key(lo, hi)
        if ck is not None:
            ck.save_unit(key, **arrays)
        else:
            mem[key] = arrays
        done += 1
        g_blocks.set(float(done), state="done")
        c_pairs.inc(float(arrays["ii"].shape[0]))
        if on_block is not None:
            on_block(done, nb)
        preemption_handler.check(checkpoint_dir=checkpoint_dir)

    with tracer.span(
        "batch.campaign", mode="simjoin", tau=tau,
        grouping=grouping, blocks=nb, resumed=resumed,
    ):
        if scheduler is not None:
            pending = [
                (lo, hi) for lo, hi in blocks
                if not (ck is not None and ck.is_done(_block_key(lo, hi)))
            ]
            for lo, hi, result in scheduler.map_blocks(spec, pending):
                with tracer.span("batch.block", lo=lo, hi=hi):
                    _save(
                        lo, hi, result["rows"], result["cols"],
                        result["scores"], [0, 0],
                    )
        else:
            for bi, (lo, hi) in enumerate(blocks):
                key = _block_key(lo, hi)
                if ck is not None and ck.is_done(key):
                    unit = ck.load_unit(key)
                    pruned_bp += int(unit["meta"][0])
                    exact_bp += int(unit["meta"][1])
                    continue
                if stats is None:
                    stats = _block_stats(engine, groups)
                hmax, lmin, sigs = stats
                with tracer.span("batch.block", lo=lo, hi=hi):
                    ii: list[np.ndarray] = []
                    jj: list[np.ndarray] = []
                    ss: list[np.ndarray] = []
                    bp_pruned = 0
                    bp_exact = 0
                    gi = groups[bi]
                    for bj in range(bi, nb):
                        num_cap = min(hmax[bi], hmax[bj])
                        if num_cap <= 0.0:
                            bp_pruned += 1
                            continue
                        bound = 2.0 * num_cap / (lmin[bi] + lmin[bj])
                        if bound < tau:
                            bp_pruned += 1
                            continue
                        if not np.any(sigs[bi] & sigs[bj]):
                            bp_pruned += 1
                            continue
                        bp_exact += 1
                        c_exact.inc()
                        gj = groups[bj]
                        sc = engine.sweep_pair_block(gi, gj)
                        if bi == bj:
                            # the diagonal owns each unordered pair
                            # once: keep the strictly-upper triangle
                            # in GLOBAL ids (self pairs excluded too)
                            keep = sc >= tau
                            keep &= gi[:, None] < gj[None, :]
                        else:
                            keep = sc >= tau
                        xi, yj = np.nonzero(keep)
                        if xi.size:
                            a, b = gi[xi], gj[yj]
                            ii.append(np.minimum(a, b))
                            jj.append(np.maximum(a, b))
                            ss.append(sc[xi, yj])
                    pruned_bp += bp_pruned
                    exact_bp += bp_exact
                    _save(
                        lo, hi,
                        np.concatenate(ii) if ii else np.empty(0, np.int64),
                        np.concatenate(jj) if jj else np.empty(0, np.int64),
                        np.concatenate(ss) if ss else np.empty(0),
                        [bp_pruned, bp_exact],
                    )
    elapsed = time.perf_counter() - t0
    ii_all, jj_all, ss_all = [], [], []
    for lo, hi in blocks:
        key = _block_key(lo, hi)
        unit = ck.load_unit(key) if ck is not None else mem[key]
        ii_all.append(unit["ii"])
        jj_all.append(unit["jj"])
        ss_all.append(unit["ss"])
    rows = np.concatenate(ii_all) if ii_all else np.empty(0, np.int64)
    cols = np.concatenate(jj_all) if jj_all else np.empty(0, np.int64)
    scores = np.concatenate(ss_all) if ss_all else np.empty(0)
    bp_total = nb * (nb + 1) // 2
    if bp_total:
        g_prune.set(pruned_bp / bp_total)
    result = SimJoinResult(
        spec=spec, rows=rows, cols=cols, scores=scores,
        blocks_total=nb, blocks_resumed=resumed,
        block_pairs_total=bp_total, block_pairs_pruned=pruned_bp,
        elapsed_s=elapsed,
        rows_per_s=engine.n * max(nb - resumed, 0) / max(nb, 1)
        / max(elapsed, 1e-9),
        backend_mode=(
            "fleet" if scheduler is not None else engine.backend_mode
        ),
    )
    if emit_pairs:
        with open(emit_pairs, "w", encoding="utf-8") as f:
            for r, c, s in zip(rows, cols, scores):
                f.write(json.dumps(
                    {"row": int(r), "col": int(c), "score": float(s)}
                ) + "\n")
    runtime_event(
        "batch_simjoin_done", echo=False, tau=tau, grouping=grouping,
        pairs=int(rows.shape[0]), blocks=nb, resumed=resumed,
        pruned_block_pairs=pruned_bp, exact_block_pairs=exact_bp,
        prune_ratio=round(result.prune_ratio, 4),
    )
    return result
