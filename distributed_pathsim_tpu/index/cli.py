"""``dpathsim index`` — build / inspect MIPS candidate indexes.

::

    dpathsim index build --dataset dblp/dblp_small.gexf \
        --metapath APVPA --out idx.npz
    dpathsim index probe --index idx.npz --dataset dblp/dblp_small.gexf \
        --row 17 --k 10

``build`` folds the half-chain factor, embeds every node (analytic
Cauchy map by default; ``--embedding learned --model ckpt.npz`` uses a
trained NeuralPathSim tower), runs k-means, packs the clusters, and
writes the ``.npz`` artifact stamped with the graph's base fingerprint
— ``dpathsim serve --topk-mode ann --index idx.npz`` refuses any
artifact whose fingerprint doesn't match the served graph.

``probe`` is the inspection tool: candidates for one row (and, with a
dataset, their exact-reranked scores via the same candidate primitives
serving uses), plus index geometry.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _parse_dataset(spec: str):
    """GEXF path or the router CLI's ``synthetic:`` scheme → EncodedHIN."""
    if spec.startswith("synthetic:"):
        from ..data.synthetic import synthetic_hin
        from ..router.cli import _parse_synthetic

        return synthetic_hin(**_parse_synthetic(spec))
    from ..engine import load_dataset

    return load_dataset(spec)


def build_index_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dpathsim index",
        description="build / probe MIPS candidate-generation indexes",
    )
    sub = p.add_subparsers(dest="action", required=True)

    b = sub.add_parser("build", help="graph -> index artifact")
    b.add_argument("--dataset", required=True,
                   help="GEXF path or synthetic:authors=..,papers=..,"
                   "venues=..,seed=..")
    b.add_argument("--metapath", default="APVPA")
    b.add_argument("--variant", default="rowsum",
                   choices=("rowsum", "diagonal"))
    b.add_argument("--out", required=True, help="index .npz path")
    b.add_argument("--embedding", default="struct",
                   choices=("struct", "learned"))
    b.add_argument("--model", default=None,
                   help="NeuralPathSim checkpoint (--embedding learned)")
    b.add_argument("--centroids", type=int, default=None,
                   help="centroid count (default: tuned sqrt(N) mult)")
    b.add_argument("--cluster-cap", type=int, default=None,
                   help="packed-cluster capacity (default: tuned/auto)")
    b.add_argument("--max-dim", type=int, default=1024,
                   help="struct map width cap (JL projection past it)")
    b.add_argument("--headroom", type=float, default=0.25,
                   help="index-capacity reserve, MATCHING the serving "
                   "process's --headroom: the artifact is stamped with "
                   "the padded graph's fingerprint, and serve/worker "
                   "refuse an index built for a different shape")
    b.add_argument("--seed", type=int, default=0)
    b.add_argument("--tuning-table", default=None)

    q = sub.add_parser("probe", help="query an index artifact")
    q.add_argument("--index", required=True, help="index .npz path")
    q.add_argument("--row", type=int, required=True)
    q.add_argument("--k", type=int, default=10)
    q.add_argument("--nprobe", type=int, default=None)
    q.add_argument("--cand-mult", type=int, default=16)
    q.add_argument("--dataset", default=None,
                   help="with it: exact-rerank the candidates and print "
                   "exact scores (the serving answer)")
    q.add_argument("--metapath", default="APVPA")
    q.add_argument("--variant", default="rowsum",
                   choices=("rowsum", "diagonal"))
    q.add_argument("--headroom", type=float, default=0.25,
                   help="must match the value the index was built with")
    return p


def _build(args) -> int:
    from .. import tuning
    from ..ops.metapath import compile_metapath
    from ..serving.cache import graph_fingerprint
    from .build import build_index, half_chain_and_denominators

    if args.tuning_table:
        tuning.install_table(args.tuning_table)
    hin = _parse_dataset(args.dataset)
    if args.headroom:
        from ..data.delta import with_headroom

        hin = with_headroom(hin, args.headroom)
    metapath = compile_metapath(args.metapath, hin.schema)
    t0 = time.perf_counter()
    c, d = half_chain_and_denominators(hin, metapath, args.variant)
    index = build_index(
        c=c, d=d, variant=args.variant, metapath=metapath,
        embedding=args.embedding, model_path=args.model,
        n_centroids=args.centroids, cluster_cap=args.cluster_cap,
        token=(graph_fingerprint(hin), 0),
        seed=args.seed, max_dim=args.max_dim,
    )
    index.save(args.out)
    print(json.dumps({
        "out": args.out,
        "n": index.n,
        "dim": index.dim,
        "centroids": index.n_centroids,
        "cluster_cap": index.cluster_cap,
        "embedding": args.embedding,
        "base_fp": index.token[0],
        "build_s": round(time.perf_counter() - t0, 3),
    }, indent=2))
    return 0


def _probe(args) -> int:
    from .. import tuning
    from .mips import CentroidIndex

    index = CentroidIndex.load(args.index)
    row = int(args.row)
    if not 0 <= row < index.n:
        raise ValueError(f"row {row} out of range [0, {index.n})")
    # the SAME heuristic serving resolves (serving/service._setup_ann):
    # an inspection tool probing a fraction of serving's clusters would
    # report missing candidates serving actually returns
    nprobe = args.nprobe or int(
        tuning.choose(
            "ann_nprobe", n=index.n,
            default=min(max(16, index.n_centroids // 3), 96),
        )
    )
    n_cand = max(args.k, args.cand_mult * args.k)
    sims, mem = index.probe_batch(np.asarray([row]), nprobe)
    cand = index.select_candidates(sims[0], mem[0], n_cand)
    out = {
        "row": row,
        "nprobe": nprobe,
        "stale": bool(index.stale[row]),
        "index": {
            "n": index.n, "dim": index.dim,
            "centroids": index.n_centroids,
            "cluster_cap": index.cluster_cap,
            "epoch": list(index.token),
            "embedding": index.meta.get("embedding"),
        },
        "candidates": [int(x) for x in cand[: max(args.k * 2, 20)]],
        "n_candidates": int(cand.shape[0]),
    }
    if args.dataset:
        from ..ops import pathsim
        from ..ops.metapath import compile_metapath
        from .build import half_chain_and_denominators

        hin = _parse_dataset(args.dataset)
        if args.headroom:
            from ..data.delta import with_headroom

            hin = with_headroom(hin, args.headroom)
        metapath = compile_metapath(args.metapath, hin.schema)
        c, d = half_chain_and_denominators(hin, metapath, args.variant)
        # candidates beyond this dataset's capacity mean a headroom
        # mismatch with the build — drop them rather than crash
        cand = cand[cand < c.shape[0]]
        counts = c[cand] @ c[row]
        scores = pathsim.score_candidates(
            counts[None, :], np.asarray([d[row]]), d[cand][None, :]
        )
        vals, idxs = pathsim.topk_from_candidate_scores(
            scores, cand[None, :], args.k
        )
        out["topk"] = [
            {"row": int(j), "score": float(v)}
            for v, j in zip(vals[0], idxs[0])
            if np.isfinite(v)
        ]
    print(json.dumps(out, indent=2))
    return 0


def index_main(argv: list[str] | None = None) -> int:
    args = build_index_parser().parse_args(argv)
    if args.action == "build":
        return _build(args)
    if args.action == "probe":
        return _probe(args)
    # unreachable: the subparser is required — but fail loudly, not
    # silently, if an action is ever added without a handler
    raise ValueError(f"unknown index action {args.action!r}")
