"""Centroid-quantized MIPS index with padded, jit-stable cluster blocks.

Layout: k-means assigns every node to one of K centroids, but instead
of the classic IVF ragged posting lists (whose traversal is a
gather-per-list scan — hostile to a matmul machine), every cluster's
member embeddings are packed into one ``[K, cap, D]`` tensor padded to
a common ``cluster_cap``. A probe for a batch of B query rows is then:

1. ``[B, D] @ [D, K]``       — centroid similarities, pick top nprobe;
2. gather the nprobe blocks  — ``[B, nprobe·cap, D]``, one fancy index;
3. ``einsum('bd,bcd->bc')``  — ONE batched matmul over the packed rows.

Every shape in the jitted probe is static — (bucket, nprobe, cap) —
so steady-state serving compiles a bounded set of programs (the serve
bucket ladder), the same contract the exact path honors. Candidate
*selection* (top-C of the probed similarities) runs on host, which
keeps the device program independent of k.

Capacity-bounded packing: clusters larger than ``cluster_cap`` spill
their farthest members to the next-nearest centroid with space (the
padding/jit-stability trade the ``ann_cluster_cap`` tuning knob
measures). Pad slots carry member id −1 and a zero vector; the probe
masks them to −inf before selection, so they can never surface.

Staleness: a delta update marks its affected rows stale
(:meth:`mark_stale`); stale rows are the serving layer's exact-fallback
set until :meth:`refresh_rows` re-embeds them in place (same slot when
the centroid assignment still holds, moved when a better centroid has
space). The index carries the ``(base_fp, delta_seq)`` consistency
token it was built/refreshed at, so router replicas can agree on index
epochs the same way they agree on graph epochs.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Sequence

import numpy as np

_SCHEMA_VERSION = 1


class IndexMismatch(ValueError):
    """A persisted index does not match the graph/config it is asked to
    serve (base fingerprint, variant, metapath, or schema version)."""


def _cap_round(x: int) -> int:
    """Cluster caps round up to a lane-friendly multiple of 8 — NOT to
    a power of two: the jit only needs the cap fixed, and pow-2
    rounding near-doubles pad slots at typical √N cluster sizes (every
    pad slot is wasted probe/rerank traffic)."""
    return max(8, -(-int(x) // 8) * 8)


def balanced_kmeans(
    emb: np.ndarray, k: int, cap: int, iters: int = 10, seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Capacity-constrained Lloyd iterations: every round runs a
    capacity-bounded assignment (closest nodes win the seats; overflow
    spills down each node's centroid-preference list) and recomputes
    centroids from the members a cluster ACTUALLY holds. Plain k-means
    + post-hoc capping failed measurably here: skewed-norm embedding
    corpora collapse into one mega-cluster whose capped overflow lands
    far from any centroid that describes it, and probe routing (top
    nprobe by query·centroid) then misses true top-k targets outright
    (recall@10 0.88 → 0.96 at nprobe=8, → 1.00 at 16, on the
    2048-author gate graph). Returns (centroids [K, D], assign [N])."""
    emb = np.asarray(emb, dtype=np.float32)
    n = emb.shape[0]
    k = min(k, n)
    rng = np.random.default_rng(seed)
    centroids = emb[rng.choice(n, size=k, replace=False)].copy()
    assign = np.zeros(n, dtype=np.int64)
    for _ in range(max(iters, 1)):
        assign = _balanced_assign(emb, centroids, cap)
        for kk in range(k):
            m = assign == kk
            if m.any():
                centroids[kk] = emb[m].mean(axis=0)
    return centroids, assign


def _balanced_assign(
    emb: np.ndarray, centroids: np.ndarray, cap: int, width: int = 8
) -> np.ndarray:
    """One capacity-bounded assignment pass: nodes claim seats in
    order of distance to their preferred centroid (closest first), each
    taking its best centroid with space; preference-list exhaustion
    falls back to any open cluster. K·cap ≥ N is the caller's
    feasibility contract."""
    n, k = emb.shape[0], centroids.shape[0]
    prefs = _pref_lists(emb, centroids, width=min(width, k))
    c2 = (centroids * centroids).sum(axis=1)
    d0 = c2[prefs[:, 0]] - 2.0 * np.einsum(
        "nd,nd->n", emb, centroids[prefs[:, 0]]
    )
    assign = np.full(n, -1, dtype=np.int64)
    fill = np.zeros(k, dtype=np.int64)
    for node in np.argsort(d0, kind="stable"):
        for r in range(prefs.shape[1]):
            c = prefs[node, r]
            if fill[c] < cap:
                assign[node] = c
                fill[c] += 1
                break
    unplaced = np.flatnonzero(assign < 0)
    if unplaced.size:
        open_c = np.flatnonzero(fill < cap)
        oi = 0
        for node in unplaced:
            while fill[open_c[oi]] >= cap:
                oi += 1
            assign[node] = open_c[oi]
            fill[open_c[oi]] += 1
    return assign


def _nearest(block: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    """argmin_k ||x - c_k||² per row, via the matmul form (the ||x||²
    term is constant per row and drops out of the argmin)."""
    d2 = (centroids * centroids).sum(axis=1)[None, :] - 2.0 * (
        block @ centroids.T
    )
    return np.argmin(d2, axis=1)


def _pref_lists(
    emb: np.ndarray, centroids: np.ndarray, width: int, chunk: int = 16384
) -> np.ndarray:
    """Each node's ``width`` nearest centroids, nearest first."""
    n, k = emb.shape[0], centroids.shape[0]
    width = min(width, k)
    prefs = np.empty((n, width), dtype=np.int64)
    c2 = (centroids * centroids).sum(axis=1)[None, :]
    for lo in range(0, n, chunk):
        block = emb[lo:lo + chunk]
        d2 = c2 - 2.0 * (block @ centroids.T)
        part = np.argpartition(d2, width - 1, axis=1)[:, :width]
        order = np.take_along_axis(d2, part, axis=1).argsort(axis=1)
        prefs[lo:lo + chunk] = np.take_along_axis(part, order, axis=1)
    return prefs


@dataclasses.dataclass
class CentroidIndex:
    """The packed index. All arrays are host-resident numpy; the probe
    lazily mirrors them to the JAX device and invalidates the mirror on
    refresh (a refresh is rare; a probe is the hot path)."""

    centroids: np.ndarray      # f32 [K, D]
    members: np.ndarray        # int32 [K, cap]; −1 = pad
    packed: np.ndarray         # f32 [K, cap, D]; zeros at pads
    cluster_of: np.ndarray     # int32 [N]
    slot_of: np.ndarray        # int32 [N]
    token: tuple[str, int]     # (base_fp, delta_seq) at build/refresh
    meta: dict                 # embedding source, variant, metapath, …
    stale: np.ndarray = None   # bool [N]

    def __post_init__(self):
        if self.stale is None:
            self.stale = np.zeros(self.cluster_of.shape[0], dtype=bool)
        self._dev = None        # (centroids, members, packed) on device
        self._probe_jit = {}    # (b, nprobe) → compiled probe

    # -- introspection -----------------------------------------------------

    @property
    def n(self) -> int:
        return int(self.cluster_of.shape[0])

    @property
    def n_centroids(self) -> int:
        return int(self.centroids.shape[0])

    @property
    def cluster_cap(self) -> int:
        return int(self.members.shape[1])

    @property
    def dim(self) -> int:
        return int(self.centroids.shape[1])

    @property
    def stale_count(self) -> int:
        return int(self.stale.sum())

    def covers(self, row: int) -> bool:
        """Is ``row`` indexed and fresh? The serving eligibility check:
        anything else answers through the exact path."""
        return 0 <= row < self.n and not bool(self.stale[row])

    # -- build -------------------------------------------------------------

    @classmethod
    def build(
        cls,
        emb: np.ndarray,
        n_centroids: int,
        cluster_cap: int | None = None,
        token: tuple[str, int] = ("", 0),
        meta: dict | None = None,
        seed: int = 0,
        iters: int = 8,
    ) -> "CentroidIndex":
        """Balanced k-means + packing. ``cluster_cap`` of None picks a
        lane-rounded (multiple-of-8) cap with 1.25× slack over a
        perfectly balanced split; an explicit cap too small to hold N
        nodes in K·cap slots is raised to the feasibility floor
        (recorded in ``meta['cap_raised_from']`` so the tuner sees
        what really ran). The capacity constraint lives INSIDE the
        k-means loop
        (:func:`balanced_kmeans`) so the centroids the probe routes on
        describe the capped clusters that actually exist."""
        emb = np.asarray(emb, dtype=np.float32)
        n = emb.shape[0]
        if n == 0:
            raise ValueError("cannot index an empty corpus")
        k = max(1, min(int(n_centroids), n))
        meta = dict(meta or {})
        floor = _cap_round(-(-n // k))
        if cluster_cap is None:
            # 1.25× slack over a perfectly balanced split: spill room
            # without paying pad traffic for slots that never fill
            cap = _cap_round(max(1, (5 * -(-n // k)) // 4))
        else:
            cap = _cap_round(cluster_cap)
            if cap < floor:
                meta["cap_raised_from"] = int(cluster_cap)
                cap = floor
        centroids, assign = balanced_kmeans(
            emb, k, cap, iters=iters, seed=seed
        )
        k = centroids.shape[0]
        cluster_of = assign.astype(np.int32)
        slot_of = np.zeros(n, dtype=np.int32)
        fill = np.zeros(k, dtype=np.int64)
        for node in range(n):
            c = assign[node]
            slot_of[node] = fill[c]
            fill[c] += 1
        members = np.full((k, cap), -1, dtype=np.int32)
        packed = np.zeros((k, cap, emb.shape[1]), dtype=np.float32)
        members[cluster_of, slot_of] = np.arange(n, dtype=np.int32)
        packed[cluster_of, slot_of] = emb
        return cls(
            centroids=centroids, members=members, packed=packed,
            cluster_of=cluster_of, slot_of=slot_of,
            token=tuple(token), meta=meta,
        )

    # -- probe -------------------------------------------------------------

    def embedding_of(self, rows: np.ndarray) -> np.ndarray:
        """Indexed rows' embeddings, read back out of the packed blocks
        (the only copy kept — queries probe with their own stored
        vector, which is what makes the index self-contained)."""
        rows = np.asarray(rows, dtype=np.int64)
        return self.packed[self.cluster_of[rows], self.slot_of[rows]]

    def _device_arrays(self):
        import jax.numpy as jnp

        if self._dev is None:
            self._dev = (
                jnp.asarray(self.centroids),
                jnp.asarray(self.members),
                jnp.asarray(self.packed),
                jnp.asarray(self.cluster_of),
                jnp.asarray(self.slot_of),
            )
        return self._dev

    def _route_fn(self, b: int, nprobe: int):
        """The route-only probe (``rerank-all`` variant): centroid
        matmul + top-nprobe + member-id gather — no embedding-block
        gather at all. The caller reranks EVERY returned member
        exactly against its packed per-cluster count blocks, so probe
        traffic is a [B, K] matmul plus int32 ids."""
        key = ("route", int(b), int(nprobe))
        fn = self._probe_jit.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp

            cap = self.cluster_cap

            @jax.jit
            def route(centroids, members, packed, cluster_of, slot_of,
                      rows):
                q = packed[cluster_of[rows], slot_of[rows]]
                csims = q @ centroids.T
                _, top_c = jax.lax.top_k(csims, nprobe)
                mem = members[top_c].reshape(
                    rows.shape[0], nprobe * cap
                )
                mem = jnp.where(mem == rows[:, None], -1, mem)
                return mem, top_c

            fn = self._probe_jit[key] = route
        return fn

    def route_batch_device(self, rows: np.ndarray, nprobe: int):
        """Issue a route-only probe; returns un-fetched device handles
        ``(member ids int32 [B, nprobe·cap], clusters int32 [B,
        nprobe])`` with self/pads already −1."""
        rows = np.asarray(rows, dtype=np.int64)
        nprobe = max(1, min(int(nprobe), self.n_centroids))
        import jax.numpy as jnp

        dev = self._device_arrays()
        return self._route_fn(rows.shape[0], nprobe)(
            *dev, jnp.asarray(rows, jnp.int32)
        )

    def route_batch(
        self, rows: np.ndarray, nprobe: int
    ) -> tuple[np.ndarray, np.ndarray]:
        mem, top_c = self.route_batch_device(rows, nprobe)
        return np.asarray(mem), np.asarray(top_c)

    def route_batch_host(
        self, rows: np.ndarray, nprobe: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Pure-numpy routing (same candidates as the device route; the
        probed-cluster ORDER may differ — it is a set, the rerank is
        order-free). The route's work is tiny ([B, K] matvec + id
        gather), so on a CPU host the XLA call overhead dominates the
        jitted version at small batches — serving uses this path when
        JAX itself is on CPU, and the compiled route on accelerators."""
        rows = np.asarray(rows, dtype=np.int64)
        nprobe = max(1, min(int(nprobe), self.n_centroids))
        q = self.packed[self.cluster_of[rows], self.slot_of[rows]]
        csims = q @ self.centroids.T
        if nprobe < self.n_centroids:
            top_c = np.argpartition(
                -csims, nprobe - 1, axis=1
            )[:, :nprobe]
        else:
            top_c = np.broadcast_to(
                np.arange(self.n_centroids), csims.shape
            )[:, :nprobe].copy()
        mem = self.members[top_c].reshape(rows.shape[0], -1)
        mem = np.where(mem == rows[:, None], -1, mem)
        return mem, top_c.astype(np.int32)

    def _probe_fn(self, b: int, nprobe: int):
        """One compiled probe per (batch bucket, nprobe): static
        shapes throughout, so the serving ladder bounds the program
        count exactly as the exact path's buckets do."""
        key = ("probe", int(b), int(nprobe))
        fn = self._probe_jit.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp

            cap = self.cluster_cap

            @jax.jit
            def probe(centroids, members, packed, cluster_of, slot_of,
                      rows):
                q = packed[cluster_of[rows], slot_of[rows]]   # [B, D]
                csims = q @ centroids.T                        # [B, K]
                _, top_c = jax.lax.top_k(csims, nprobe)        # [B, P]
                mem = members[top_c].reshape(rows.shape[0], nprobe * cap)
                emb = packed[top_c].reshape(
                    rows.shape[0], nprobe * cap, packed.shape[-1]
                )
                sims = jnp.einsum("bd,bcd->bc", q, emb)
                # pads and the query row itself can never be candidates
                mask = (mem < 0) | (mem == rows[:, None])
                sims = jnp.where(mask, -jnp.inf, sims)
                return sims, mem

            fn = self._probe_jit[key] = probe
        return fn

    def warm(self, buckets: Sequence[int], nprobe: int,
             variant: str = "shortlist") -> None:
        """Pre-compile the probe for every serving bucket (the ANN
        analog of utils.xla_flags.warm_compile_cache)."""
        for b in buckets:
            rows = np.zeros(int(b), dtype=np.int64)
            if variant == "rerank-all":
                self.route_batch(rows, nprobe)
            else:
                self.probe_batch(rows, nprobe)

    def probe_batch_device(self, rows: np.ndarray, nprobe: int):
        """Issue a probe and return the un-fetched device handles
        ``(sims, mem)`` — JAX's async dispatch lets the serving double
        buffer overlap the next probe with this one's host fan-out."""
        rows = np.asarray(rows, dtype=np.int64)
        nprobe = max(1, min(int(nprobe), self.n_centroids))
        import jax.numpy as jnp

        dev = self._device_arrays()
        return self._probe_fn(rows.shape[0], nprobe)(
            *dev, jnp.asarray(rows, jnp.int32)
        )

    def probe_batch(
        self, rows: np.ndarray, nprobe: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Probe for a padded batch of query rows. Returns host
        ``(sims f32 [B, nprobe·cap], member ids int32 [B, nprobe·cap])``
        with pads/self at −inf; the caller selects its top-C on host so
        the device program never depends on k."""
        sims, mem = self.probe_batch_device(rows, nprobe)
        return np.asarray(sims), np.asarray(mem)

    @staticmethod
    def select_candidates(
        sims_row: np.ndarray, mem_row: np.ndarray, n_cand: int
    ) -> np.ndarray:
        """Host top-C over one probed row: int64 candidate ids (masked
        slots dropped; may return fewer than ``n_cand``)."""
        n_cand = min(int(n_cand), sims_row.shape[0])
        part = np.argpartition(-sims_row, n_cand - 1)[:n_cand]
        keep = np.isfinite(sims_row[part])
        return mem_row[part[keep]].astype(np.int64)

    # -- staleness & refresh ----------------------------------------------

    def mark_stale(self, rows: Sequence[int] | np.ndarray) -> int:
        """Mark rows whose graph state changed: they fall back to the
        exact path until refreshed. Rows beyond the indexed range
        (appended nodes) are implicitly stale — ``covers`` is False for
        them already. Returns how many indexed rows were marked."""
        rows = np.asarray(rows, dtype=np.int64)
        rows = rows[(rows >= 0) & (rows < self.n)]
        self.stale[rows] = True
        return int(rows.shape[0])

    def refresh_rows(
        self, rows: np.ndarray, emb: np.ndarray,
        token: tuple[str, int] | None = None,
    ) -> list[int]:
        """Re-embed ``rows`` in place with their fresh vectors, clear
        their staleness, and (optionally) advance the consistency
        token. A row whose nearest centroid changed moves when the
        target block has space; when it doesn't, the vector is updated
        in its current slot (assignment slightly off-centroid — recall
        is guarded by the serving layer's shadow sampling, and the next
        full rebuild re-balances). Returns the rows that could NOT be
        refreshed (not indexed, e.g. appended past the build): those
        stay on the exact path until a rebuild."""
        rows = np.asarray(rows, dtype=np.int64)
        emb = np.asarray(emb, dtype=np.float32)
        from ..obs.trace import get_tracer

        span = get_tracer().child_span(
            "index.refresh_rows", n=int(rows.shape[0])
        )
        with span:
            return self._refresh_rows(rows, emb, token)

    def _refresh_rows(
        self, rows: np.ndarray, emb: np.ndarray,
        token: tuple[str, int] | None,
    ) -> list[int]:
        unplaced: list[int] = []
        for i, row in enumerate(rows):
            row = int(row)
            if not 0 <= row < self.n:
                unplaced.append(row)
                continue
            vec = emb[i]
            best = int(_nearest(vec[None, :], self.centroids)[0])
            cur = int(self.cluster_of[row])
            if best != cur:
                free = np.flatnonzero(self.members[best] < 0)
                if free.size:
                    old_slot = int(self.slot_of[row])
                    self.members[cur, old_slot] = -1
                    self.packed[cur, old_slot] = 0.0
                    slot = int(free[0])
                    self.members[best, slot] = row
                    self.cluster_of[row] = best
                    self.slot_of[row] = slot
                    cur = best
            self.packed[cur, int(self.slot_of[row])] = vec
            self.stale[row] = False
        if token is not None:
            self.token = tuple(token)
        self._dev = None  # host arrays changed: re-mirror on next probe
        return unplaced

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        """One ``.npz``, written atomically (tmp + rename) like every
        other artifact in this repo."""
        payload = {
            "centroids": self.centroids,
            "members": self.members,
            "packed": self.packed,
            "cluster_of": self.cluster_of,
            "slot_of": self.slot_of,
            "stale": self.stale,
            "meta": np.frombuffer(
                json.dumps({
                    **self.meta,
                    "schema_version": _SCHEMA_VERSION,
                    "base_fp": self.token[0],
                    "delta_seq": int(self.token[1]),
                }).encode(),
                dtype=np.uint8,
            ),
        }
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **payload)
        os.replace(tmp, path)

    @classmethod
    def load(
        cls, path: str, expect_base_fp: str | None = None
    ) -> "CentroidIndex":
        """Restore; ``expect_base_fp`` (the serving graph's base
        fingerprint) rejects an index built for a different graph with
        a NAMED error instead of silently wrong candidates."""
        with np.load(path) as z:
            meta = json.loads(z["meta"].tobytes().decode())
            if meta.get("schema_version") != _SCHEMA_VERSION:
                raise IndexMismatch(
                    f"{path!r}: index schema "
                    f"{meta.get('schema_version')!r} != "
                    f"{_SCHEMA_VERSION} — rebuild with `dpathsim index "
                    "build`"
                )
            base_fp = meta.pop("base_fp", "")
            delta_seq = int(meta.pop("delta_seq", 0))
            if expect_base_fp is not None and base_fp != expect_base_fp:
                raise IndexMismatch(
                    f"{path!r} was built for graph {base_fp!r}, not "
                    f"{expect_base_fp!r} — rebuild against the served "
                    "dataset"
                )
            meta.pop("schema_version", None)
            return cls(
                centroids=z["centroids"],
                members=z["members"],
                packed=z["packed"],
                cluster_of=z["cluster_of"],
                slot_of=z["slot_of"],
                stale=z["stale"].astype(bool),
                token=(base_fp, delta_seq),
                meta=meta,
            )
