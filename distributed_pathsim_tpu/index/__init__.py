"""Sublinear top-k candidate generation: a TPU-friendly MIPS index.

Exact PathSim serving scores a full O(N) row per query; at millions of
authors a production service can't. This package puts a *candidate
generation* tier in front of the exact engine (ROADMAP item 2, grounded
in the Neural-PathSim inductive-index idea and Atrapos's workload
framing): a k-means centroid-quantized inner-product index over the
neural/analytic node embeddings, with the per-cluster embeddings packed
into padded jit-stable blocks so a probe is ONE batched matmul — no
gather-heavy IVF traversal — and the exact f64 scorer reranks the
candidates, so the user-visible answer stays exact whenever the true
top-k is inside the candidate set (tie order included).

- :mod:`mips` — :class:`CentroidIndex`: build (k-means + capacity-
  bounded packing), probe (batched, static shapes), per-row staleness
  + in-place refresh, atomic save/load.
- :mod:`build` — embedding maps (analytic Cauchy-quadrature map by
  default; learned two-tower checkpoints as the compact alternative)
  and the graph → index build pipeline.
- :mod:`cli` — ``dpathsim index build`` / ``dpathsim index probe``.

The serving integration (``--topk-mode ann``, exact fallback, shadow-
recall confidence, delta staleness) lives in serving/service.py;
DESIGN.md §23 has the full contract.
"""

from .build import build_index, struct_embeddings  # noqa: F401
from .mips import CentroidIndex, IndexMismatch  # noqa: F401

__all__ = [
    "CentroidIndex",
    "IndexMismatch",
    "build_index",
    "struct_embeddings",
]
