"""Graph → MIPS index: embedding maps and the build pipeline.

Two embedding sources, both inner-product-faithful to the exact score:

- ``struct`` (default, no training): the analytic Cauchy-quadrature
  map φ(j) = vec_k(√(2·w_k)·e^(−d_j·t_k)·C_j) from models/neural.py —
  φ(i)·φ(j) ≈ 2·(C_i·C_j)/(d_i+d_j) to the quadrature's uniform ~3–7%
  relative error, which is ranking-grade. Its raw width is m·V; past
  ``max_dim`` a seeded Gaussian (JL) projection compresses it — inner
  products are preserved in expectation and the serving layer's
  shadow-recall gate measures what actually survived.
- ``learned``: a trained :class:`~..models.neural.NeuralPathSim`
  checkpoint's two-tower embeddings (O(d) with d≪m·V) for corpora
  where the analytic map is too wide even projected.

Centroid count and cluster cap resolve through the tuning registry
(``ann_centroids``, ``ann_cluster_cap``) with the documented heuristics
as defaults, so a measured table reshapes the index exactly like it
reshapes kernel tiles.
"""

from __future__ import annotations

import numpy as np

from ..models.neural import (
    NeuralPathSim,
    cauchy_quadrature,
    quadrature_gates,
)
from ..utils.logging import runtime_event
from .mips import CentroidIndex

# quadrature width of the struct map — the trainer's own constant, so
# a widened grid there widens index builds with it
_QUAD_M = NeuralPathSim.QUAD_M


def half_chain_and_denominators(
    hin, metapath, variant: str = "rowsum"
) -> tuple[np.ndarray, np.ndarray]:
    """Dense half-chain factor C [N, V] (f64, exact integer counts) and
    the denominator vector of ``variant`` — the two host arrays both
    the index build and the exact candidate rerank read."""
    from ..ops import planner

    c = planner.dense_half(hin, metapath).astype(np.float64)
    if variant == "rowsum":
        d = c @ c.sum(axis=0)
    elif variant == "diagonal":
        d = np.einsum("nv,nv->n", c, c)
    else:
        raise ValueError(f"unknown PathSim variant {variant!r}")
    return c, d


def struct_embeddings(
    c: np.ndarray,
    d: np.ndarray,
    quad: tuple[np.ndarray, np.ndarray] | None = None,
    quad_m: int = _QUAD_M,
    max_dim: int = 1024,
    seed: int = 0,
    chunk: int = 8192,
) -> np.ndarray:
    """The analytic Cauchy map φ [N, min(m·V, max_dim)] (f32). Chunked
    over rows so the unprojected [chunk, m·V] block is the largest
    intermediate even when a projection is active.

    ``quad`` (nodes t, weights w) pins the quadrature grid: φ vectors
    are only mutually inner-product-consistent when embedded on ONE
    grid, so a row refresh against an existing index must pass the
    grid (and projection seed) the index was built with — the build
    persists both in ``meta``."""
    c32 = np.asarray(c, dtype=np.float32)
    n, v = c32.shape
    t, w = quad if quad is not None else cauchy_quadrature(d, m=quad_m)
    t = np.asarray(t, dtype=np.float64)
    w = np.asarray(w, dtype=np.float64)
    quad_m = int(t.shape[0])
    gates = quadrature_gates(d, t)
    scale = np.sqrt(2.0 * w).astype(np.float32)
    full_dim = quad_m * v
    proj = None
    if full_dim > max_dim:
        rng = np.random.default_rng(seed)
        proj = (
            rng.standard_normal((full_dim, max_dim)) / np.sqrt(max_dim)
        ).astype(np.float32)
    out = np.empty((n, max_dim if proj is not None else full_dim),
                   dtype=np.float32)
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        phi = (
            scale[None, :, None]
            * gates[lo:hi, :, None]
            * c32[lo:hi, None, :]
        ).reshape(hi - lo, full_dim)
        out[lo:hi] = phi if proj is None else phi @ proj
    return out


def learned_embeddings(model_path: str, n_expect: int) -> np.ndarray:
    """Corpus embeddings from a trained NeuralPathSim checkpoint,
    validated against the served corpus size."""
    from ..models.neural import NeuralPathSim

    model = NeuralPathSim.load(model_path)
    if model.n != n_expect:
        raise ValueError(
            f"checkpoint {model_path!r} embeds {model.n} nodes; the "
            f"served graph has {n_expect} — retrain/rebuild against "
            "the served dataset"
        )
    return np.asarray(model.embeddings(), dtype=np.float32)


def default_centroids(n: int, mult: float = 1.0) -> int:
    """The √N heuristic floor the ``ann_centroids`` knob scales."""
    return max(1, int(round(mult * np.sqrt(max(n, 1)))))


def build_index(
    hin=None,
    metapath=None,
    variant: str = "rowsum",
    c: np.ndarray | None = None,
    d: np.ndarray | None = None,
    embedding: str = "struct",
    model_path: str | None = None,
    n_centroids: int | None = None,
    cluster_cap: int | None = None,
    token: tuple[str, int] = ("", 0),
    seed: int = 0,
    max_dim: int = 1024,
) -> CentroidIndex:
    """The one build entry point (CLI, serving startup, tests). Pass
    either a graph (``hin`` + ``metapath``) or precomputed ``c``/``d``
    (the serving layer already holds both)."""
    from .. import tuning

    if c is None or d is None:
        if hin is None or metapath is None:
            raise ValueError("build_index needs hin+metapath or c+d")
        c, d = half_chain_and_denominators(hin, metapath, variant)
    n = c.shape[0]
    quad = None
    if embedding == "struct":
        quad = cauchy_quadrature(d, m=_QUAD_M)
        emb = struct_embeddings(c, d, quad=quad, max_dim=max_dim, seed=seed)
    elif embedding == "learned":
        if model_path is None:
            raise ValueError("embedding='learned' needs model_path")
        emb = learned_embeddings(model_path, n)
    else:
        raise ValueError(
            f"unknown embedding source {embedding!r}; "
            "choose 'struct' or 'learned'"
        )
    if n_centroids is None:
        # 2·√N default (measured): finer clusters → smaller caps →
        # less probe/rerank pad traffic at equal routing recall
        mult = tuning.choose("ann_centroids", n=n, default=2.0)
        n_centroids = default_centroids(n, float(mult))
    if cluster_cap is None:
        cluster_cap = tuning.choose("ann_cluster_cap", n=n, default=None)
    index = CentroidIndex.build(
        emb,
        n_centroids=n_centroids,
        cluster_cap=int(cluster_cap) if cluster_cap else None,
        token=token,
        seed=seed,
        meta={
            "embedding": embedding,
            "variant": variant,
            "metapath": getattr(metapath, "name", None),
            "dim": int(emb.shape[1]),
            "model_path": model_path,
            # the refresh contract: re-embeds must reuse this grid and
            # projection, or inner products across rows go inconsistent
            "quad_t": list(quad[0]) if quad is not None else None,
            "quad_w": list(quad[1]) if quad is not None else None,
            "max_dim": int(max_dim),
            "seed": int(seed),
        },
    )
    if "cap_raised_from" in index.meta:
        runtime_event(
            "index_cap_raised", echo=False,
            requested=index.meta["cap_raised_from"],
            actual=index.cluster_cap,
        )
    runtime_event(
        "index_built", echo=False, n=index.n,
        centroids=index.n_centroids, cap=index.cluster_cap,
        dim=index.dim, embedding=embedding,
    )
    return index


def refresh_embeddings(
    index: CentroidIndex,
    rows: np.ndarray,
    c: np.ndarray,
    d: np.ndarray,
) -> np.ndarray:
    """Fresh embeddings for ``rows`` from the PATCHED graph state,
    consistent with the build's map (the persisted quadrature grid and
    projection seed — NOT a recomputed grid, which would break
    inner-product consistency with un-refreshed rows). Only meaningful
    for the struct map — a learned index refreshes by re-running the
    tower offline, which the serving layer surfaces as 'rebuild
    required' instead."""
    if index.meta.get("embedding") != "struct":
        raise ValueError(
            "in-place refresh is only supported for struct-embedded "
            "indexes; rebuild the learned index offline"
        )
    quad = (
        np.asarray(index.meta["quad_t"]), np.asarray(index.meta["quad_w"])
    )
    rows = np.asarray(rows, dtype=np.int64)
    from ..obs.trace import get_tracer

    # φ is row-local given the pinned grid, so only the affected rows'
    # C/d slices are embedded — the refresh stays O(Δ), not O(N). The
    # span parents into the refresh trace (the background ann.refresh
    # root, or a protocol refresh_index's serve.op), so the fleet
    # export shows where refresh time goes per delta.
    with get_tracer().child_span(
        "index.refresh_embed", rows=int(rows.shape[0])
    ):
        return struct_embeddings(
            np.asarray(c)[rows], np.asarray(d)[rows], quad=quad,
            max_dim=int(index.meta.get("max_dim", 1024)),
            seed=int(index.meta.get("seed", 0)),
        )
