"""Lazy native build: compile the C++ shared libraries on first use.

No pybind11 in this image, so bindings go through a plain C ABI + ctypes.
The build is a single g++ invocation per library, cached next to the
source; failures degrade gracefully to the pure-Python implementations.
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import threading

_DIR = pathlib.Path(__file__).resolve().parent
_LOCK = threading.Lock()
_BUILT: dict[str, pathlib.Path | None] = {}


def shared_lib(name: str) -> pathlib.Path | None:
    """Return the path to lib<name>.so, building it if needed.
    None if the toolchain is missing or compilation fails."""
    with _LOCK:
        if name in _BUILT:
            return _BUILT[name]
        src = _DIR / f"{name}.cpp"
        out = _DIR / f"lib{name}.so"
        result: pathlib.Path | None = None
        if src.exists():
            if out.exists() and out.stat().st_mtime >= src.stat().st_mtime:
                result = out
            else:
                # Compile to a process-unique temp path then atomically
                # rename: a concurrent process never CDLLs a half-written
                # .so (the in-process lock can't protect across processes).
                tmp = out.with_suffix(f".tmp{os.getpid()}")
                try:
                    subprocess.run(
                        [
                            "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                            str(src), "-o", str(tmp),
                        ],
                        check=True,
                        capture_output=True,
                        timeout=120,
                    )
                    os.replace(tmp, out)
                    result = out
                except (subprocess.SubprocessError, FileNotFoundError, OSError):
                    tmp.unlink(missing_ok=True)
                    result = None
        _BUILT[name] = result
        return result
