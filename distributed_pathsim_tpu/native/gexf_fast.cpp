// Fast streaming GEXF parser (native data-loader for the framework).
//
// The reference's loader is networkx.read_gexf through Python XML DOM
// (reference DPathSim_APVPA.py:114-129) — fine for 2k nodes, minutes for
// millions. This is a single-pass, zero-dependency tokenizer over the
// GEXF subset the DBLP datasets use (nodes/edges with attvalues), with
// the exact semantics of the Python fallback in ../data/gexf.py:
//   - node_type   := node attvalue whose declared title is "node_type"
//   - relationship:= edge attvalue whose declared title is "label"
//                    (falling back to the edge's label= XML attribute)
//   - label       := node label= attribute, falling back to id
//   - duplicate (src,dst) edges keep first position, last relationship
//     (networkx DiGraph attribute-overwrite behavior)
//   - document order preserved (it drives the reference's log order)
//
// C ABI: results are returned as two NUL-separated string blobs
// (id\0label\0type\0 per node; src\0dst\0rel\0 per edge) consumed by
// ctypes in gexf_native.py.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Attr {
  std::string name;
  std::string value;
};

// ---------------------------------------------------------------------------
// Strictness (r04 differential fuzz): the native parser is the DEFAULT
// loader, and a 400-case mutation fuzz against the Python (expat) path
// found 86 inputs expat rejects that this tokenizer silently loaded —
// truncations, bad entities, byte corruption. A corrupted file must
// fail loudly, not load partially; these checks close every divergence
// class the fuzz surfaced (tests/test_native.py::test_differential_fuzz).
// ---------------------------------------------------------------------------

// Whole-document scan: reject invalid UTF-8 (incl. overlongs and
// surrogates) and control characters outside {\t, \n, \r} — expat
// refuses both wherever they appear (text, attributes, comments).
bool validate_document(const std::string& data, std::string* err) {
  const auto* s = reinterpret_cast<const unsigned char*>(data.data());
  size_t n = data.size();
  for (size_t i = 0; i < n;) {
    unsigned char c = s[i];
    if (c < 0x80) {
      if (c < 0x20 && c != '\t' && c != '\n' && c != '\r') {
        *err = "invalid control character";
        return false;
      }
      ++i;
      continue;
    }
    int len;
    if (c >= 0xC2 && c <= 0xDF) len = 2;
    else if (c >= 0xE0 && c <= 0xEF) len = 3;
    else if (c >= 0xF0 && c <= 0xF4) len = 4;
    else {  // continuation byte as lead, overlong lead, or > U+10FFFF
      *err = "invalid UTF-8";
      return false;
    }
    if (i + len > n) {
      *err = "truncated UTF-8 sequence";
      return false;
    }
    for (int k = 1; k < len; ++k) {
      if ((s[i + k] & 0xC0) != 0x80) {
        *err = "invalid UTF-8";
        return false;
      }
    }
    if ((c == 0xE0 && s[i + 1] < 0xA0) ||   // overlong 3-byte
        (c == 0xED && s[i + 1] >= 0xA0) ||  // UTF-16 surrogate
        (c == 0xF0 && s[i + 1] < 0x90) ||   // overlong 4-byte
        (c == 0xF4 && s[i + 1] >= 0x90)) {  // > U+10FFFF
      *err = "invalid UTF-8";
      return false;
    }
    // U+FFFE / U+FFFF (EF BF BE / EF BF BF) are not XML Chars; expat
    // rejects the literal bytes just like the numeric references.
    if (c == 0xEF && s[i + 1] == 0xBF &&
        (s[i + 2] == 0xBE || s[i + 2] == 0xBF)) {
      *err = "XML-invalid character U+FFFE/U+FFFF";
      return false;
    }
    i += len;
  }
  return true;
}

// Decode the five XML built-in entities plus numeric references —
// STRICT: unknown entities, bare '&', and numeric references to
// XML-invalid code points are errors (expat parity), never passed
// through. Entities are parsed inline (no arbitrary length cap —
// numeric references may carry leading zeros). ``out`` may be null to
// validate without building a string; when non-null (attribute
// values), literal whitespace normalizes to spaces the way expat's
// attribute-value normalization does (\r\n → one space; character
// REFERENCES like &#10; stay literal, per the XML spec).
bool decode_entities_strict(const char* s, size_t n, std::string* out,
                            std::string* err) {
  for (size_t i = 0; i < n;) {
    char c = s[i];
    if (c != '&') {
      if (c == '\r' && i + 1 < n && s[i + 1] == '\n') ++i;  // CRLF → LF
      if (out) {
        *out += (c == '\r' || c == '\n' || c == '\t') ? ' ' : c;
      }
      ++i;
      continue;
    }
    size_t j = i + 1;
    if (j < n && s[j] == '#') {
      ++j;
      bool hex = false;
      if (j < n && (s[j] == 'x' || s[j] == 'X')) {
        hex = true;
        ++j;
      }
      size_t d0 = j;
      long cp = 0;
      for (; j < n; ++j) {
        char ch = s[j];
        int digit;
        if (ch >= '0' && ch <= '9') digit = ch - '0';
        else if (hex && ch >= 'a' && ch <= 'f') digit = ch - 'a' + 10;
        else if (hex && ch >= 'A' && ch <= 'F') digit = ch - 'A' + 10;
        else break;
        if (cp <= 0x10FFFF) cp = cp * (hex ? 16 : 10) + digit;
        // saturates: once past the Unicode range further digits can't
        // bring it back, and the range check below rejects it
      }
      if (j == d0 || j >= n || s[j] != ';') {
        *err = "malformed numeric character reference";
        return false;
      }
      // XML 1.0 Char production: no control chars (except \t\n\r), no
      // surrogates, no U+FFFE/U+FFFF, nothing past U+10FFFF.
      if (cp > 0x10FFFF ||
          (cp < 0x20 && cp != 0x9 && cp != 0xA && cp != 0xD) ||
          (cp >= 0xD800 && cp <= 0xDFFF) || cp == 0xFFFE || cp == 0xFFFF) {
        *err = "numeric reference to XML-invalid character";
        return false;
      }
      if (out) {
        if (cp < 0x80) *out += static_cast<char>(cp);
        else if (cp < 0x800) {
          *out += static_cast<char>(0xC0 | (cp >> 6));
          *out += static_cast<char>(0x80 | (cp & 0x3F));
        } else if (cp < 0x10000) {
          *out += static_cast<char>(0xE0 | (cp >> 12));
          *out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
          *out += static_cast<char>(0x80 | (cp & 0x3F));
        } else {
          *out += static_cast<char>(0xF0 | (cp >> 18));
          *out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
          *out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
          *out += static_cast<char>(0x80 | (cp & 0x3F));
        }
      }
      i = j + 1;
      continue;
    }
    size_t e0 = j;
    while (j < n &&
           ((s[j] >= 'a' && s[j] <= 'z') || (s[j] >= 'A' && s[j] <= 'Z') ||
            (s[j] >= '0' && s[j] <= '9'))) {
      ++j;
    }
    if (j == e0 || j >= n || s[j] != ';') {
      *err = "bare '&' (unterminated entity reference)";
      return false;
    }
    std::string ent(s + e0, j - e0);
    if (ent == "amp") { if (out) *out += '&'; }
    else if (ent == "lt") { if (out) *out += '<'; }
    else if (ent == "gt") { if (out) *out += '>'; }
    else if (ent == "quot") { if (out) *out += '"'; }
    else if (ent == "apos") { if (out) *out += '\''; }
    else {
      *err = "unknown entity '&" + ent + ";'";
      return false;
    }
    i = j + 1;
  }
  return true;
}

// 202 ranges, derived empirically from this build's
// expat (scripts/derive tool in r04 commit message)
constexpr unsigned kNameStartRanges[][2] = {
    {0xc0, 0xd6}, {0xd8, 0xf6}, {0xf8, 0x131}, {0x134, 0x13e},
    {0x141, 0x148}, {0x14a, 0x17e}, {0x180, 0x1c3}, {0x1cd, 0x1f0},
    {0x1f4, 0x1f5}, {0x1fa, 0x217}, {0x250, 0x2a8}, {0x2bb, 0x2c1},
    {0x386, 0x386}, {0x388, 0x38a}, {0x38c, 0x38c}, {0x38e, 0x3a1},
    {0x3a3, 0x3ce}, {0x3d0, 0x3d6}, {0x3da, 0x3da}, {0x3dc, 0x3dc},
    {0x3de, 0x3de}, {0x3e0, 0x3e0}, {0x3e2, 0x3f3}, {0x401, 0x40c},
    {0x40e, 0x44f}, {0x451, 0x45c}, {0x45e, 0x481}, {0x490, 0x4c4},
    {0x4c7, 0x4c8}, {0x4cb, 0x4cc}, {0x4d0, 0x4eb}, {0x4ee, 0x4f5},
    {0x4f8, 0x4f9}, {0x531, 0x556}, {0x559, 0x559}, {0x561, 0x586},
    {0x5d0, 0x5ea}, {0x5f0, 0x5f2}, {0x621, 0x63a}, {0x641, 0x64a},
    {0x671, 0x6b7}, {0x6ba, 0x6be}, {0x6c0, 0x6ce}, {0x6d0, 0x6d3},
    {0x6d5, 0x6d5}, {0x6e5, 0x6e6}, {0x905, 0x939}, {0x93d, 0x93d},
    {0x958, 0x961}, {0x985, 0x98c}, {0x98f, 0x990}, {0x993, 0x9a8},
    {0x9aa, 0x9b0}, {0x9b2, 0x9b2}, {0x9b6, 0x9b9}, {0x9dc, 0x9dd},
    {0x9df, 0x9e1}, {0x9f0, 0x9f1}, {0xa05, 0xa0a}, {0xa0f, 0xa10},
    {0xa13, 0xa28}, {0xa2a, 0xa30}, {0xa32, 0xa33}, {0xa35, 0xa36},
    {0xa38, 0xa39}, {0xa59, 0xa5c}, {0xa5e, 0xa5e}, {0xa72, 0xa74},
    {0xa85, 0xa8b}, {0xa8d, 0xa8d}, {0xa8f, 0xa91}, {0xa93, 0xaa8},
    {0xaaa, 0xab0}, {0xab2, 0xab3}, {0xab5, 0xab9}, {0xabd, 0xabd},
    {0xae0, 0xae0}, {0xb05, 0xb0c}, {0xb0f, 0xb10}, {0xb13, 0xb28},
    {0xb2a, 0xb30}, {0xb32, 0xb33}, {0xb36, 0xb39}, {0xb3d, 0xb3d},
    {0xb5c, 0xb5d}, {0xb5f, 0xb61}, {0xb85, 0xb8a}, {0xb8e, 0xb90},
    {0xb92, 0xb95}, {0xb99, 0xb9a}, {0xb9c, 0xb9c}, {0xb9e, 0xb9f},
    {0xba3, 0xba4}, {0xba8, 0xbaa}, {0xbae, 0xbb5}, {0xbb7, 0xbb9},
    {0xc05, 0xc0c}, {0xc0e, 0xc10}, {0xc12, 0xc28}, {0xc2a, 0xc33},
    {0xc35, 0xc39}, {0xc60, 0xc61}, {0xc85, 0xc8c}, {0xc8e, 0xc90},
    {0xc92, 0xca8}, {0xcaa, 0xcb3}, {0xcb5, 0xcb9}, {0xcde, 0xcde},
    {0xce0, 0xce1}, {0xd05, 0xd0c}, {0xd0e, 0xd10}, {0xd12, 0xd28},
    {0xd2a, 0xd39}, {0xd60, 0xd61}, {0xe01, 0xe2e}, {0xe30, 0xe30},
    {0xe32, 0xe33}, {0xe40, 0xe45}, {0xe81, 0xe82}, {0xe84, 0xe84},
    {0xe87, 0xe88}, {0xe8a, 0xe8a}, {0xe8d, 0xe8d}, {0xe94, 0xe97},
    {0xe99, 0xe9f}, {0xea1, 0xea3}, {0xea5, 0xea5}, {0xea7, 0xea7},
    {0xeaa, 0xeab}, {0xead, 0xeae}, {0xeb0, 0xeb0}, {0xeb2, 0xeb3},
    {0xebd, 0xebd}, {0xec0, 0xec4}, {0xf40, 0xf47}, {0xf49, 0xf69},
    {0x10a0, 0x10c5}, {0x10d0, 0x10f6}, {0x1100, 0x1100}, {0x1102, 0x1103},
    {0x1105, 0x1107}, {0x1109, 0x1109}, {0x110b, 0x110c}, {0x110e, 0x1112},
    {0x113c, 0x113c}, {0x113e, 0x113e}, {0x1140, 0x1140}, {0x114c, 0x114c},
    {0x114e, 0x114e}, {0x1150, 0x1150}, {0x1154, 0x1155}, {0x1159, 0x1159},
    {0x115f, 0x1161}, {0x1163, 0x1163}, {0x1165, 0x1165}, {0x1167, 0x1167},
    {0x1169, 0x1169}, {0x116d, 0x116e}, {0x1172, 0x1173}, {0x1175, 0x1175},
    {0x119e, 0x119e}, {0x11a8, 0x11a8}, {0x11ab, 0x11ab}, {0x11ae, 0x11af},
    {0x11b7, 0x11b8}, {0x11ba, 0x11ba}, {0x11bc, 0x11c2}, {0x11eb, 0x11eb},
    {0x11f0, 0x11f0}, {0x11f9, 0x11f9}, {0x1e00, 0x1e9b}, {0x1ea0, 0x1ef9},
    {0x1f00, 0x1f15}, {0x1f18, 0x1f1d}, {0x1f20, 0x1f45}, {0x1f48, 0x1f4d},
    {0x1f50, 0x1f57}, {0x1f59, 0x1f59}, {0x1f5b, 0x1f5b}, {0x1f5d, 0x1f5d},
    {0x1f5f, 0x1f7d}, {0x1f80, 0x1fb4}, {0x1fb6, 0x1fbc}, {0x1fbe, 0x1fbe},
    {0x1fc2, 0x1fc4}, {0x1fc6, 0x1fcc}, {0x1fd0, 0x1fd3}, {0x1fd6, 0x1fdb},
    {0x1fe0, 0x1fec}, {0x1ff2, 0x1ff4}, {0x1ff6, 0x1ffc}, {0x2126, 0x2126},
    {0x212a, 0x212b}, {0x212e, 0x212e}, {0x2180, 0x2182}, {0x3007, 0x3007},
    {0x3021, 0x3029}, {0x3041, 0x3094}, {0x30a1, 0x30fa}, {0x3105, 0x312c},
    {0x4e00, 0x9fa5}, {0xac00, 0xd7a3},
};
// 282 ranges, derived empirically from this build's
// expat (scripts/derive tool in r04 commit message)
constexpr unsigned kNameCharRanges[][2] = {
    {0xb7, 0xb7}, {0xc0, 0xd6}, {0xd8, 0xf6}, {0xf8, 0x131},
    {0x134, 0x13e}, {0x141, 0x148}, {0x14a, 0x17e}, {0x180, 0x1c3},
    {0x1cd, 0x1f0}, {0x1f4, 0x1f5}, {0x1fa, 0x217}, {0x250, 0x2a8},
    {0x2bb, 0x2c1}, {0x2d0, 0x2d1}, {0x300, 0x345}, {0x360, 0x361},
    {0x386, 0x38a}, {0x38c, 0x38c}, {0x38e, 0x3a1}, {0x3a3, 0x3ce},
    {0x3d0, 0x3d6}, {0x3da, 0x3da}, {0x3dc, 0x3dc}, {0x3de, 0x3de},
    {0x3e0, 0x3e0}, {0x3e2, 0x3f3}, {0x401, 0x40c}, {0x40e, 0x44f},
    {0x451, 0x45c}, {0x45e, 0x481}, {0x483, 0x486}, {0x490, 0x4c4},
    {0x4c7, 0x4c8}, {0x4cb, 0x4cc}, {0x4d0, 0x4eb}, {0x4ee, 0x4f5},
    {0x4f8, 0x4f9}, {0x531, 0x556}, {0x559, 0x559}, {0x561, 0x586},
    {0x591, 0x5a1}, {0x5a3, 0x5b9}, {0x5bb, 0x5bd}, {0x5bf, 0x5bf},
    {0x5c1, 0x5c2}, {0x5c4, 0x5c4}, {0x5d0, 0x5ea}, {0x5f0, 0x5f2},
    {0x621, 0x63a}, {0x640, 0x652}, {0x660, 0x669}, {0x670, 0x6b7},
    {0x6ba, 0x6be}, {0x6c0, 0x6ce}, {0x6d0, 0x6d3}, {0x6d5, 0x6e8},
    {0x6ea, 0x6ed}, {0x6f0, 0x6f9}, {0x901, 0x903}, {0x905, 0x939},
    {0x93c, 0x94d}, {0x951, 0x954}, {0x958, 0x963}, {0x966, 0x96f},
    {0x981, 0x983}, {0x985, 0x98c}, {0x98f, 0x990}, {0x993, 0x9a8},
    {0x9aa, 0x9b0}, {0x9b2, 0x9b2}, {0x9b6, 0x9b9}, {0x9bc, 0x9bc},
    {0x9be, 0x9c4}, {0x9c7, 0x9c8}, {0x9cb, 0x9cd}, {0x9d7, 0x9d7},
    {0x9dc, 0x9dd}, {0x9df, 0x9e3}, {0x9e6, 0x9f1}, {0xa02, 0xa02},
    {0xa05, 0xa0a}, {0xa0f, 0xa10}, {0xa13, 0xa28}, {0xa2a, 0xa30},
    {0xa32, 0xa33}, {0xa35, 0xa36}, {0xa38, 0xa39}, {0xa3c, 0xa3c},
    {0xa3e, 0xa42}, {0xa47, 0xa48}, {0xa4b, 0xa4d}, {0xa59, 0xa5c},
    {0xa5e, 0xa5e}, {0xa66, 0xa74}, {0xa81, 0xa83}, {0xa85, 0xa8b},
    {0xa8d, 0xa8d}, {0xa8f, 0xa91}, {0xa93, 0xaa8}, {0xaaa, 0xab0},
    {0xab2, 0xab3}, {0xab5, 0xab9}, {0xabc, 0xac5}, {0xac7, 0xac9},
    {0xacb, 0xacd}, {0xae0, 0xae0}, {0xae6, 0xaef}, {0xb01, 0xb03},
    {0xb05, 0xb0c}, {0xb0f, 0xb10}, {0xb13, 0xb28}, {0xb2a, 0xb30},
    {0xb32, 0xb33}, {0xb36, 0xb39}, {0xb3c, 0xb43}, {0xb47, 0xb48},
    {0xb4b, 0xb4d}, {0xb56, 0xb57}, {0xb5c, 0xb5d}, {0xb5f, 0xb61},
    {0xb66, 0xb6f}, {0xb82, 0xb83}, {0xb85, 0xb8a}, {0xb8e, 0xb90},
    {0xb92, 0xb95}, {0xb99, 0xb9a}, {0xb9c, 0xb9c}, {0xb9e, 0xb9f},
    {0xba3, 0xba4}, {0xba8, 0xbaa}, {0xbae, 0xbb5}, {0xbb7, 0xbb9},
    {0xbbe, 0xbc2}, {0xbc6, 0xbc8}, {0xbca, 0xbcd}, {0xbd7, 0xbd7},
    {0xbe7, 0xbef}, {0xc01, 0xc03}, {0xc05, 0xc0c}, {0xc0e, 0xc10},
    {0xc12, 0xc28}, {0xc2a, 0xc33}, {0xc35, 0xc39}, {0xc3e, 0xc44},
    {0xc46, 0xc48}, {0xc4a, 0xc4d}, {0xc55, 0xc56}, {0xc60, 0xc61},
    {0xc66, 0xc6f}, {0xc82, 0xc83}, {0xc85, 0xc8c}, {0xc8e, 0xc90},
    {0xc92, 0xca8}, {0xcaa, 0xcb3}, {0xcb5, 0xcb9}, {0xcbe, 0xcc4},
    {0xcc6, 0xcc8}, {0xcca, 0xccd}, {0xcd5, 0xcd6}, {0xcde, 0xcde},
    {0xce0, 0xce1}, {0xce6, 0xcef}, {0xd02, 0xd03}, {0xd05, 0xd0c},
    {0xd0e, 0xd10}, {0xd12, 0xd28}, {0xd2a, 0xd39}, {0xd3e, 0xd43},
    {0xd46, 0xd48}, {0xd4a, 0xd4d}, {0xd57, 0xd57}, {0xd60, 0xd61},
    {0xd66, 0xd6f}, {0xe01, 0xe2e}, {0xe30, 0xe3a}, {0xe40, 0xe4e},
    {0xe50, 0xe59}, {0xe81, 0xe82}, {0xe84, 0xe84}, {0xe87, 0xe88},
    {0xe8a, 0xe8a}, {0xe8d, 0xe8d}, {0xe94, 0xe97}, {0xe99, 0xe9f},
    {0xea1, 0xea3}, {0xea5, 0xea5}, {0xea7, 0xea7}, {0xeaa, 0xeab},
    {0xead, 0xeae}, {0xeb0, 0xeb9}, {0xebb, 0xebd}, {0xec0, 0xec4},
    {0xec6, 0xec6}, {0xec8, 0xecd}, {0xed0, 0xed9}, {0xf18, 0xf19},
    {0xf20, 0xf29}, {0xf35, 0xf35}, {0xf37, 0xf37}, {0xf39, 0xf39},
    {0xf3e, 0xf47}, {0xf49, 0xf69}, {0xf71, 0xf84}, {0xf86, 0xf8b},
    {0xf90, 0xf95}, {0xf97, 0xf97}, {0xf99, 0xfad}, {0xfb1, 0xfb7},
    {0xfb9, 0xfb9}, {0x10a0, 0x10c5}, {0x10d0, 0x10f6}, {0x1100, 0x1100},
    {0x1102, 0x1103}, {0x1105, 0x1107}, {0x1109, 0x1109}, {0x110b, 0x110c},
    {0x110e, 0x1112}, {0x113c, 0x113c}, {0x113e, 0x113e}, {0x1140, 0x1140},
    {0x114c, 0x114c}, {0x114e, 0x114e}, {0x1150, 0x1150}, {0x1154, 0x1155},
    {0x1159, 0x1159}, {0x115f, 0x1161}, {0x1163, 0x1163}, {0x1165, 0x1165},
    {0x1167, 0x1167}, {0x1169, 0x1169}, {0x116d, 0x116e}, {0x1172, 0x1173},
    {0x1175, 0x1175}, {0x119e, 0x119e}, {0x11a8, 0x11a8}, {0x11ab, 0x11ab},
    {0x11ae, 0x11af}, {0x11b7, 0x11b8}, {0x11ba, 0x11ba}, {0x11bc, 0x11c2},
    {0x11eb, 0x11eb}, {0x11f0, 0x11f0}, {0x11f9, 0x11f9}, {0x1e00, 0x1e9b},
    {0x1ea0, 0x1ef9}, {0x1f00, 0x1f15}, {0x1f18, 0x1f1d}, {0x1f20, 0x1f45},
    {0x1f48, 0x1f4d}, {0x1f50, 0x1f57}, {0x1f59, 0x1f59}, {0x1f5b, 0x1f5b},
    {0x1f5d, 0x1f5d}, {0x1f5f, 0x1f7d}, {0x1f80, 0x1fb4}, {0x1fb6, 0x1fbc},
    {0x1fbe, 0x1fbe}, {0x1fc2, 0x1fc4}, {0x1fc6, 0x1fcc}, {0x1fd0, 0x1fd3},
    {0x1fd6, 0x1fdb}, {0x1fe0, 0x1fec}, {0x1ff2, 0x1ff4}, {0x1ff6, 0x1ffc},
    {0x20d0, 0x20dc}, {0x20e1, 0x20e1}, {0x2126, 0x2126}, {0x212a, 0x212b},
    {0x212e, 0x212e}, {0x2180, 0x2182}, {0x3005, 0x3005}, {0x3007, 0x3007},
    {0x3021, 0x302f}, {0x3031, 0x3035}, {0x3041, 0x3094}, {0x3099, 0x309a},
    {0x309d, 0x309e}, {0x30a1, 0x30fa}, {0x30fc, 0x30fe}, {0x3105, 0x312c},
    {0x4e00, 0x9fa5}, {0xac00, 0xd7a3},
};

// A minimal tag token: name + attributes + open/close/selfclose kind.
struct Tag {
  std::string name;          // namespace-stripped (semantic dispatch)
  std::string raw_name;      // as written (nesting must match exactly)
  std::vector<Attr> attrs;
  std::vector<std::string> raw_attr_names;  // for prefix validation
  std::vector<std::string> declared;        // xmlns:PREFIX on this tag
  std::vector<std::string> declared_uris;   // URIs of those bindings
  bool closing = false;      // </name>
  bool self_closing = false; // <name ... />
};

const char* attr_of(const Tag& t, const char* name) {
  for (const auto& a : t.attrs)
    if (a.name == name) return a.value.c_str();
  return nullptr;
}

std::string local_name(const std::string& qname) {
  size_t c = qname.rfind(':');
  return c == std::string::npos ? qname : qname.substr(c + 1);
}

struct Parser {
  const char* p;
  const char* end;
  const char* doc_start;
  std::string error;
  struct OpenElem {
    std::string raw_name;
    std::vector<std::string> declared;  // xmlns:PREFIX bindings
  };
  std::vector<OpenElem> open_stack;  // open elements, innermost last
  // prefix → stack of bound URIs (innermost last)
  std::unordered_map<std::string, std::vector<std::string>> ns_active;
  bool seen_root = false;
  bool seen_doctype = false;

  explicit Parser(const char* data, size_t len)
      : p(data), end(data + len), doc_start(data) {}

  // True when the document ended well-formed: no error, exactly one
  // root element, and every element closed. Truncated files (the
  // fuzz's biggest silent-acceptance class) fail here.
  bool eof_ok() const {
    return error.empty() && seen_root && open_stack.empty();
  }

  // Advance to the next tag; returns false at EOF or error (check
  // ``error``). Skips comments, CDATA, processing instructions, and
  // doctype declarations; validates the text spans in between
  // (strict entities; nothing but whitespace outside the root).
  bool next_tag(Tag* tag) {
    while (p < end) {
      const char* lt = static_cast<const char*>(memchr(p, '<', end - p));
      if (!check_text(p, lt ? lt : end)) return false;
      if (!lt) { p = end; return false; }
      p = lt + 1;
      if (p >= end) return fail("truncated document");
      if (*p == '?') {  // processing instruction / XML declaration
        const char* pi_lt = p - 1;
        const char* close = strstr_bounded("?>");
        if (!close) return fail("unterminated PI");
        if (!check_pi(p + 1, close, pi_lt == doc_start)) return false;
        p = close + 2;
        continue;
      }
      if (*p == '!') {
        if (end - p >= 3 && p[1] == '-' && p[2] == '-') {  // comment
          const char* close = strstr_bounded("-->");
          if (!close) return fail("unterminated comment");
          p = close + 3;
          continue;
        }
        if (end - p >= 8 && strncmp(p, "![CDATA[", 8) == 0) {
          // CDATA is character data: only legal inside the root.
          if (open_stack.empty()) {
            return fail(seen_root ? "junk after document element"
                                  : "CDATA before document element");
          }
          const char* close = strstr_bounded("]]>");
          if (!close) return fail("unterminated CDATA");
          p = close + 3;
          continue;
        }
        if (end - p >= 8 && strncmp(p, "!DOCTYPE", 8) == 0 &&
            (end - p == 8 || is_space(p[8]))) {
          // one DOCTYPE, in the prolog only (internal subsets with
          // nested '>' are out of scope for GEXF)
          if (seen_root || seen_doctype) return fail("misplaced DOCTYPE");
          seen_doctype = true;
          const char* close =
              static_cast<const char*>(memchr(p, '>', end - p));
          if (!close) return fail("unterminated declaration");
          p = close + 1;
          continue;
        }
        // Anything else after '<!' is corruption — skipping it would
        // silently drop a damaged element (e.g. a byte flip turning
        // '<node .../>' into '<!ode .../>').
        return fail("malformed markup declaration");
      }
      if (!parse_tag(tag)) return false;
      // Well-formedness: closing tags must match the innermost open
      // element; a second root (or any tag after the root closed) is
      // junk after the document element.
      if (tag->closing) {
        if (open_stack.empty() ||
            open_stack.back().raw_name != tag->raw_name) {
          return fail("mismatched closing tag");
        }
        for (const auto& pre : open_stack.back().declared) {
          auto it = ns_active.find(pre);
          it->second.pop_back();
          if (it->second.empty()) ns_active.erase(it);
        }
        open_stack.pop_back();
      } else {
        if (open_stack.empty() && seen_root) {
          return fail("junk after document element");
        }
        seen_root = true;
        if (!tag->self_closing) {
          for (size_t i = 0; i < tag->declared.size(); ++i) {
            ns_active[tag->declared[i]].push_back(tag->declared_uris[i]);
          }
          open_stack.push_back({tag->raw_name, tag->declared});
        }
      }
      return true;
    }
    return false;
  }

 private:
  const char* strstr_bounded(const char* needle) {
    size_t n = strlen(needle);
    for (const char* q = p; q + n <= end; ++q)
      if (memcmp(q, needle, n) == 0) return q;
    return nullptr;
  }

  bool fail(const char* msg) {
    error = msg;
    p = end;
    return false;
  }

  bool fail_str(std::string msg) {
    error = std::move(msg);
    p = end;
    return false;
  }

  // Processing instruction [s, e): target name must be a valid Name,
  // and the reserved target "xml" (any case) is only legal as THE XML
  // DECLARATION — first bytes of the document, with the strict
  // version/encoding/standalone pseudo-attribute grammar expat
  // enforces. Catches duplicated or displaced declarations and
  // corruption inside the declaration itself.
  bool check_pi(const char* s, const char* e, bool at_doc_start) {
    const char* q = s;
    const char* name_start = q;
    while (q < e && is_name_char(*q)) ++q;
    if (!valid_name(name_start, q) ||
        memchr(name_start, ':', q - name_start)) {
      return fail("malformed PI target");
    }
    std::string target(name_start, q - name_start);
    bool is_xml_decl =
        target.size() == 3 && (target[0] == 'x' || target[0] == 'X') &&
        (target[1] == 'm' || target[1] == 'M') &&
        (target[2] == 'l' || target[2] == 'L');
    if (!is_xml_decl) return true;  // ordinary PI: contents are free-form
    if (!at_doc_start || target != "xml") {
      return fail("XML declaration not at start of document");
    }
    // version="1.x" [encoding="..."] [standalone="yes|no"]
    const char* names[3] = {"version", "encoding", "standalone"};
    int next_allowed = 0;
    while (true) {
      const char* before = q;
      while (q < e && is_space(*q)) ++q;
      if (q == e) break;
      if (before == q) return fail("malformed XML declaration");
      const char* a0 = q;
      while (q < e && is_name_char(*q)) ++q;
      std::string an(a0, q - a0);
      int which = -1;
      for (int i = next_allowed; i < 3; ++i) {
        if (an == names[i]) { which = i; break; }
      }
      if (which < 0 || (which > 0 && next_allowed == 0)) {
        return fail("malformed XML declaration");  // wrong name/order
      }
      next_allowed = which + 1;
      while (q < e && is_space(*q)) ++q;
      if (q == e || *q != '=') return fail("malformed XML declaration");
      ++q;
      while (q < e && is_space(*q)) ++q;
      if (q == e || (*q != '"' && *q != '\'')) {
        return fail("malformed XML declaration");
      }
      char quote = *q++;
      const char* v0 = q;
      while (q < e && *q != quote) ++q;
      if (q == e) return fail("malformed XML declaration");
      std::string val(v0, q - v0);
      ++q;
      if (which == 0) {
        if (val.size() < 3 || val.compare(0, 2, "1.") != 0) {
          return fail("malformed XML declaration");
        }
        for (size_t i = 2; i < val.size(); ++i) {
          if (val[i] < '0' || val[i] > '9') {
            return fail("malformed XML declaration");
          }
        }
      } else if (which == 1) {
        if (val.empty()) return fail("malformed XML declaration");
        for (char c : val) {
          if (!((c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z') ||
                (c >= '0' && c <= '9') || c == '.' || c == '_' ||
                c == '-')) {
            return fail("malformed XML declaration");
          }
        }
      } else if (val != "yes" && val != "no") {
        return fail("malformed XML declaration");
      }
    }
    if (next_allowed == 0) return fail("malformed XML declaration");
    return true;
  }

  // Text between tags: outside the root only whitespace is allowed;
  // inside, entity references must be valid (content itself is
  // discarded — GEXF carries data in attributes).
  bool check_text(const char* s, const char* e) {
    if (open_stack.empty()) {
      for (const char* q = s; q < e; ++q) {
        if (!is_space(*q)) {
          return fail(seen_root ? "junk after document element"
                                : "text before document element");
        }
      }
      return true;
    }
    std::string err;
    if (!decode_entities_strict(s, e - s, nullptr, &err)) {
      return fail_str(err + " in text");
    }
    return true;
  }

  static bool is_space(char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r';
  }
  // Byte-level span scan for names: ASCII NameChars plus any ≥0x80
  // byte (multi-byte sequences are validated as CODE POINTS by
  // valid_name below — a 10k-mutant soak found expat rejecting
  // non-NameChar Unicode (U+00D7, or the 5th-edition-only U+0132)
  // inside names that a byte-level check waved through).
  static bool is_name_char(char ch) {
    unsigned char c = static_cast<unsigned char>(ch);
    return (c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z') ||
           (c >= '0' && c <= '9') || c == '-' || c == '.' || c == '_' ||
           c == ':' || c >= 0x80;
  }
  // Name character classes — NOT the XML 1.0 5th-edition ranges: the
  // Python fallback parses through expat, which enforces the FOURTH
  // edition (Unicode-2.0-frozen) Appendix-B tables, and parity with
  // the fallback is the contract (a 10k-mutant soak caught 5th-ed
  // ranges accepting names like "sou\u05F0rce" that expat rejects).
  // The tables below are derived EMPIRICALLY from this build's expat:
  // every BMP code point was probed as <Xx/> (name start) and <aXx/>
  // (name char); no supplementary-plane code point is accepted.
  static bool in_ranges(unsigned cp, const unsigned (*r)[2], int n) {
    int lo = 0, hi = n - 1;
    while (lo <= hi) {
      int mid = (lo + hi) / 2;
      if (cp < r[mid][0]) hi = mid - 1;
      else if (cp > r[mid][1]) lo = mid + 1;
      else return true;
    }
    return false;
  }
  static bool is_name_start_cp(unsigned cp) {
    if (cp < 0x80) {
      return cp == ':' || cp == '_' || (cp >= 'A' && cp <= 'Z') ||
             (cp >= 'a' && cp <= 'z');
    }
    return in_ranges(cp, kNameStartRanges,
                     sizeof(kNameStartRanges) / sizeof(*kNameStartRanges));
  }
  static bool is_name_cp(unsigned cp) {
    if (cp < 0x80) {
      return is_name_start_cp(cp) || cp == '-' || cp == '.' ||
             (cp >= '0' && cp <= '9');
    }
    return in_ranges(cp, kNameCharRanges,
                     sizeof(kNameCharRanges) / sizeof(*kNameCharRanges));
  }
  // Decode one code point; input is valid UTF-8 (document pre-scan).
  static unsigned next_cp(const char*& q) {
    unsigned char c = static_cast<unsigned char>(*q++);
    if (c < 0x80) return c;
    int extra = c >= 0xF0 ? 3 : c >= 0xE0 ? 2 : 1;
    unsigned cp = c & (0x3F >> extra);
    for (int i = 0; i < extra; ++i) {
      cp = (cp << 6) | (static_cast<unsigned char>(*q++) & 0x3F);
    }
    return cp;
  }
  static bool valid_name(const char* s, const char* e) {
    const char* q = s;
    bool first = true;
    while (q < e) {
      unsigned cp = next_cp(q);
      if (first ? !is_name_start_cp(cp) : !is_name_cp(cp)) return false;
      first = false;
    }
    return !first;
  }

  // Namespace validation (the Python fallback parses through expat
  // WITH namespace processing, so this is part of the parity
  // contract): unbound prefixes reject; NCName structure (no second
  // colon, local part starts with a NameStartChar); declarations with
  // empty URIs or reserved prefixes reject; duplicate attributes are
  // detected on EXPANDED (uri, local) names. Bindings declared on THIS
  // tag apply to the whole tag regardless of attribute order.
  static constexpr const char* kXmlUri =
      "http://www.w3.org/XML/1998/namespace";
  static constexpr const char* kXmlnsUri =
      "http://www.w3.org/2000/xmlns/";

  bool check_prefixes(Tag* tag) {
    // collect this tag's declarations (with URI validation)
    for (size_t i = 0; i < tag->raw_attr_names.size(); ++i) {
      const std::string& raw = tag->raw_attr_names[i];
      if (raw == "xmlns") {
        // default-namespace declaration: xmlns="" (undeclaring) is
        // legal, but binding the default to either reserved URI is
        // not — expat (the fallback's parser) rejects both binding
        // the xmlns URI to anything and binding the xml URI to any
        // prefix other than "xml", the default included.
        const std::string& uri = tag->attrs[i].value;
        if (uri == kXmlUri || uri == kXmlnsUri) {
          return fail("reserved namespace binding");
        }
        continue;
      }
      if (raw.compare(0, 6, "xmlns:") == 0) {
        std::string pre = raw.substr(6);
        const std::string& uri = tag->attrs[i].value;
        if (pre.empty() || pre.find(':') != std::string::npos) {
          return fail("malformed xmlns declaration");
        }
        if (uri.empty()) return fail("must not undeclare prefix");
        if (pre == "xmlns") return fail("reserved prefix (xmlns)");
        if (pre == "xml" ? uri != kXmlUri
                         : (uri == kXmlUri || uri == kXmlnsUri)) {
          return fail("reserved namespace binding");
        }
        tag->declared.push_back(pre);
        tag->declared_uris.push_back(uri);
      }
    }
    // prefix → URI under this tag's scope ("" = unbound)
    auto resolve = [&](const std::string& pre) -> std::string {
      if (pre == "xml") return kXmlUri;
      for (size_t i = tag->declared.size(); i-- > 0;) {
        if (tag->declared[i] == pre) return tag->declared_uris[i];
      }
      auto it = ns_active.find(pre);
      if (it != ns_active.end()) return it->second.back();
      return "";
    };
    // split + structural NCName checks; returns false on malformed
    auto split_name = [&](const std::string& raw, std::string* pre,
                          std::string* local) -> bool {
      size_t c = raw.find(':');
      if (c == std::string::npos) {
        *pre = "";
        *local = raw;
        return true;
      }
      *pre = raw.substr(0, c);
      *local = raw.substr(c + 1);
      if (pre->empty() || local->empty() ||
          local->find(':') != std::string::npos) {
        return false;  // ":x", "x:", "a:b:c"
      }
      const char* q = local->data();
      if (!is_name_start_cp(next_cp(q))) return false;  // e.g. "p:9x"
      return true;
    };
    std::string pre, local;
    if (!split_name(tag->raw_name, &pre, &local) ||
        pre == "xmlns" || (!pre.empty() && resolve(pre).empty())) {
      return fail("unbound or malformed namespace prefix");
    }
    // expanded-name duplicate detection (raw duplicates were caught
    // inline during attribute parsing)
    std::vector<std::pair<std::string, std::string>> seen;
    for (const auto& raw : tag->raw_attr_names) {
      if (raw == "xmlns" || raw.compare(0, 6, "xmlns:") == 0) continue;
      if (!split_name(raw, &pre, &local)) {
        return fail("unbound or malformed namespace prefix");
      }
      std::string uri;
      if (!pre.empty()) {
        uri = resolve(pre);
        if (uri.empty()) {
          return fail("unbound or malformed namespace prefix");
        }
      }
      for (const auto& sn : seen) {
        if (sn.first == uri && sn.second == local) {
          return fail("duplicate attribute");
        }
      }
      seen.emplace_back(std::move(uri), std::move(local));
    }
    return true;
  }

  bool parse_tag(Tag* tag) {
    tag->attrs.clear();
    tag->raw_attr_names.clear();
    tag->declared.clear();
    tag->declared_uris.clear();
    tag->closing = tag->self_closing = false;
    if (p < end && *p == '/') {
      tag->closing = true;
      ++p;
    }
    const char* start = p;
    while (p < end && is_name_char(*p)) ++p;
    if (!valid_name(start, p)) return fail("malformed tag name");
    tag->raw_name.assign(start, p - start);
    tag->name = local_name(tag->raw_name);
    // attributes
    while (p < end) {
      while (p < end && is_space(*p)) ++p;
      if (p >= end) return fail("unterminated tag");
      if (*p == '>') {
        ++p;
        return check_prefixes(tag);
      }
      if (*p == '/') {
        if (tag->closing) return fail("malformed closing tag");
        ++p;
        if (p < end && *p == '>') {
          ++p;
          tag->self_closing = true;
          return check_prefixes(tag);
        }
        return fail("stray '/' in tag");
      }
      if (tag->closing) return fail("attribute on closing tag");
      const char* astart = p;
      while (p < end && is_name_char(*p)) ++p;
      if (!valid_name(astart, p)) return fail("malformed attribute name");
      const char* p0 = p;
      std::string aname = local_name(std::string(astart, p - astart));
      while (p < end && is_space(*p)) ++p;
      if (p >= end || *p != '=') return fail("attribute without value");
      ++p;
      while (p < end && is_space(*p)) ++p;
      if (p >= end || (*p != '"' && *p != '\'')) return fail("unquoted attribute");
      char quote = *p++;
      const char* vstart = p;
      const char* vend =
          static_cast<const char*>(memchr(p, quote, end - p));
      if (!vend) return fail("unterminated attribute value");
      if (memchr(vstart, '<', vend - vstart)) {
        return fail("'<' in attribute value");
      }
      p = vend + 1;
      if (p < end && !is_space(*p) && *p != '>' && *p != '/') {
        return fail("missing whitespace between attributes");
      }
      std::string decoded, err;
      if (!decode_entities_strict(vstart, vend - vstart, &decoded, &err)) {
        return fail_str(err + " in attribute value");
      }
      for (const auto& r : tag->raw_attr_names) {
        if (r.size() == static_cast<size_t>(p0 - astart) &&
            memcmp(r.data(), astart, r.size()) == 0) {
          return fail("duplicate attribute");
        }
      }
      tag->raw_attr_names.emplace_back(astart, p0 - astart);
      tag->attrs.push_back({std::move(aname), std::move(decoded)});
    }
    return fail("unterminated tag");
  }
};

struct Gexf {
  std::string nodes_blob;  // id\0label\0type\0 ...
  std::string edges_blob;  // src\0dst\0rel\0 ...
  std::string graph_name;
  long num_nodes = 0;
  long num_edges = 0;
  std::string error;
};

void append3(std::string* blob, const std::string& a, const std::string& b,
             const std::string& c) {
  blob->append(a);
  blob->push_back('\0');
  blob->append(b);
  blob->push_back('\0');
  blob->append(c);
  blob->push_back('\0');
}

}  // namespace

extern "C" {

Gexf* gexf_parse(const char* path) {
  auto* g = new Gexf();
  FILE* f = fopen(path, "rb");
  if (!f) {
    g->error = std::string("cannot open ") + path;
    return g;
  }
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string data(static_cast<size_t>(size), '\0');
  if (size > 0 && fread(&data[0], 1, size, f) != static_cast<size_t>(size)) {
    fclose(f);
    g->error = "short read";
    return g;
  }
  fclose(f);

  if (!validate_document(data, &g->error)) return g;

  // A UTF-8 BOM is legal before the XML declaration — skip it so the
  // declaration still counts as "at start of document".
  const char* doc = data.data();
  size_t doc_len = data.size();
  if (doc_len >= 3 && memcmp(doc, "\xEF\xBB\xBF", 3) == 0) {
    doc += 3;
    doc_len -= 3;
  }
  Parser parser(doc, doc_len);
  Tag tag;

  // attribute-id → title maps, per declaration class
  std::unordered_map<std::string, std::string> node_titles, edge_titles;
  std::string cur_attr_class;

  struct EdgeRec {
    std::string src, dst, rel;
  };
  std::vector<EdgeRec> edges;
  std::unordered_map<std::string, size_t> edge_pos;  // "src\0dst" → index

  // current open element being filled (node or edge)
  enum class Open { None, Node, Edge } open = Open::None;
  std::string cur_id, cur_label, cur_type;  // node fields
  bool cur_label_present = false;  // label="" is kept, absent falls back to id
  EdgeRec cur_edge;

  auto flush_node = [&]() {
    append3(&g->nodes_blob, cur_id, cur_label_present ? cur_label : cur_id,
            cur_type);
    ++g->num_nodes;
  };
  auto flush_edge = [&]() {
    std::string key = cur_edge.src + '\0' + cur_edge.dst;
    auto it = edge_pos.find(key);
    if (it == edge_pos.end()) {
      edge_pos.emplace(std::move(key), edges.size());
      edges.push_back(cur_edge);
    } else {
      edges[it->second].rel = cur_edge.rel;  // last relationship wins
    }
  };

  while (parser.next_tag(&tag)) {
    if (!tag.closing) {
      if (tag.name == "graph") {
        const char* nm = attr_of(tag, "name");
        g->graph_name = nm ? nm : "";
      } else if (tag.name == "attributes") {
        const char* cls = attr_of(tag, "class");
        cur_attr_class = cls ? cls : "";
      } else if (tag.name == "attribute" && !cur_attr_class.empty()) {
        const char* id = attr_of(tag, "id");
        const char* title = attr_of(tag, "title");
        auto& titles = cur_attr_class == "node" ? node_titles : edge_titles;
        titles[id ? id : ""] = title ? title : "";
        if (tag.self_closing) continue;
      } else if (tag.name == "node") {
        const char* id = attr_of(tag, "id");
        const char* label = attr_of(tag, "label");
        cur_id = id ? id : "";
        cur_label = label ? label : "";
        cur_label_present = label != nullptr;
        cur_type.clear();
        if (tag.self_closing) {
          flush_node();
        } else {
          open = Open::Node;
        }
      } else if (tag.name == "edge") {
        const char* src = attr_of(tag, "source");
        const char* dst = attr_of(tag, "target");
        const char* label = attr_of(tag, "label");
        cur_edge = {src ? src : "", dst ? dst : "", label ? label : ""};
        if (tag.self_closing) {
          flush_edge();
        } else {
          open = Open::Edge;
        }
      } else if (tag.name == "attvalue") {
        std::string for_id = attr_of(tag, "for") ? attr_of(tag, "for") : "";
        const char* value = attr_of(tag, "value");
        // Undeclared attribute ids fall back to the id itself as the
        // title, and repeated attvalues overwrite (dict semantics) —
        // both matching the Python parser's titles.get(id, id).
        if (open == Open::Node) {
          auto it = node_titles.find(for_id);
          const std::string& title =
              it != node_titles.end() ? it->second : for_id;
          if (title == "node_type") cur_type = value ? value : "";
        } else if (open == Open::Edge) {
          auto it = edge_titles.find(for_id);
          const std::string& title =
              it != edge_titles.end() ? it->second : for_id;
          if (title == "label") cur_edge.rel = value ? value : "";
        }
      }
    } else {  // closing tag
      if (tag.name == "node" && open == Open::Node) {
        flush_node();
        open = Open::None;
      } else if (tag.name == "edge" && open == Open::Edge) {
        flush_edge();
        open = Open::None;
      } else if (tag.name == "attributes") {
        cur_attr_class.clear();
      }
    }
  }

  if (!parser.error.empty()) {
    g->error = parser.error;
    return g;
  }
  if (!parser.eof_ok()) {
    g->error = parser.seen_root
                   ? "truncated document (unclosed elements at EOF)"
                   : "no document element";
    return g;
  }
  for (const auto& e : edges) append3(&g->edges_blob, e.src, e.dst, e.rel);
  g->num_edges = static_cast<long>(edges.size());
  return g;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Encoded view: the native twin of data/encode.encode_hin + infer_schema.
//
// Marshalling strings for millions of nodes/edges costs more than the
// parse itself (measured: the blob→Vertex/Edge path is SLOWER than pure
// Python at 2M nodes), so the hot path never builds per-edge Python
// objects: types and relationships are interned here, edge endpoints
// are resolved to dense per-type indices natively, and Python receives
// int32 COO arrays plus one id\0label\0 blob per node type.
//
// Semantics mirrored exactly (see data/encode.py, data/schema.py):
//   - node types in first-appearance vertex order; per-type node index
//     in document order; duplicate node ids: every occurrence gets an
//     index, LAST occurrence wins for edge resolution (dict overwrite)
//   - relationship signatures inferred from endpoints; mixed signatures
//     rejected; missing endpoints rejected (same messages)
//   - blocks keyed per relationship in first-appearance (deduped) edge
//     order; COO entries in edge order
// ---------------------------------------------------------------------------

struct GexfEncoded {
  std::string type_names_blob;            // type\0 per type
  std::vector<long> type_counts;          // nodes per type
  std::string nodes_blob;                 // per type: id\0label\0 ...
  std::vector<long> node_blob_offsets;    // n_types+1 byte offsets
  std::string rel_names_blob;             // rel\0 per relationship
  std::vector<int> rel_types;             // 2*n_rels: src,dst type idx
  std::vector<long> rel_offsets;          // n_rels+1 entry offsets
  std::vector<int> rows, cols;            // concatenated COO
  std::string error;
};

extern "C" {

GexfEncoded* gexf_encode(Gexf* g) {
  auto* e = new GexfEncoded();
  if (!g->error.empty()) {
    e->error = g->error;
    return e;
  }
  // Walk the nodes blob once: intern types, assign per-type indices.
  std::unordered_map<std::string, int> type_idx;
  std::vector<std::string> type_names;
  std::vector<std::string> per_type_blob;
  // id → (type, within-type index); overwrite = last occurrence wins.
  std::unordered_map<std::string, std::pair<int, int>> node_of;
  node_of.reserve(static_cast<size_t>(g->num_nodes) * 2);
  {
    const char* p = g->nodes_blob.data();
    const char* end = p + g->nodes_blob.size();
    while (p < end) {
      const char* id = p;
      size_t idl = strlen(p);
      p += idl + 1;
      const char* label = p;
      size_t labell = strlen(p);
      p += labell + 1;
      std::string type(p);
      p += type.size() + 1;
      auto it = type_idx.find(type);
      int t;
      if (it == type_idx.end()) {
        t = static_cast<int>(type_names.size());
        type_idx.emplace(type, t);
        type_names.push_back(type);
        per_type_blob.emplace_back();
        e->type_counts.push_back(0);
      } else {
        t = it->second;
      }
      int within = static_cast<int>(e->type_counts[t]++);
      auto& blob = per_type_blob[t];
      blob.append(id, idl);
      blob.push_back('\0');
      blob.append(label, labell);
      blob.push_back('\0');
      node_of[std::string(id, idl)] = {t, within};
    }
  }
  e->node_blob_offsets.push_back(0);
  for (size_t t = 0; t < per_type_blob.size(); ++t) {
    e->nodes_blob += per_type_blob[t];
    e->node_blob_offsets.push_back(static_cast<long>(e->nodes_blob.size()));
    e->type_names_blob += type_names[t];
    e->type_names_blob.push_back('\0');
  }

  // Walk the edges blob: infer relationship signatures, resolve COO.
  std::unordered_map<std::string, int> rel_idx;
  std::vector<std::vector<int>> rel_rows, rel_cols;
  {
    const char* p = g->edges_blob.data();
    const char* end = p + g->edges_blob.size();
    while (p < end) {
      std::string src(p);
      p += src.size() + 1;
      std::string dst(p);
      p += dst.size() + 1;
      std::string rel(p);
      p += rel.size() + 1;
      auto si = node_of.find(src);
      auto di = node_of.find(dst);
      if (si == node_of.end() || di == node_of.end()) {
        e->error = "edge endpoint '" +
                   (si == node_of.end() ? src : dst) +
                   "' has no vertex entry";
        return e;
      }
      auto it = rel_idx.find(rel);
      int r;
      if (it == rel_idx.end()) {
        r = static_cast<int>(rel_rows.size());
        rel_idx.emplace(rel, r);
        rel_rows.emplace_back();
        rel_cols.emplace_back();
        e->rel_names_blob += rel;
        e->rel_names_blob.push_back('\0');
        e->rel_types.push_back(si->second.first);
        e->rel_types.push_back(di->second.first);
      } else {
        r = it->second;
        if (e->rel_types[2 * r] != si->second.first ||
            e->rel_types[2 * r + 1] != di->second.first) {
          e->error = "relationship '" + rel + "' has mixed signatures";
          return e;
        }
      }
      rel_rows[r].push_back(si->second.second);
      rel_cols[r].push_back(di->second.second);
    }
  }
  e->rel_offsets.push_back(0);
  for (size_t r = 0; r < rel_rows.size(); ++r) {
    e->rows.insert(e->rows.end(), rel_rows[r].begin(), rel_rows[r].end());
    e->cols.insert(e->cols.end(), rel_cols[r].begin(), rel_cols[r].end());
    e->rel_offsets.push_back(static_cast<long>(e->rows.size()));
  }
  return e;
}

long genc_num_types(GexfEncoded* e) {
  return static_cast<long>(e->type_counts.size());
}
const char* genc_type_names(GexfEncoded* e, long* len) {
  *len = static_cast<long>(e->type_names_blob.size());
  return e->type_names_blob.data();
}
const long* genc_type_counts(GexfEncoded* e) { return e->type_counts.data(); }
const char* genc_nodes_blob(GexfEncoded* e, long* len) {
  *len = static_cast<long>(e->nodes_blob.size());
  return e->nodes_blob.data();
}
const long* genc_node_offsets(GexfEncoded* e) {
  return e->node_blob_offsets.data();
}
long genc_num_rels(GexfEncoded* e) {
  return static_cast<long>(e->rel_offsets.size()) - 1;
}
const char* genc_rel_names(GexfEncoded* e, long* len) {
  *len = static_cast<long>(e->rel_names_blob.size());
  return e->rel_names_blob.data();
}
const int* genc_rel_types(GexfEncoded* e) { return e->rel_types.data(); }
const long* genc_rel_offsets(GexfEncoded* e) { return e->rel_offsets.data(); }
const int* genc_rows(GexfEncoded* e) { return e->rows.data(); }
const int* genc_cols(GexfEncoded* e) { return e->cols.data(); }
const char* genc_error(GexfEncoded* e) {
  return e->error.empty() ? nullptr : e->error.c_str();
}
void genc_free(GexfEncoded* e) { delete e; }

}  // extern "C"

extern "C" {

long gexf_num_nodes(Gexf* g) { return g->num_nodes; }
long gexf_num_edges(Gexf* g) { return g->num_edges; }

const char* gexf_nodes_blob(Gexf* g, long* len) {
  *len = static_cast<long>(g->nodes_blob.size());
  return g->nodes_blob.data();
}
const char* gexf_edges_blob(Gexf* g, long* len) {
  *len = static_cast<long>(g->edges_blob.size());
  return g->edges_blob.data();
}
const char* gexf_graph_name(Gexf* g) { return g->graph_name.c_str(); }

const char* gexf_error(Gexf* g) {
  return g->error.empty() ? nullptr : g->error.c_str();
}
void gexf_free(Gexf* g) { delete g; }

}  // extern "C"
