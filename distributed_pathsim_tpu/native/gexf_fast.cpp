// Fast streaming GEXF parser (native data-loader for the framework).
//
// The reference's loader is networkx.read_gexf through Python XML DOM
// (reference DPathSim_APVPA.py:114-129) — fine for 2k nodes, minutes for
// millions. This is a single-pass, zero-dependency tokenizer over the
// GEXF subset the DBLP datasets use (nodes/edges with attvalues), with
// the exact semantics of the Python fallback in ../data/gexf.py:
//   - node_type   := node attvalue whose declared title is "node_type"
//   - relationship:= edge attvalue whose declared title is "label"
//                    (falling back to the edge's label= XML attribute)
//   - label       := node label= attribute, falling back to id
//   - duplicate (src,dst) edges keep first position, last relationship
//     (networkx DiGraph attribute-overwrite behavior)
//   - document order preserved (it drives the reference's log order)
//
// C ABI: results are returned as two NUL-separated string blobs
// (id\0label\0type\0 per node; src\0dst\0rel\0 per edge) consumed by
// ctypes in gexf_native.py.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Attr {
  std::string name;
  std::string value;
};

// Decode the five XML built-in entities plus numeric references.
std::string decode_entities(const std::string& s) {
  if (s.find('&') == std::string::npos) return s;
  std::string out;
  out.reserve(s.size());
  for (size_t i = 0; i < s.size();) {
    if (s[i] != '&') {
      out += s[i++];
      continue;
    }
    size_t semi = s.find(';', i);
    if (semi == std::string::npos || semi - i > 12) {
      out += s[i++];
      continue;
    }
    std::string ent = s.substr(i + 1, semi - i - 1);
    if (ent == "amp") out += '&';
    else if (ent == "lt") out += '<';
    else if (ent == "gt") out += '>';
    else if (ent == "quot") out += '"';
    else if (ent == "apos") out += '\'';
    else if (!ent.empty() && ent[0] == '#') {
      long cp = (ent.size() > 1 && (ent[1] == 'x' || ent[1] == 'X'))
                    ? strtol(ent.c_str() + 2, nullptr, 16)
                    : strtol(ent.c_str() + 1, nullptr, 10);
      // UTF-8 encode the code point.
      if (cp < 0x80) out += static_cast<char>(cp);
      else if (cp < 0x800) {
        out += static_cast<char>(0xC0 | (cp >> 6));
        out += static_cast<char>(0x80 | (cp & 0x3F));
      } else if (cp < 0x10000) {
        out += static_cast<char>(0xE0 | (cp >> 12));
        out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
        out += static_cast<char>(0x80 | (cp & 0x3F));
      } else {
        out += static_cast<char>(0xF0 | (cp >> 18));
        out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
        out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
        out += static_cast<char>(0x80 | (cp & 0x3F));
      }
    } else {
      out += s.substr(i, semi - i + 1);  // unknown entity: keep verbatim
      i = semi + 1;
      continue;
    }
    i = semi + 1;
  }
  return out;
}

// A minimal tag token: name + attributes + open/close/selfclose kind.
struct Tag {
  std::string name;
  std::vector<Attr> attrs;
  bool closing = false;      // </name>
  bool self_closing = false; // <name ... />
};

const char* attr_of(const Tag& t, const char* name) {
  for (const auto& a : t.attrs)
    if (a.name == name) return a.value.c_str();
  return nullptr;
}

std::string local_name(const std::string& qname) {
  size_t c = qname.rfind(':');
  return c == std::string::npos ? qname : qname.substr(c + 1);
}

struct Parser {
  const char* p;
  const char* end;
  std::string error;

  explicit Parser(const char* data, size_t len) : p(data), end(data + len) {}

  // Advance to the next tag; returns false at EOF. Skips comments,
  // CDATA, processing instructions, and doctype declarations.
  bool next_tag(Tag* tag) {
    while (p < end) {
      const char* lt = static_cast<const char*>(memchr(p, '<', end - p));
      if (!lt) return false;
      p = lt + 1;
      if (p >= end) return false;
      if (*p == '?') {  // <?xml ... ?>
        const char* close = strstr_bounded("?>");
        if (!close) return fail("unterminated PI");
        p = close + 2;
        continue;
      }
      if (*p == '!') {
        if (end - p >= 3 && p[1] == '-' && p[2] == '-') {  // comment
          const char* close = strstr_bounded("-->");
          if (!close) return fail("unterminated comment");
          p = close + 3;
          continue;
        }
        if (end - p >= 8 && strncmp(p, "![CDATA[", 8) == 0) {
          const char* close = strstr_bounded("]]>");
          if (!close) return fail("unterminated CDATA");
          p = close + 3;
          continue;
        }
        const char* close = static_cast<const char*>(memchr(p, '>', end - p));
        if (!close) return fail("unterminated declaration");
        p = close + 1;
        continue;
      }
      return parse_tag(tag);
    }
    return false;
  }

 private:
  const char* strstr_bounded(const char* needle) {
    size_t n = strlen(needle);
    for (const char* q = p; q + n <= end; ++q)
      if (memcmp(q, needle, n) == 0) return q;
    return nullptr;
  }

  bool fail(const char* msg) {
    error = msg;
    p = end;
    return false;
  }

  static bool is_space(char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r';
  }
  static bool is_name_char(char c) {
    return !is_space(c) && c != '>' && c != '/' && c != '=';
  }

  bool parse_tag(Tag* tag) {
    tag->attrs.clear();
    tag->closing = tag->self_closing = false;
    if (p < end && *p == '/') {
      tag->closing = true;
      ++p;
    }
    const char* start = p;
    while (p < end && is_name_char(*p)) ++p;
    tag->name = local_name(std::string(start, p - start));
    // attributes
    while (p < end) {
      while (p < end && is_space(*p)) ++p;
      if (p >= end) return fail("unterminated tag");
      if (*p == '>') {
        ++p;
        return true;
      }
      if (*p == '/') {
        ++p;
        if (p < end && *p == '>') {
          ++p;
          tag->self_closing = true;
          return true;
        }
        return fail("stray '/' in tag");
      }
      const char* astart = p;
      while (p < end && is_name_char(*p)) ++p;
      std::string aname = local_name(std::string(astart, p - astart));
      while (p < end && is_space(*p)) ++p;
      if (p >= end || *p != '=') return fail("attribute without value");
      ++p;
      while (p < end && is_space(*p)) ++p;
      if (p >= end || (*p != '"' && *p != '\'')) return fail("unquoted attribute");
      char quote = *p++;
      const char* vstart = p;
      const char* vend =
          static_cast<const char*>(memchr(p, quote, end - p));
      if (!vend) return fail("unterminated attribute value");
      p = vend + 1;
      tag->attrs.push_back(
          {std::move(aname), decode_entities(std::string(vstart, vend - vstart))});
    }
    return fail("unterminated tag");
  }
};

struct Gexf {
  std::string nodes_blob;  // id\0label\0type\0 ...
  std::string edges_blob;  // src\0dst\0rel\0 ...
  std::string graph_name;
  long num_nodes = 0;
  long num_edges = 0;
  std::string error;
};

void append3(std::string* blob, const std::string& a, const std::string& b,
             const std::string& c) {
  blob->append(a);
  blob->push_back('\0');
  blob->append(b);
  blob->push_back('\0');
  blob->append(c);
  blob->push_back('\0');
}

}  // namespace

extern "C" {

Gexf* gexf_parse(const char* path) {
  auto* g = new Gexf();
  FILE* f = fopen(path, "rb");
  if (!f) {
    g->error = std::string("cannot open ") + path;
    return g;
  }
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string data(static_cast<size_t>(size), '\0');
  if (size > 0 && fread(&data[0], 1, size, f) != static_cast<size_t>(size)) {
    fclose(f);
    g->error = "short read";
    return g;
  }
  fclose(f);

  Parser parser(data.data(), data.size());
  Tag tag;

  // attribute-id → title maps, per declaration class
  std::unordered_map<std::string, std::string> node_titles, edge_titles;
  std::string cur_attr_class;

  struct EdgeRec {
    std::string src, dst, rel;
  };
  std::vector<EdgeRec> edges;
  std::unordered_map<std::string, size_t> edge_pos;  // "src\0dst" → index

  // current open element being filled (node or edge)
  enum class Open { None, Node, Edge } open = Open::None;
  std::string cur_id, cur_label, cur_type;  // node fields
  bool cur_label_present = false;  // label="" is kept, absent falls back to id
  EdgeRec cur_edge;

  auto flush_node = [&]() {
    append3(&g->nodes_blob, cur_id, cur_label_present ? cur_label : cur_id,
            cur_type);
    ++g->num_nodes;
  };
  auto flush_edge = [&]() {
    std::string key = cur_edge.src + '\0' + cur_edge.dst;
    auto it = edge_pos.find(key);
    if (it == edge_pos.end()) {
      edge_pos.emplace(std::move(key), edges.size());
      edges.push_back(cur_edge);
    } else {
      edges[it->second].rel = cur_edge.rel;  // last relationship wins
    }
  };

  while (parser.next_tag(&tag)) {
    if (!tag.closing) {
      if (tag.name == "graph") {
        const char* nm = attr_of(tag, "name");
        g->graph_name = nm ? nm : "";
      } else if (tag.name == "attributes") {
        const char* cls = attr_of(tag, "class");
        cur_attr_class = cls ? cls : "";
      } else if (tag.name == "attribute" && !cur_attr_class.empty()) {
        const char* id = attr_of(tag, "id");
        const char* title = attr_of(tag, "title");
        auto& titles = cur_attr_class == "node" ? node_titles : edge_titles;
        titles[id ? id : ""] = title ? title : "";
        if (tag.self_closing) continue;
      } else if (tag.name == "node") {
        const char* id = attr_of(tag, "id");
        const char* label = attr_of(tag, "label");
        cur_id = id ? id : "";
        cur_label = label ? label : "";
        cur_label_present = label != nullptr;
        cur_type.clear();
        if (tag.self_closing) {
          flush_node();
        } else {
          open = Open::Node;
        }
      } else if (tag.name == "edge") {
        const char* src = attr_of(tag, "source");
        const char* dst = attr_of(tag, "target");
        const char* label = attr_of(tag, "label");
        cur_edge = {src ? src : "", dst ? dst : "", label ? label : ""};
        if (tag.self_closing) {
          flush_edge();
        } else {
          open = Open::Edge;
        }
      } else if (tag.name == "attvalue") {
        std::string for_id = attr_of(tag, "for") ? attr_of(tag, "for") : "";
        const char* value = attr_of(tag, "value");
        // Undeclared attribute ids fall back to the id itself as the
        // title, and repeated attvalues overwrite (dict semantics) —
        // both matching the Python parser's titles.get(id, id).
        if (open == Open::Node) {
          auto it = node_titles.find(for_id);
          const std::string& title =
              it != node_titles.end() ? it->second : for_id;
          if (title == "node_type") cur_type = value ? value : "";
        } else if (open == Open::Edge) {
          auto it = edge_titles.find(for_id);
          const std::string& title =
              it != edge_titles.end() ? it->second : for_id;
          if (title == "label") cur_edge.rel = value ? value : "";
        }
      }
    } else {  // closing tag
      if (tag.name == "node" && open == Open::Node) {
        flush_node();
        open = Open::None;
      } else if (tag.name == "edge" && open == Open::Edge) {
        flush_edge();
        open = Open::None;
      } else if (tag.name == "attributes") {
        cur_attr_class.clear();
      }
    }
  }

  if (!parser.error.empty()) {
    g->error = parser.error;
    return g;
  }
  for (const auto& e : edges) append3(&g->edges_blob, e.src, e.dst, e.rel);
  g->num_edges = static_cast<long>(edges.size());
  return g;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Encoded view: the native twin of data/encode.encode_hin + infer_schema.
//
// Marshalling strings for millions of nodes/edges costs more than the
// parse itself (measured: the blob→Vertex/Edge path is SLOWER than pure
// Python at 2M nodes), so the hot path never builds per-edge Python
// objects: types and relationships are interned here, edge endpoints
// are resolved to dense per-type indices natively, and Python receives
// int32 COO arrays plus one id\0label\0 blob per node type.
//
// Semantics mirrored exactly (see data/encode.py, data/schema.py):
//   - node types in first-appearance vertex order; per-type node index
//     in document order; duplicate node ids: every occurrence gets an
//     index, LAST occurrence wins for edge resolution (dict overwrite)
//   - relationship signatures inferred from endpoints; mixed signatures
//     rejected; missing endpoints rejected (same messages)
//   - blocks keyed per relationship in first-appearance (deduped) edge
//     order; COO entries in edge order
// ---------------------------------------------------------------------------

struct GexfEncoded {
  std::string type_names_blob;            // type\0 per type
  std::vector<long> type_counts;          // nodes per type
  std::string nodes_blob;                 // per type: id\0label\0 ...
  std::vector<long> node_blob_offsets;    // n_types+1 byte offsets
  std::string rel_names_blob;             // rel\0 per relationship
  std::vector<int> rel_types;             // 2*n_rels: src,dst type idx
  std::vector<long> rel_offsets;          // n_rels+1 entry offsets
  std::vector<int> rows, cols;            // concatenated COO
  std::string error;
};

extern "C" {

GexfEncoded* gexf_encode(Gexf* g) {
  auto* e = new GexfEncoded();
  if (!g->error.empty()) {
    e->error = g->error;
    return e;
  }
  // Walk the nodes blob once: intern types, assign per-type indices.
  std::unordered_map<std::string, int> type_idx;
  std::vector<std::string> type_names;
  std::vector<std::string> per_type_blob;
  // id → (type, within-type index); overwrite = last occurrence wins.
  std::unordered_map<std::string, std::pair<int, int>> node_of;
  node_of.reserve(static_cast<size_t>(g->num_nodes) * 2);
  {
    const char* p = g->nodes_blob.data();
    const char* end = p + g->nodes_blob.size();
    while (p < end) {
      const char* id = p;
      size_t idl = strlen(p);
      p += idl + 1;
      const char* label = p;
      size_t labell = strlen(p);
      p += labell + 1;
      std::string type(p);
      p += type.size() + 1;
      auto it = type_idx.find(type);
      int t;
      if (it == type_idx.end()) {
        t = static_cast<int>(type_names.size());
        type_idx.emplace(type, t);
        type_names.push_back(type);
        per_type_blob.emplace_back();
        e->type_counts.push_back(0);
      } else {
        t = it->second;
      }
      int within = static_cast<int>(e->type_counts[t]++);
      auto& blob = per_type_blob[t];
      blob.append(id, idl);
      blob.push_back('\0');
      blob.append(label, labell);
      blob.push_back('\0');
      node_of[std::string(id, idl)] = {t, within};
    }
  }
  e->node_blob_offsets.push_back(0);
  for (size_t t = 0; t < per_type_blob.size(); ++t) {
    e->nodes_blob += per_type_blob[t];
    e->node_blob_offsets.push_back(static_cast<long>(e->nodes_blob.size()));
    e->type_names_blob += type_names[t];
    e->type_names_blob.push_back('\0');
  }

  // Walk the edges blob: infer relationship signatures, resolve COO.
  std::unordered_map<std::string, int> rel_idx;
  std::vector<std::vector<int>> rel_rows, rel_cols;
  {
    const char* p = g->edges_blob.data();
    const char* end = p + g->edges_blob.size();
    while (p < end) {
      std::string src(p);
      p += src.size() + 1;
      std::string dst(p);
      p += dst.size() + 1;
      std::string rel(p);
      p += rel.size() + 1;
      auto si = node_of.find(src);
      auto di = node_of.find(dst);
      if (si == node_of.end() || di == node_of.end()) {
        e->error = "edge endpoint '" +
                   (si == node_of.end() ? src : dst) +
                   "' has no vertex entry";
        return e;
      }
      auto it = rel_idx.find(rel);
      int r;
      if (it == rel_idx.end()) {
        r = static_cast<int>(rel_rows.size());
        rel_idx.emplace(rel, r);
        rel_rows.emplace_back();
        rel_cols.emplace_back();
        e->rel_names_blob += rel;
        e->rel_names_blob.push_back('\0');
        e->rel_types.push_back(si->second.first);
        e->rel_types.push_back(di->second.first);
      } else {
        r = it->second;
        if (e->rel_types[2 * r] != si->second.first ||
            e->rel_types[2 * r + 1] != di->second.first) {
          e->error = "relationship '" + rel + "' has mixed signatures";
          return e;
        }
      }
      rel_rows[r].push_back(si->second.second);
      rel_cols[r].push_back(di->second.second);
    }
  }
  e->rel_offsets.push_back(0);
  for (size_t r = 0; r < rel_rows.size(); ++r) {
    e->rows.insert(e->rows.end(), rel_rows[r].begin(), rel_rows[r].end());
    e->cols.insert(e->cols.end(), rel_cols[r].begin(), rel_cols[r].end());
    e->rel_offsets.push_back(static_cast<long>(e->rows.size()));
  }
  return e;
}

long genc_num_types(GexfEncoded* e) {
  return static_cast<long>(e->type_counts.size());
}
const char* genc_type_names(GexfEncoded* e, long* len) {
  *len = static_cast<long>(e->type_names_blob.size());
  return e->type_names_blob.data();
}
const long* genc_type_counts(GexfEncoded* e) { return e->type_counts.data(); }
const char* genc_nodes_blob(GexfEncoded* e, long* len) {
  *len = static_cast<long>(e->nodes_blob.size());
  return e->nodes_blob.data();
}
const long* genc_node_offsets(GexfEncoded* e) {
  return e->node_blob_offsets.data();
}
long genc_num_rels(GexfEncoded* e) {
  return static_cast<long>(e->rel_offsets.size()) - 1;
}
const char* genc_rel_names(GexfEncoded* e, long* len) {
  *len = static_cast<long>(e->rel_names_blob.size());
  return e->rel_names_blob.data();
}
const int* genc_rel_types(GexfEncoded* e) { return e->rel_types.data(); }
const long* genc_rel_offsets(GexfEncoded* e) { return e->rel_offsets.data(); }
const int* genc_rows(GexfEncoded* e) { return e->rows.data(); }
const int* genc_cols(GexfEncoded* e) { return e->cols.data(); }
const char* genc_error(GexfEncoded* e) {
  return e->error.empty() ? nullptr : e->error.c_str();
}
void genc_free(GexfEncoded* e) { delete e; }

}  // extern "C"

extern "C" {

long gexf_num_nodes(Gexf* g) { return g->num_nodes; }
long gexf_num_edges(Gexf* g) { return g->num_edges; }

const char* gexf_nodes_blob(Gexf* g, long* len) {
  *len = static_cast<long>(g->nodes_blob.size());
  return g->nodes_blob.data();
}
const char* gexf_edges_blob(Gexf* g, long* len) {
  *len = static_cast<long>(g->edges_blob.size());
  return g->edges_blob.data();
}
const char* gexf_graph_name(Gexf* g) { return g->graph_name.c_str(); }

const char* gexf_error(Gexf* g) {
  return g->error.empty() ? nullptr : g->error.c_str();
}
void gexf_free(Gexf* g) { delete g; }

}  // extern "C"
