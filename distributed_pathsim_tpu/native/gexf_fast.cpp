// Fast streaming GEXF parser (native data-loader for the framework).
//
// The reference's loader is networkx.read_gexf through Python XML DOM
// (reference DPathSim_APVPA.py:114-129) — fine for 2k nodes, minutes for
// millions. This is a single-pass, zero-dependency tokenizer over the
// GEXF subset the DBLP datasets use (nodes/edges with attvalues), with
// the exact semantics of the Python fallback in ../data/gexf.py:
//   - node_type   := node attvalue whose declared title is "node_type"
//   - relationship:= edge attvalue whose declared title is "label"
//                    (falling back to the edge's label= XML attribute)
//   - label       := node label= attribute, falling back to id
//   - duplicate (src,dst) edges keep first position, last relationship
//     (networkx DiGraph attribute-overwrite behavior)
//   - document order preserved (it drives the reference's log order)
//
// C ABI: results are returned as two NUL-separated string blobs
// (id\0label\0type\0 per node; src\0dst\0rel\0 per edge) consumed by
// ctypes in gexf_native.py.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Attr {
  std::string name;
  std::string value;
};

// ---------------------------------------------------------------------------
// Strictness (r04 differential fuzz): the native parser is the DEFAULT
// loader, and a 400-case mutation fuzz against the Python (expat) path
// found 86 inputs expat rejects that this tokenizer silently loaded —
// truncations, bad entities, byte corruption. A corrupted file must
// fail loudly, not load partially; these checks close every divergence
// class the fuzz surfaced (tests/test_native.py::test_differential_fuzz).
// ---------------------------------------------------------------------------

// Whole-document scan: reject invalid UTF-8 (incl. overlongs and
// surrogates) and control characters outside {\t, \n, \r} — expat
// refuses both wherever they appear (text, attributes, comments).
bool validate_document(const std::string& data, std::string* err) {
  const auto* s = reinterpret_cast<const unsigned char*>(data.data());
  size_t n = data.size();
  for (size_t i = 0; i < n;) {
    unsigned char c = s[i];
    if (c < 0x80) {
      if (c < 0x20 && c != '\t' && c != '\n' && c != '\r') {
        *err = "invalid control character";
        return false;
      }
      ++i;
      continue;
    }
    int len;
    if (c >= 0xC2 && c <= 0xDF) len = 2;
    else if (c >= 0xE0 && c <= 0xEF) len = 3;
    else if (c >= 0xF0 && c <= 0xF4) len = 4;
    else {  // continuation byte as lead, overlong lead, or > U+10FFFF
      *err = "invalid UTF-8";
      return false;
    }
    if (i + len > n) {
      *err = "truncated UTF-8 sequence";
      return false;
    }
    for (int k = 1; k < len; ++k) {
      if ((s[i + k] & 0xC0) != 0x80) {
        *err = "invalid UTF-8";
        return false;
      }
    }
    if ((c == 0xE0 && s[i + 1] < 0xA0) ||   // overlong 3-byte
        (c == 0xED && s[i + 1] >= 0xA0) ||  // UTF-16 surrogate
        (c == 0xF0 && s[i + 1] < 0x90) ||   // overlong 4-byte
        (c == 0xF4 && s[i + 1] >= 0x90)) {  // > U+10FFFF
      *err = "invalid UTF-8";
      return false;
    }
    // U+FFFE / U+FFFF (EF BF BE / EF BF BF) are not XML Chars; expat
    // rejects the literal bytes just like the numeric references.
    if (c == 0xEF && s[i + 1] == 0xBF &&
        (s[i + 2] == 0xBE || s[i + 2] == 0xBF)) {
      *err = "XML-invalid character U+FFFE/U+FFFF";
      return false;
    }
    i += len;
  }
  return true;
}

// Decode the five XML built-in entities plus numeric references —
// STRICT: unknown entities, bare '&', and numeric references to
// XML-invalid code points are errors (expat parity), never passed
// through. Entities are parsed inline (no arbitrary length cap —
// numeric references may carry leading zeros). ``out`` may be null to
// validate without building a string; when non-null (attribute
// values), literal whitespace normalizes to spaces the way expat's
// attribute-value normalization does (\r\n → one space; character
// REFERENCES like &#10; stay literal, per the XML spec).
bool decode_entities_strict(const char* s, size_t n, std::string* out,
                            std::string* err) {
  for (size_t i = 0; i < n;) {
    char c = s[i];
    if (c != '&') {
      if (c == '\r' && i + 1 < n && s[i + 1] == '\n') ++i;  // CRLF → LF
      if (out) {
        *out += (c == '\r' || c == '\n' || c == '\t') ? ' ' : c;
      }
      ++i;
      continue;
    }
    size_t j = i + 1;
    if (j < n && s[j] == '#') {
      ++j;
      bool hex = false;
      if (j < n && (s[j] == 'x' || s[j] == 'X')) {
        hex = true;
        ++j;
      }
      size_t d0 = j;
      long cp = 0;
      for (; j < n; ++j) {
        char ch = s[j];
        int digit;
        if (ch >= '0' && ch <= '9') digit = ch - '0';
        else if (hex && ch >= 'a' && ch <= 'f') digit = ch - 'a' + 10;
        else if (hex && ch >= 'A' && ch <= 'F') digit = ch - 'A' + 10;
        else break;
        if (cp <= 0x10FFFF) cp = cp * (hex ? 16 : 10) + digit;
        // saturates: once past the Unicode range further digits can't
        // bring it back, and the range check below rejects it
      }
      if (j == d0 || j >= n || s[j] != ';') {
        *err = "malformed numeric character reference";
        return false;
      }
      // XML 1.0 Char production: no control chars (except \t\n\r), no
      // surrogates, no U+FFFE/U+FFFF, nothing past U+10FFFF.
      if (cp > 0x10FFFF ||
          (cp < 0x20 && cp != 0x9 && cp != 0xA && cp != 0xD) ||
          (cp >= 0xD800 && cp <= 0xDFFF) || cp == 0xFFFE || cp == 0xFFFF) {
        *err = "numeric reference to XML-invalid character";
        return false;
      }
      if (out) {
        if (cp < 0x80) *out += static_cast<char>(cp);
        else if (cp < 0x800) {
          *out += static_cast<char>(0xC0 | (cp >> 6));
          *out += static_cast<char>(0x80 | (cp & 0x3F));
        } else if (cp < 0x10000) {
          *out += static_cast<char>(0xE0 | (cp >> 12));
          *out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
          *out += static_cast<char>(0x80 | (cp & 0x3F));
        } else {
          *out += static_cast<char>(0xF0 | (cp >> 18));
          *out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
          *out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
          *out += static_cast<char>(0x80 | (cp & 0x3F));
        }
      }
      i = j + 1;
      continue;
    }
    size_t e0 = j;
    while (j < n &&
           ((s[j] >= 'a' && s[j] <= 'z') || (s[j] >= 'A' && s[j] <= 'Z') ||
            (s[j] >= '0' && s[j] <= '9'))) {
      ++j;
    }
    if (j == e0 || j >= n || s[j] != ';') {
      *err = "bare '&' (unterminated entity reference)";
      return false;
    }
    std::string ent(s + e0, j - e0);
    if (ent == "amp") { if (out) *out += '&'; }
    else if (ent == "lt") { if (out) *out += '<'; }
    else if (ent == "gt") { if (out) *out += '>'; }
    else if (ent == "quot") { if (out) *out += '"'; }
    else if (ent == "apos") { if (out) *out += '\''; }
    else {
      *err = "unknown entity '&" + ent + ";'";
      return false;
    }
    i = j + 1;
  }
  return true;
}

// A minimal tag token: name + attributes + open/close/selfclose kind.
struct Tag {
  std::string name;          // namespace-stripped (semantic dispatch)
  std::string raw_name;      // as written (nesting must match exactly)
  std::vector<Attr> attrs;
  bool closing = false;      // </name>
  bool self_closing = false; // <name ... />
};

const char* attr_of(const Tag& t, const char* name) {
  for (const auto& a : t.attrs)
    if (a.name == name) return a.value.c_str();
  return nullptr;
}

std::string local_name(const std::string& qname) {
  size_t c = qname.rfind(':');
  return c == std::string::npos ? qname : qname.substr(c + 1);
}

struct Parser {
  const char* p;
  const char* end;
  const char* doc_start;
  std::string error;
  std::vector<std::string> open_stack;  // raw names of open elements
  bool seen_root = false;
  bool seen_doctype = false;

  explicit Parser(const char* data, size_t len)
      : p(data), end(data + len), doc_start(data) {}

  // True when the document ended well-formed: no error, exactly one
  // root element, and every element closed. Truncated files (the
  // fuzz's biggest silent-acceptance class) fail here.
  bool eof_ok() const {
    return error.empty() && seen_root && open_stack.empty();
  }

  // Advance to the next tag; returns false at EOF or error (check
  // ``error``). Skips comments, CDATA, processing instructions, and
  // doctype declarations; validates the text spans in between
  // (strict entities; nothing but whitespace outside the root).
  bool next_tag(Tag* tag) {
    while (p < end) {
      const char* lt = static_cast<const char*>(memchr(p, '<', end - p));
      if (!check_text(p, lt ? lt : end)) return false;
      if (!lt) { p = end; return false; }
      p = lt + 1;
      if (p >= end) return fail("truncated document");
      if (*p == '?') {  // processing instruction / XML declaration
        const char* pi_lt = p - 1;
        const char* close = strstr_bounded("?>");
        if (!close) return fail("unterminated PI");
        if (!check_pi(p + 1, close, pi_lt == doc_start)) return false;
        p = close + 2;
        continue;
      }
      if (*p == '!') {
        if (end - p >= 3 && p[1] == '-' && p[2] == '-') {  // comment
          const char* close = strstr_bounded("-->");
          if (!close) return fail("unterminated comment");
          p = close + 3;
          continue;
        }
        if (end - p >= 8 && strncmp(p, "![CDATA[", 8) == 0) {
          // CDATA is character data: only legal inside the root.
          if (open_stack.empty()) {
            return fail(seen_root ? "junk after document element"
                                  : "CDATA before document element");
          }
          const char* close = strstr_bounded("]]>");
          if (!close) return fail("unterminated CDATA");
          p = close + 3;
          continue;
        }
        if (end - p >= 8 && strncmp(p, "!DOCTYPE", 8) == 0 &&
            (end - p == 8 || is_space(p[8]))) {
          // one DOCTYPE, in the prolog only (internal subsets with
          // nested '>' are out of scope for GEXF)
          if (seen_root || seen_doctype) return fail("misplaced DOCTYPE");
          seen_doctype = true;
          const char* close =
              static_cast<const char*>(memchr(p, '>', end - p));
          if (!close) return fail("unterminated declaration");
          p = close + 1;
          continue;
        }
        // Anything else after '<!' is corruption — skipping it would
        // silently drop a damaged element (e.g. a byte flip turning
        // '<node .../>' into '<!ode .../>').
        return fail("malformed markup declaration");
      }
      if (!parse_tag(tag)) return false;
      // Well-formedness: closing tags must match the innermost open
      // element; a second root (or any tag after the root closed) is
      // junk after the document element.
      if (tag->closing) {
        if (open_stack.empty() || open_stack.back() != tag->raw_name) {
          return fail("mismatched closing tag");
        }
        open_stack.pop_back();
      } else {
        if (open_stack.empty() && seen_root) {
          return fail("junk after document element");
        }
        seen_root = true;
        if (!tag->self_closing) open_stack.push_back(tag->raw_name);
      }
      return true;
    }
    return false;
  }

 private:
  const char* strstr_bounded(const char* needle) {
    size_t n = strlen(needle);
    for (const char* q = p; q + n <= end; ++q)
      if (memcmp(q, needle, n) == 0) return q;
    return nullptr;
  }

  bool fail(const char* msg) {
    error = msg;
    p = end;
    return false;
  }

  bool fail_str(std::string msg) {
    error = std::move(msg);
    p = end;
    return false;
  }

  // Processing instruction [s, e): target name must be a valid Name,
  // and the reserved target "xml" (any case) is only legal as THE XML
  // DECLARATION — first bytes of the document, with the strict
  // version/encoding/standalone pseudo-attribute grammar expat
  // enforces. Catches duplicated or displaced declarations and
  // corruption inside the declaration itself.
  bool check_pi(const char* s, const char* e, bool at_doc_start) {
    const char* q = s;
    const char* name_start = q;
    while (q < e && is_name_char(*q)) ++q;
    if (q == name_start ||
        !is_name_start(static_cast<unsigned char>(*name_start))) {
      return fail("malformed PI target");
    }
    std::string target(name_start, q - name_start);
    bool is_xml_decl =
        target.size() == 3 && (target[0] == 'x' || target[0] == 'X') &&
        (target[1] == 'm' || target[1] == 'M') &&
        (target[2] == 'l' || target[2] == 'L');
    if (!is_xml_decl) return true;  // ordinary PI: contents are free-form
    if (!at_doc_start || target != "xml") {
      return fail("XML declaration not at start of document");
    }
    // version="1.x" [encoding="..."] [standalone="yes|no"]
    const char* names[3] = {"version", "encoding", "standalone"};
    int next_allowed = 0;
    while (true) {
      const char* before = q;
      while (q < e && is_space(*q)) ++q;
      if (q == e) break;
      if (before == q) return fail("malformed XML declaration");
      const char* a0 = q;
      while (q < e && is_name_char(*q)) ++q;
      std::string an(a0, q - a0);
      int which = -1;
      for (int i = next_allowed; i < 3; ++i) {
        if (an == names[i]) { which = i; break; }
      }
      if (which < 0 || (which > 0 && next_allowed == 0)) {
        return fail("malformed XML declaration");  // wrong name/order
      }
      next_allowed = which + 1;
      while (q < e && is_space(*q)) ++q;
      if (q == e || *q != '=') return fail("malformed XML declaration");
      ++q;
      while (q < e && is_space(*q)) ++q;
      if (q == e || (*q != '"' && *q != '\'')) {
        return fail("malformed XML declaration");
      }
      char quote = *q++;
      const char* v0 = q;
      while (q < e && *q != quote) ++q;
      if (q == e) return fail("malformed XML declaration");
      std::string val(v0, q - v0);
      ++q;
      if (which == 0) {
        if (val.size() < 3 || val.compare(0, 2, "1.") != 0) {
          return fail("malformed XML declaration");
        }
        for (size_t i = 2; i < val.size(); ++i) {
          if (val[i] < '0' || val[i] > '9') {
            return fail("malformed XML declaration");
          }
        }
      } else if (which == 1) {
        if (val.empty()) return fail("malformed XML declaration");
        for (char c : val) {
          if (!((c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z') ||
                (c >= '0' && c <= '9') || c == '.' || c == '_' ||
                c == '-')) {
            return fail("malformed XML declaration");
          }
        }
      } else if (val != "yes" && val != "no") {
        return fail("malformed XML declaration");
      }
    }
    if (next_allowed == 0) return fail("malformed XML declaration");
    return true;
  }

  // Text between tags: outside the root only whitespace is allowed;
  // inside, entity references must be valid (content itself is
  // discarded — GEXF carries data in attributes).
  bool check_text(const char* s, const char* e) {
    if (open_stack.empty()) {
      for (const char* q = s; q < e; ++q) {
        if (!is_space(*q)) {
          return fail(seen_root ? "junk after document element"
                                : "text before document element");
        }
      }
      return true;
    }
    std::string err;
    if (!decode_entities_strict(s, e - s, nullptr, &err)) {
      return fail_str(err + " in text");
    }
    return true;
  }

  static bool is_space(char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r';
  }
  // XML NameChar (ASCII range; ≥0x80 allowed through as in
  // is_name_start). Anything looser lets corrupted names like
  // "sou&rce" parse as names expat rejects.
  static bool is_name_char(char ch) {
    unsigned char c = static_cast<unsigned char>(ch);
    return (c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z') ||
           (c >= '0' && c <= '9') || c == '-' || c == '.' || c == '_' ||
           c == ':' || c >= 0x80;
  }
  // XML NameStartChar, ASCII range (multi-byte UTF-8 leads are allowed
  // through — the document-level scan guarantees they are valid
  // sequences, and non-ASCII element names don't occur in GEXF).
  static bool is_name_start(unsigned char c) {
    return (c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z') || c == '_' ||
           c == ':' || c >= 0x80;
  }

  bool parse_tag(Tag* tag) {
    tag->attrs.clear();
    tag->closing = tag->self_closing = false;
    if (p < end && *p == '/') {
      tag->closing = true;
      ++p;
    }
    const char* start = p;
    while (p < end && is_name_char(*p)) ++p;
    if (p == start || !is_name_start(static_cast<unsigned char>(*start))) {
      return fail("malformed tag name");
    }
    tag->raw_name.assign(start, p - start);
    tag->name = local_name(tag->raw_name);
    // attributes
    while (p < end) {
      while (p < end && is_space(*p)) ++p;
      if (p >= end) return fail("unterminated tag");
      if (*p == '>') {
        ++p;
        return true;
      }
      if (*p == '/') {
        if (tag->closing) return fail("malformed closing tag");
        ++p;
        if (p < end && *p == '>') {
          ++p;
          tag->self_closing = true;
          return true;
        }
        return fail("stray '/' in tag");
      }
      if (tag->closing) return fail("attribute on closing tag");
      const char* astart = p;
      while (p < end && is_name_char(*p)) ++p;
      if (p == astart ||
          !is_name_start(static_cast<unsigned char>(*astart))) {
        return fail("malformed attribute name");
      }
      std::string aname = local_name(std::string(astart, p - astart));
      while (p < end && is_space(*p)) ++p;
      if (p >= end || *p != '=') return fail("attribute without value");
      ++p;
      while (p < end && is_space(*p)) ++p;
      if (p >= end || (*p != '"' && *p != '\'')) return fail("unquoted attribute");
      char quote = *p++;
      const char* vstart = p;
      const char* vend =
          static_cast<const char*>(memchr(p, quote, end - p));
      if (!vend) return fail("unterminated attribute value");
      if (memchr(vstart, '<', vend - vstart)) {
        return fail("'<' in attribute value");
      }
      p = vend + 1;
      if (p < end && !is_space(*p) && *p != '>' && *p != '/') {
        return fail("missing whitespace between attributes");
      }
      std::string decoded, err;
      if (!decode_entities_strict(vstart, vend - vstart, &decoded, &err)) {
        return fail_str(err + " in attribute value");
      }
      for (const auto& a : tag->attrs) {
        if (a.name == aname) return fail("duplicate attribute");
      }
      tag->attrs.push_back({std::move(aname), std::move(decoded)});
    }
    return fail("unterminated tag");
  }
};

struct Gexf {
  std::string nodes_blob;  // id\0label\0type\0 ...
  std::string edges_blob;  // src\0dst\0rel\0 ...
  std::string graph_name;
  long num_nodes = 0;
  long num_edges = 0;
  std::string error;
};

void append3(std::string* blob, const std::string& a, const std::string& b,
             const std::string& c) {
  blob->append(a);
  blob->push_back('\0');
  blob->append(b);
  blob->push_back('\0');
  blob->append(c);
  blob->push_back('\0');
}

}  // namespace

extern "C" {

Gexf* gexf_parse(const char* path) {
  auto* g = new Gexf();
  FILE* f = fopen(path, "rb");
  if (!f) {
    g->error = std::string("cannot open ") + path;
    return g;
  }
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string data(static_cast<size_t>(size), '\0');
  if (size > 0 && fread(&data[0], 1, size, f) != static_cast<size_t>(size)) {
    fclose(f);
    g->error = "short read";
    return g;
  }
  fclose(f);

  if (!validate_document(data, &g->error)) return g;

  // A UTF-8 BOM is legal before the XML declaration — skip it so the
  // declaration still counts as "at start of document".
  const char* doc = data.data();
  size_t doc_len = data.size();
  if (doc_len >= 3 && memcmp(doc, "\xEF\xBB\xBF", 3) == 0) {
    doc += 3;
    doc_len -= 3;
  }
  Parser parser(doc, doc_len);
  Tag tag;

  // attribute-id → title maps, per declaration class
  std::unordered_map<std::string, std::string> node_titles, edge_titles;
  std::string cur_attr_class;

  struct EdgeRec {
    std::string src, dst, rel;
  };
  std::vector<EdgeRec> edges;
  std::unordered_map<std::string, size_t> edge_pos;  // "src\0dst" → index

  // current open element being filled (node or edge)
  enum class Open { None, Node, Edge } open = Open::None;
  std::string cur_id, cur_label, cur_type;  // node fields
  bool cur_label_present = false;  // label="" is kept, absent falls back to id
  EdgeRec cur_edge;

  auto flush_node = [&]() {
    append3(&g->nodes_blob, cur_id, cur_label_present ? cur_label : cur_id,
            cur_type);
    ++g->num_nodes;
  };
  auto flush_edge = [&]() {
    std::string key = cur_edge.src + '\0' + cur_edge.dst;
    auto it = edge_pos.find(key);
    if (it == edge_pos.end()) {
      edge_pos.emplace(std::move(key), edges.size());
      edges.push_back(cur_edge);
    } else {
      edges[it->second].rel = cur_edge.rel;  // last relationship wins
    }
  };

  while (parser.next_tag(&tag)) {
    if (!tag.closing) {
      if (tag.name == "graph") {
        const char* nm = attr_of(tag, "name");
        g->graph_name = nm ? nm : "";
      } else if (tag.name == "attributes") {
        const char* cls = attr_of(tag, "class");
        cur_attr_class = cls ? cls : "";
      } else if (tag.name == "attribute" && !cur_attr_class.empty()) {
        const char* id = attr_of(tag, "id");
        const char* title = attr_of(tag, "title");
        auto& titles = cur_attr_class == "node" ? node_titles : edge_titles;
        titles[id ? id : ""] = title ? title : "";
        if (tag.self_closing) continue;
      } else if (tag.name == "node") {
        const char* id = attr_of(tag, "id");
        const char* label = attr_of(tag, "label");
        cur_id = id ? id : "";
        cur_label = label ? label : "";
        cur_label_present = label != nullptr;
        cur_type.clear();
        if (tag.self_closing) {
          flush_node();
        } else {
          open = Open::Node;
        }
      } else if (tag.name == "edge") {
        const char* src = attr_of(tag, "source");
        const char* dst = attr_of(tag, "target");
        const char* label = attr_of(tag, "label");
        cur_edge = {src ? src : "", dst ? dst : "", label ? label : ""};
        if (tag.self_closing) {
          flush_edge();
        } else {
          open = Open::Edge;
        }
      } else if (tag.name == "attvalue") {
        std::string for_id = attr_of(tag, "for") ? attr_of(tag, "for") : "";
        const char* value = attr_of(tag, "value");
        // Undeclared attribute ids fall back to the id itself as the
        // title, and repeated attvalues overwrite (dict semantics) —
        // both matching the Python parser's titles.get(id, id).
        if (open == Open::Node) {
          auto it = node_titles.find(for_id);
          const std::string& title =
              it != node_titles.end() ? it->second : for_id;
          if (title == "node_type") cur_type = value ? value : "";
        } else if (open == Open::Edge) {
          auto it = edge_titles.find(for_id);
          const std::string& title =
              it != edge_titles.end() ? it->second : for_id;
          if (title == "label") cur_edge.rel = value ? value : "";
        }
      }
    } else {  // closing tag
      if (tag.name == "node" && open == Open::Node) {
        flush_node();
        open = Open::None;
      } else if (tag.name == "edge" && open == Open::Edge) {
        flush_edge();
        open = Open::None;
      } else if (tag.name == "attributes") {
        cur_attr_class.clear();
      }
    }
  }

  if (!parser.error.empty()) {
    g->error = parser.error;
    return g;
  }
  if (!parser.eof_ok()) {
    g->error = parser.seen_root
                   ? "truncated document (unclosed elements at EOF)"
                   : "no document element";
    return g;
  }
  for (const auto& e : edges) append3(&g->edges_blob, e.src, e.dst, e.rel);
  g->num_edges = static_cast<long>(edges.size());
  return g;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Encoded view: the native twin of data/encode.encode_hin + infer_schema.
//
// Marshalling strings for millions of nodes/edges costs more than the
// parse itself (measured: the blob→Vertex/Edge path is SLOWER than pure
// Python at 2M nodes), so the hot path never builds per-edge Python
// objects: types and relationships are interned here, edge endpoints
// are resolved to dense per-type indices natively, and Python receives
// int32 COO arrays plus one id\0label\0 blob per node type.
//
// Semantics mirrored exactly (see data/encode.py, data/schema.py):
//   - node types in first-appearance vertex order; per-type node index
//     in document order; duplicate node ids: every occurrence gets an
//     index, LAST occurrence wins for edge resolution (dict overwrite)
//   - relationship signatures inferred from endpoints; mixed signatures
//     rejected; missing endpoints rejected (same messages)
//   - blocks keyed per relationship in first-appearance (deduped) edge
//     order; COO entries in edge order
// ---------------------------------------------------------------------------

struct GexfEncoded {
  std::string type_names_blob;            // type\0 per type
  std::vector<long> type_counts;          // nodes per type
  std::string nodes_blob;                 // per type: id\0label\0 ...
  std::vector<long> node_blob_offsets;    // n_types+1 byte offsets
  std::string rel_names_blob;             // rel\0 per relationship
  std::vector<int> rel_types;             // 2*n_rels: src,dst type idx
  std::vector<long> rel_offsets;          // n_rels+1 entry offsets
  std::vector<int> rows, cols;            // concatenated COO
  std::string error;
};

extern "C" {

GexfEncoded* gexf_encode(Gexf* g) {
  auto* e = new GexfEncoded();
  if (!g->error.empty()) {
    e->error = g->error;
    return e;
  }
  // Walk the nodes blob once: intern types, assign per-type indices.
  std::unordered_map<std::string, int> type_idx;
  std::vector<std::string> type_names;
  std::vector<std::string> per_type_blob;
  // id → (type, within-type index); overwrite = last occurrence wins.
  std::unordered_map<std::string, std::pair<int, int>> node_of;
  node_of.reserve(static_cast<size_t>(g->num_nodes) * 2);
  {
    const char* p = g->nodes_blob.data();
    const char* end = p + g->nodes_blob.size();
    while (p < end) {
      const char* id = p;
      size_t idl = strlen(p);
      p += idl + 1;
      const char* label = p;
      size_t labell = strlen(p);
      p += labell + 1;
      std::string type(p);
      p += type.size() + 1;
      auto it = type_idx.find(type);
      int t;
      if (it == type_idx.end()) {
        t = static_cast<int>(type_names.size());
        type_idx.emplace(type, t);
        type_names.push_back(type);
        per_type_blob.emplace_back();
        e->type_counts.push_back(0);
      } else {
        t = it->second;
      }
      int within = static_cast<int>(e->type_counts[t]++);
      auto& blob = per_type_blob[t];
      blob.append(id, idl);
      blob.push_back('\0');
      blob.append(label, labell);
      blob.push_back('\0');
      node_of[std::string(id, idl)] = {t, within};
    }
  }
  e->node_blob_offsets.push_back(0);
  for (size_t t = 0; t < per_type_blob.size(); ++t) {
    e->nodes_blob += per_type_blob[t];
    e->node_blob_offsets.push_back(static_cast<long>(e->nodes_blob.size()));
    e->type_names_blob += type_names[t];
    e->type_names_blob.push_back('\0');
  }

  // Walk the edges blob: infer relationship signatures, resolve COO.
  std::unordered_map<std::string, int> rel_idx;
  std::vector<std::vector<int>> rel_rows, rel_cols;
  {
    const char* p = g->edges_blob.data();
    const char* end = p + g->edges_blob.size();
    while (p < end) {
      std::string src(p);
      p += src.size() + 1;
      std::string dst(p);
      p += dst.size() + 1;
      std::string rel(p);
      p += rel.size() + 1;
      auto si = node_of.find(src);
      auto di = node_of.find(dst);
      if (si == node_of.end() || di == node_of.end()) {
        e->error = "edge endpoint '" +
                   (si == node_of.end() ? src : dst) +
                   "' has no vertex entry";
        return e;
      }
      auto it = rel_idx.find(rel);
      int r;
      if (it == rel_idx.end()) {
        r = static_cast<int>(rel_rows.size());
        rel_idx.emplace(rel, r);
        rel_rows.emplace_back();
        rel_cols.emplace_back();
        e->rel_names_blob += rel;
        e->rel_names_blob.push_back('\0');
        e->rel_types.push_back(si->second.first);
        e->rel_types.push_back(di->second.first);
      } else {
        r = it->second;
        if (e->rel_types[2 * r] != si->second.first ||
            e->rel_types[2 * r + 1] != di->second.first) {
          e->error = "relationship '" + rel + "' has mixed signatures";
          return e;
        }
      }
      rel_rows[r].push_back(si->second.second);
      rel_cols[r].push_back(di->second.second);
    }
  }
  e->rel_offsets.push_back(0);
  for (size_t r = 0; r < rel_rows.size(); ++r) {
    e->rows.insert(e->rows.end(), rel_rows[r].begin(), rel_rows[r].end());
    e->cols.insert(e->cols.end(), rel_cols[r].begin(), rel_cols[r].end());
    e->rel_offsets.push_back(static_cast<long>(e->rows.size()));
  }
  return e;
}

long genc_num_types(GexfEncoded* e) {
  return static_cast<long>(e->type_counts.size());
}
const char* genc_type_names(GexfEncoded* e, long* len) {
  *len = static_cast<long>(e->type_names_blob.size());
  return e->type_names_blob.data();
}
const long* genc_type_counts(GexfEncoded* e) { return e->type_counts.data(); }
const char* genc_nodes_blob(GexfEncoded* e, long* len) {
  *len = static_cast<long>(e->nodes_blob.size());
  return e->nodes_blob.data();
}
const long* genc_node_offsets(GexfEncoded* e) {
  return e->node_blob_offsets.data();
}
long genc_num_rels(GexfEncoded* e) {
  return static_cast<long>(e->rel_offsets.size()) - 1;
}
const char* genc_rel_names(GexfEncoded* e, long* len) {
  *len = static_cast<long>(e->rel_names_blob.size());
  return e->rel_names_blob.data();
}
const int* genc_rel_types(GexfEncoded* e) { return e->rel_types.data(); }
const long* genc_rel_offsets(GexfEncoded* e) { return e->rel_offsets.data(); }
const int* genc_rows(GexfEncoded* e) { return e->rows.data(); }
const int* genc_cols(GexfEncoded* e) { return e->cols.data(); }
const char* genc_error(GexfEncoded* e) {
  return e->error.empty() ? nullptr : e->error.c_str();
}
void genc_free(GexfEncoded* e) { delete e; }

}  // extern "C"

extern "C" {

long gexf_num_nodes(Gexf* g) { return g->num_nodes; }
long gexf_num_edges(Gexf* g) { return g->num_edges; }

const char* gexf_nodes_blob(Gexf* g, long* len) {
  *len = static_cast<long>(g->nodes_blob.size());
  return g->nodes_blob.data();
}
const char* gexf_edges_blob(Gexf* g, long* len) {
  *len = static_cast<long>(g->edges_blob.size());
  return g->edges_blob.data();
}
const char* gexf_graph_name(Gexf* g) { return g->graph_name.c_str(); }

const char* gexf_error(Gexf* g) {
  return g->error.empty() ? nullptr : g->error.c_str();
}
void gexf_free(Gexf* g) { delete g; }

}  // extern "C"
