"""ctypes bridge to the C++ GEXF parser (gexf_fast.cpp).

Same output as data/gexf.py's Python parser — document order, dedup and
attvalue semantics included — but a single native pass over the file.
Falls back cleanly (available() → False) when the toolchain is missing.
"""

from __future__ import annotations

import ctypes

from ..data.schema import Edge, HINGraph, Vertex
from .build import shared_lib

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = shared_lib("gexf_fast")
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    lib.gexf_parse.restype = ctypes.c_void_p
    lib.gexf_parse.argtypes = [ctypes.c_char_p]
    lib.gexf_num_nodes.restype = ctypes.c_long
    lib.gexf_num_nodes.argtypes = [ctypes.c_void_p]
    lib.gexf_num_edges.restype = ctypes.c_long
    lib.gexf_num_edges.argtypes = [ctypes.c_void_p]
    lib.gexf_nodes_blob.restype = ctypes.POINTER(ctypes.c_char)
    lib.gexf_nodes_blob.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_long)]
    lib.gexf_edges_blob.restype = ctypes.POINTER(ctypes.c_char)
    lib.gexf_edges_blob.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_long)]
    lib.gexf_graph_name.restype = ctypes.c_char_p
    lib.gexf_graph_name.argtypes = [ctypes.c_void_p]
    lib.gexf_error.restype = ctypes.c_char_p
    lib.gexf_error.argtypes = [ctypes.c_void_p]
    lib.gexf_free.restype = None
    lib.gexf_free.argtypes = [ctypes.c_void_p]
    # encoded view
    lib.gexf_encode.restype = ctypes.c_void_p
    lib.gexf_encode.argtypes = [ctypes.c_void_p]
    lib.genc_num_types.restype = ctypes.c_long
    lib.genc_num_types.argtypes = [ctypes.c_void_p]
    lib.genc_type_names.restype = ctypes.POINTER(ctypes.c_char)
    lib.genc_type_names.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_long)]
    lib.genc_type_counts.restype = ctypes.POINTER(ctypes.c_long)
    lib.genc_type_counts.argtypes = [ctypes.c_void_p]
    lib.genc_nodes_blob.restype = ctypes.POINTER(ctypes.c_char)
    lib.genc_nodes_blob.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_long)]
    lib.genc_node_offsets.restype = ctypes.POINTER(ctypes.c_long)
    lib.genc_node_offsets.argtypes = [ctypes.c_void_p]
    lib.genc_num_rels.restype = ctypes.c_long
    lib.genc_num_rels.argtypes = [ctypes.c_void_p]
    lib.genc_rel_names.restype = ctypes.POINTER(ctypes.c_char)
    lib.genc_rel_names.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_long)]
    lib.genc_rel_types.restype = ctypes.POINTER(ctypes.c_int)
    lib.genc_rel_types.argtypes = [ctypes.c_void_p]
    lib.genc_rel_offsets.restype = ctypes.POINTER(ctypes.c_long)
    lib.genc_rel_offsets.argtypes = [ctypes.c_void_p]
    lib.genc_rows.restype = ctypes.POINTER(ctypes.c_int)
    lib.genc_rows.argtypes = [ctypes.c_void_p]
    lib.genc_cols.restype = ctypes.POINTER(ctypes.c_int)
    lib.genc_cols.argtypes = [ctypes.c_void_p]
    lib.genc_error.restype = ctypes.c_char_p
    lib.genc_error.argtypes = [ctypes.c_void_p]
    lib.genc_free.restype = None
    lib.genc_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def read_gexf(path: str) -> HINGraph:
    lib = _load()
    if lib is None:
        raise RuntimeError("native GEXF parser unavailable")
    handle = lib.gexf_parse(path.encode())
    try:
        err = lib.gexf_error(handle)
        if err:
            raise ValueError(f"GEXF parse error: {err.decode()}")
        n_nodes = lib.gexf_num_nodes(handle)
        n_edges = lib.gexf_num_edges(handle)
        graph_name = (lib.gexf_graph_name(handle) or b"").decode("utf-8")

        length = ctypes.c_long()
        buf = lib.gexf_nodes_blob(handle, ctypes.byref(length))
        node_fields = (
            ctypes.string_at(buf, length.value).decode("utf-8").split("\0")
            if length.value
            else []
        )
        buf = lib.gexf_edges_blob(handle, ctypes.byref(length))
        edge_fields = (
            ctypes.string_at(buf, length.value).decode("utf-8").split("\0")
            if length.value
            else []
        )
    finally:
        lib.gexf_free(handle)

    # blobs end with a trailing NUL → drop the final empty split
    if node_fields and node_fields[-1] == "":
        node_fields.pop()
    if edge_fields and edge_fields[-1] == "":
        edge_fields.pop()
    if len(node_fields) != 3 * n_nodes or len(edge_fields) != 3 * n_edges:
        raise ValueError("native GEXF parser returned inconsistent blobs")

    vertices = [
        Vertex(id=node_fields[i], label=node_fields[i + 1], node_type=node_fields[i + 2])
        for i in range(0, len(node_fields), 3)
    ]
    edges = [
        Edge(src=edge_fields[i], dst=edge_fields[i + 1], relationship=edge_fields[i + 2])
        for i in range(0, len(edge_fields), 3)
    ]
    return HINGraph(vertices=vertices, edges=edges, name=graph_name)


def read_gexf_encoded(path: str):
    """Parse AND encode natively: GEXF file → :class:`EncodedHIN` with
    no per-edge Python objects ever created.

    Equivalent to ``encode_hin(read_gexf(path))`` (same type/relationship
    order, same per-type document-order indices, same duplicate-id and
    mixed-signature semantics — tested against it), but edge endpoints
    are resolved to dense int32 COO in C++. At dblp_large scale the
    Python-object marshalling dominates the pure-parse path, so this is
    the loader the engine uses for big files.
    """
    import numpy as np

    from ..data.encode import AdjacencyBlock, EncodedHIN, TypeIndex
    from ..data.schema import HINSchema

    lib = _load()
    if lib is None:
        raise RuntimeError("native GEXF parser unavailable")
    handle = lib.gexf_parse(path.encode())
    enc = None
    try:
        err = lib.gexf_error(handle)
        if err:
            raise ValueError(f"GEXF parse error: {err.decode()}")
        graph_name = (lib.gexf_graph_name(handle) or b"").decode("utf-8")
        enc = lib.gexf_encode(handle)
        err = lib.genc_error(enc)
        if err:
            raise ValueError(err.decode())

        n_types = lib.genc_num_types(enc)
        length = ctypes.c_long()
        buf = lib.genc_type_names(enc, ctypes.byref(length))
        type_names = (
            ctypes.string_at(buf, length.value).decode("utf-8").split("\0")[:-1]
            if length.value else []
        )
        counts = lib.genc_type_counts(enc)[:n_types] if n_types else []
        offsets = lib.genc_node_offsets(enc)[: n_types + 1]
        buf = lib.genc_nodes_blob(enc, ctypes.byref(length))
        nodes_raw = ctypes.string_at(buf, length.value) if length.value else b""

        indices: dict[str, TypeIndex] = {}
        for t, tname in enumerate(type_names):
            section = nodes_raw[offsets[t]:offsets[t + 1]]
            fields = section.decode("utf-8").split("\0")
            if fields and fields[-1] == "":
                fields.pop()
            assert len(fields) == 2 * counts[t], "inconsistent node section"
            ids = tuple(fields[0::2])
            labels = tuple(fields[1::2])
            indices[tname] = TypeIndex(
                node_type=tname,
                ids=ids,
                labels=labels,
                index_of={s: i for i, s in enumerate(ids)},
            )

        n_rels = lib.genc_num_rels(enc)
        buf = lib.genc_rel_names(enc, ctypes.byref(length))
        rel_names = (
            ctypes.string_at(buf, length.value).decode("utf-8").split("\0")[:-1]
            if length.value else []
        )
        rel_types = lib.genc_rel_types(enc)[: 2 * n_rels] if n_rels else []
        rel_offsets = lib.genc_rel_offsets(enc)[: n_rels + 1]
        total = rel_offsets[n_rels] if n_rels else 0
        if total:
            rows_all = np.ctypeslib.as_array(lib.genc_rows(enc), shape=(total,))
            cols_all = np.ctypeslib.as_array(lib.genc_cols(enc), shape=(total,))
        else:  # zero edges: vector data() is NULL, as_array would raise
            rows_all = cols_all = np.empty(0, dtype=np.int32)

        relations: dict[str, tuple[str, str]] = {}
        blocks: dict[str, AdjacencyBlock] = {}
        for r, rel in enumerate(rel_names):
            src_t = type_names[rel_types[2 * r]]
            dst_t = type_names[rel_types[2 * r + 1]]
            relations[rel] = (src_t, dst_t)
            lo, hi = rel_offsets[r], rel_offsets[r + 1]
            blocks[rel] = AdjacencyBlock(
                relationship=rel,
                src_type=src_t,
                dst_type=dst_t,
                # copy out: the backing buffer dies with genc_free
                rows=np.array(rows_all[lo:hi], dtype=np.int32),
                cols=np.array(cols_all[lo:hi], dtype=np.int32),
                shape=(indices[src_t].size, indices[dst_t].size),
            )
        schema = HINSchema(node_types=tuple(type_names), relations=relations)
        return EncodedHIN(
            schema=schema, indices=indices, blocks=blocks, name=graph_name
        )
    finally:
        if enc is not None:
            lib.genc_free(enc)
        lib.gexf_free(handle)
