"""ctypes bridge to the C++ GEXF parser (built lazily from native/).

Falls back cleanly when the shared library can't be built; see
native/gexf_fast.cpp. For now this is a stub that reports unavailable —
the build hook lands with the native milestone.
"""

from __future__ import annotations


def available() -> bool:
    return False


def read_gexf(path: str):
    raise NotImplementedError("native GEXF parser not built")
