"""ctypes bridge to the C++ GEXF parser (gexf_fast.cpp).

Same output as data/gexf.py's Python parser — document order, dedup and
attvalue semantics included — but a single native pass over the file.
Falls back cleanly (available() → False) when the toolchain is missing.
"""

from __future__ import annotations

import ctypes

from ..data.schema import Edge, HINGraph, Vertex
from .build import shared_lib

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = shared_lib("gexf_fast")
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    lib.gexf_parse.restype = ctypes.c_void_p
    lib.gexf_parse.argtypes = [ctypes.c_char_p]
    lib.gexf_num_nodes.restype = ctypes.c_long
    lib.gexf_num_nodes.argtypes = [ctypes.c_void_p]
    lib.gexf_num_edges.restype = ctypes.c_long
    lib.gexf_num_edges.argtypes = [ctypes.c_void_p]
    lib.gexf_nodes_blob.restype = ctypes.POINTER(ctypes.c_char)
    lib.gexf_nodes_blob.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_long)]
    lib.gexf_edges_blob.restype = ctypes.POINTER(ctypes.c_char)
    lib.gexf_edges_blob.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_long)]
    lib.gexf_graph_name.restype = ctypes.c_char_p
    lib.gexf_graph_name.argtypes = [ctypes.c_void_p]
    lib.gexf_error.restype = ctypes.c_char_p
    lib.gexf_error.argtypes = [ctypes.c_void_p]
    lib.gexf_free.restype = None
    lib.gexf_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def read_gexf(path: str) -> HINGraph:
    lib = _load()
    if lib is None:
        raise RuntimeError("native GEXF parser unavailable")
    handle = lib.gexf_parse(path.encode())
    try:
        err = lib.gexf_error(handle)
        if err:
            raise ValueError(f"GEXF parse error: {err.decode()}")
        n_nodes = lib.gexf_num_nodes(handle)
        n_edges = lib.gexf_num_edges(handle)
        graph_name = (lib.gexf_graph_name(handle) or b"").decode("utf-8")

        length = ctypes.c_long()
        buf = lib.gexf_nodes_blob(handle, ctypes.byref(length))
        node_fields = (
            ctypes.string_at(buf, length.value).decode("utf-8").split("\0")
            if length.value
            else []
        )
        buf = lib.gexf_edges_blob(handle, ctypes.byref(length))
        edge_fields = (
            ctypes.string_at(buf, length.value).decode("utf-8").split("\0")
            if length.value
            else []
        )
    finally:
        lib.gexf_free(handle)

    # blobs end with a trailing NUL → drop the final empty split
    if node_fields and node_fields[-1] == "":
        node_fields.pop()
    if edge_fields and edge_fields[-1] == "":
        edge_fields.pop()
    if len(node_fields) != 3 * n_nodes or len(edge_fields) != 3 * n_edges:
        raise ValueError("native GEXF parser returned inconsistent blobs")

    vertices = [
        Vertex(id=node_fields[i], label=node_fields[i + 1], node_type=node_fields[i + 2])
        for i in range(0, len(node_fields), 3)
    ]
    edges = [
        Edge(src=edge_fields[i], dst=edge_fields[i + 1], relationship=edge_fields[i + 2])
        for i in range(0, len(edge_fields), 3)
    ]
    return HINGraph(vertices=vertices, edges=edges, name=graph_name)
