// Native COO sparse-sparse product with coalescing — the host-side
// structural join of the half-chain fold (ops/sparse.py coo_matmul +
// summed), done in one C++ pass.
//
// This is the TPU framework's replacement for the reference's
// distributed 4-way motif join (DPathSim_APVPA.py:72-84): the join
// structure is computed ONCE on the host, here, and the arithmetic runs
// on device. At million-author scale this call dominates host time, so
// it gets the native treatment alongside the GEXF parser.
//
// C ABI, handle-based like gexf_fast.cpp: callers get an opaque result
// handle, read nnz, copy out flat arrays, free. Output is coalesced and
// sorted row-major — byte-identical ordering to the numpy path
// (np.unique over row*ncols+col yields ascending keys).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct CooResult {
  std::vector<int64_t> rows;
  std::vector<int64_t> cols;
  std::vector<double> weights;
  std::string error;
};

}  // namespace

extern "C" {

// a: [nnz_a] COO triplets of an (M x K) matrix.
// b: [nnz_b] COO triplets of a (K x N) matrix; b_nrows = K, b_ncols = N.
// Returns an opaque CooResult* (never null); check coo_error() first.
void* coo_spgemm(const int64_t* a_rows, const int64_t* a_cols,
                 const double* a_w, int64_t nnz_a, const int64_t* b_rows,
                 const int64_t* b_cols, const double* b_w, int64_t nnz_b,
                 int64_t b_nrows, int64_t b_ncols) {
  auto* res = new CooResult();
  if (b_nrows < 0 || b_ncols <= 0) {
    res->error = "coo_spgemm: bad b shape";
    return res;
  }
  // CSR index of b by row (counting sort — rows are dense indices).
  std::vector<int64_t> row_start(static_cast<size_t>(b_nrows) + 1, 0);
  for (int64_t i = 0; i < nnz_b; ++i) {
    int64_t r = b_rows[i];
    if (r < 0 || r >= b_nrows) {
      res->error = "coo_spgemm: b row index out of range";
      return res;
    }
    ++row_start[static_cast<size_t>(r) + 1];
  }
  for (int64_t r = 0; r < b_nrows; ++r) row_start[r + 1] += row_start[r];
  std::vector<int64_t> b_col_sorted(nnz_b);
  std::vector<double> b_w_sorted(nnz_b);
  {
    std::vector<int64_t> fill(row_start.begin(), row_start.end() - 1);
    for (int64_t i = 0; i < nnz_b; ++i) {
      int64_t pos = fill[b_rows[i]]++;
      b_col_sorted[pos] = b_cols[i];
      b_w_sorted[pos] = b_w[i];
    }
  }
  // Join + accumulate. Key = row * b_ncols + col (row-major), matching
  // the numpy coalesce; counts are small integers so the accumulation
  // order cannot change the f64 result.
  std::unordered_map<uint64_t, double> acc;
  acc.reserve(static_cast<size_t>(nnz_a));
  const uint64_t ncols = static_cast<uint64_t>(b_ncols);
  for (int64_t i = 0; i < nnz_a; ++i) {
    int64_t mid = a_cols[i];
    if (mid < 0 || mid >= b_nrows) {
      res->error = "coo_spgemm: a col index out of range";
      return res;
    }
    const double aw = a_w[i];
    const uint64_t base = static_cast<uint64_t>(a_rows[i]) * ncols;
    for (int64_t p = row_start[mid]; p < row_start[mid + 1]; ++p) {
      acc[base + static_cast<uint64_t>(b_col_sorted[p])] += aw * b_w_sorted[p];
    }
  }
  // Extract sorted row-major for a deterministic, numpy-identical order.
  std::vector<std::pair<uint64_t, double>> entries(acc.begin(), acc.end());
  std::sort(entries.begin(), entries.end(),
            [](const auto& x, const auto& y) { return x.first < y.first; });
  res->rows.reserve(entries.size());
  res->cols.reserve(entries.size());
  res->weights.reserve(entries.size());
  for (const auto& [k, w] : entries) {
    res->rows.push_back(static_cast<int64_t>(k / ncols));
    res->cols.push_back(static_cast<int64_t>(k % ncols));
    res->weights.push_back(w);
  }
  return res;
}

const char* coo_error(void* h) {
  auto* res = static_cast<CooResult*>(h);
  return res->error.empty() ? nullptr : res->error.c_str();
}

int64_t coo_result_nnz(void* h) {
  return static_cast<int64_t>(static_cast<CooResult*>(h)->rows.size());
}

void coo_result_fill(void* h, int64_t* rows, int64_t* cols, double* w) {
  auto* res = static_cast<CooResult*>(h);
  const size_t n = res->rows.size();
  std::memcpy(rows, res->rows.data(), n * sizeof(int64_t));
  std::memcpy(cols, res->cols.data(), n * sizeof(int64_t));
  std::memcpy(w, res->weights.data(), n * sizeof(double));
}

void coo_free(void* h) { delete static_cast<CooResult*>(h); }

}  // extern "C"
