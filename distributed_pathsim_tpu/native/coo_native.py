"""ctypes bridge to the C++ COO SpGEMM (coo_fast.cpp).

Drop-in for ``ops.sparse.coo_matmul(a, b).summed()`` — identical output
(coalesced, row-major sorted; integer-weight accumulation is exact in
f64 regardless of order). Falls back cleanly (available() → False) when
the toolchain is missing.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .build import shared_lib

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = shared_lib("coo_fast")
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    lib.coo_spgemm.restype = ctypes.c_void_p
    lib.coo_spgemm.argtypes = [
        i64p, i64p, f64p, ctypes.c_int64,
        i64p, i64p, f64p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64,
    ]
    lib.coo_error.restype = ctypes.c_char_p
    lib.coo_error.argtypes = [ctypes.c_void_p]
    lib.coo_result_nnz.restype = ctypes.c_int64
    lib.coo_result_nnz.argtypes = [ctypes.c_void_p]
    lib.coo_result_fill.restype = None
    lib.coo_result_fill.argtypes = [ctypes.c_void_p, i64p, i64p, f64p]
    lib.coo_free.restype = None
    lib.coo_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def _as_i64(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int64)


def _as_f64(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float64)


def coo_matmul_summed(a, b):
    """(a @ b) coalesced, as a new COOMatrix. a: (M,K), b: (K,N)."""
    from ..ops.sparse import COOMatrix

    if a.shape[1] != b.shape[0]:  # same guard as the numpy coo_matmul
        raise ValueError(f"shape mismatch {a.shape} @ {b.shape}")
    lib = _load()
    if lib is None:
        raise RuntimeError("native coo library unavailable")
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    ar, ac, aw = _as_i64(a.rows), _as_i64(a.cols), _as_f64(a.weights)
    br, bc, bw = _as_i64(b.rows), _as_i64(b.cols), _as_f64(b.weights)
    h = lib.coo_spgemm(
        ar.ctypes.data_as(i64p), ac.ctypes.data_as(i64p),
        aw.ctypes.data_as(f64p), len(ar),
        br.ctypes.data_as(i64p), bc.ctypes.data_as(i64p),
        bw.ctypes.data_as(f64p), len(br),
        b.shape[0], b.shape[1],
    )
    try:
        err = lib.coo_error(h)
        if err:
            raise ValueError(err.decode())
        nnz = lib.coo_result_nnz(h)
        rows = np.empty(nnz, dtype=np.int64)
        cols = np.empty(nnz, dtype=np.int64)
        weights = np.empty(nnz, dtype=np.float64)
        if nnz:
            lib.coo_result_fill(
                h,
                rows.ctypes.data_as(i64p),
                cols.ctypes.data_as(i64p),
                weights.ctypes.data_as(f64p),
            )
    finally:
        lib.coo_free(h)
    return COOMatrix(
        rows=rows, cols=cols, weights=weights, shape=(a.shape[0], b.shape[1])
    )
