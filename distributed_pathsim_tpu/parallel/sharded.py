"""Row-sharded commuting-matrix chain under shard_map.

SPMD design (BASELINE.json config 3): the first block of the chain (the
source-type × next-type adjacency, e.g. ``A_AP``) is sharded along its
rows over the ``dp`` mesh axis; the remaining (small, contracted) blocks
are replicated. Each device computes its row-block of the half-chain
``C = A_AP @ A_PV`` locally; then

- global column total  (Σ_x C[x, :]):  local colsum + ``psum`` over dp —
  this is the ONLY cross-device reduction the row sums need
- row sums:  ``C_local @ colsum_total``       (no communication)
- all-pairs M row-block:  ``C_local @ C_fullᵀ`` where ``C_full`` comes
  from ``all_gather`` (moderate N), or from a ``ppermute`` ring that
  streams peer blocks through ICI without ever holding all of M or all
  of C (large N — the ring-attention communication pattern applied to
  the author axis; see parallel/ring.py)

Padding: the row axis is padded to a device multiple with all-zero rows;
zero rows of ``A_AP`` produce zero rows of C and M and contribute zero to
every ``psum`` — tested, not assumed (tests/test_sharded.py).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.compat import shard_map
from .mesh import pad_to_multiple
from .ring import ring_allpairs_rowblock, ring_topk_rowblock


# all_gather materializes every peer's C block on every device — fast
# (one fused collective, maximal overlap) until the gathered [N_pad, V]
# factor starts to crowd HBM; the ppermute ring keeps only 2 peer
# blocks live at any time at the cost of D-1 dependent steps. Crossover
# measured on the virtual mesh (SHARDED_SCALING_r03.json): allgather
# wins at every size that fits; the ring exists for the sizes that
# don't. Budget: gathered C + local M row-block + working set, well
# under a v5e's 16 GB HBM.
_ALLGATHER_C_MAX_BYTES = 2 << 30


def resolve_ring_kernel(n_rows: int, v_out: int, k: int) -> bool:
    """Ring-step fold choice (``ring_kernel`` tuning knob): the rect
    two-pass Pallas kernel vs the jnp fold — bit-identical results, so
    this is purely a measured-performance pick. Feasibility (real
    Pallas backend, kernel's (V, k) gate) is a hard override: a tuned
    'rect-pallas' on a shape the kernel rejects silently folds. Callers
    should resolve BEFORE a jitted boundary — a ``use_pallas=None``
    passed into the jitted ring programs is resolved at trace time and
    frozen into that program's cache entry."""
    from .. import tuning
    from ..ops import pallas_kernels as pk

    feasible = pk.pallas_supported() and pk.rect_supported(v_out, k)
    choice = tuning.choose(
        "ring_kernel", n=n_rows, v=v_out,
        default="rect-pallas" if feasible else "jnp-fold",
    )
    return choice == "rect-pallas" and feasible


def choose_allpairs_strategy(
    n_rows: int, v_width: int, n_devices: int, itemsize: int = 4
) -> str:
    """Pick ``allgather`` vs ``ring`` for the all-pairs product.

    ``allgather`` until the gathered C ([N_pad, V] on EVERY device)
    exceeds the HBM budget; ``ring`` beyond. The fold/psum/top-k phases
    are identical under either choice.
    """
    n_pad = pad_to_multiple(n_rows, n_devices)
    gathered_bytes = n_pad * v_width * itemsize
    return "allgather" if gathered_bytes <= _ALLGATHER_C_MAX_BYTES else "ring"


def shard_first_block_rows(
    first: np.ndarray, mesh: Mesh, axis: str = "dp"
) -> jax.Array:
    """Pad the row axis to a device multiple and place with rows sharded
    over ``axis``. Returns the padded, sharded device array."""
    n_dev = mesh.shape[axis]
    n_pad = pad_to_multiple(first.shape[0], n_dev)
    if n_pad != first.shape[0]:
        first = np.pad(first, ((0, n_pad - first.shape[0]), (0, 0)))
    sharding = NamedSharding(mesh, P(axis, None))
    return jax.device_put(first, sharding)


def replicate(x: np.ndarray, mesh: Mesh) -> jax.Array:
    return jax.device_put(x, NamedSharding(mesh, P()))


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "allpairs_strategy", "want_m")
)
def sharded_chain_outputs(
    first: jax.Array,
    rest: Sequence[jax.Array],
    mesh: Mesh,
    axis: str = "dp",
    allpairs_strategy: str = "allgather",
    want_m: bool = True,
):
    """Compute (M_rowblocks, rowsums) for a *symmetric* chain, sharded.

    ``first`` is the row-sharded (padded) first half-block; ``rest`` are
    the remaining replicated half-chain blocks. Returns M with rows
    sharded over ``axis`` (or None if ``want_m`` is False) and the full
    rowsum vector, row-sharded.
    """

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), tuple(P() for _ in rest)),
        out_specs=(P(axis, None) if want_m else P(), P(axis)),
    )
    def run(first_local, rest_blocks):
        with jax.default_matmul_precision("highest"):
            c_local = first_local
            for b in rest_blocks:
                c_local = jnp.matmul(c_local, b)
            colsum_total = jax.lax.psum(jnp.sum(c_local, axis=0), axis)
            rowsums_local = jnp.matmul(c_local, colsum_total)
            if not want_m:
                return jnp.zeros((1, 1), dtype=c_local.dtype), rowsums_local
            if allpairs_strategy == "allgather":
                c_full = jax.lax.all_gather(c_local, axis, axis=0, tiled=True)
                m_local = jnp.matmul(c_local, c_full.T)
            elif allpairs_strategy == "ring":
                m_local = ring_allpairs_rowblock(c_local, axis)
            else:
                raise ValueError(
                    f"unknown allpairs_strategy {allpairs_strategy!r}"
                )
            return m_local, rowsums_local

    m, rowsums = run(first, tuple(rest))
    return (m if want_m else None), rowsums


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "axis", "k", "n_true", "mask_self", "variant", "use_pallas"
    ),
)
def sharded_topk(
    first: jax.Array,
    rest: Sequence[jax.Array],
    mesh: Mesh,
    k: int,
    n_true: int,
    axis: str = "dp",
    mask_self: bool = True,
    variant: str = "rowsum",
    use_pallas: bool | None = None,
):
    """Distributed per-row top-k without materializing any score block
    bigger than [n_loc, n_loc]: local half-chain fold, one ``psum`` for
    column totals, then the ``ppermute`` ring streams peer C-blocks and
    folds score tiles into each device's running top-k
    (ring.ring_topk_rowblock). Output is row-sharded [N_pad, k].

    ``variant`` picks the denominator the ring carries: "rowsum" needs
    the one psum above; "diagonal" (diag(M)[i] = Σ_v C[i,v]², textbook
    PathSim) is purely local — no collective at all."""
    if use_pallas is None:
        # feasibility is part of the gate: the rect kernel serves any V
        # (un-tiled stripe kernel to V ≤ 512, the K-tiled variant
        # beyond) but needs k < _CAND for self-exclusion headroom;
        # shapes it rejects fall back to the jnp ring fold whatever the
        # tuning table says
        v_out = rest[-1].shape[1] if rest else first.shape[1]
        use_pallas = resolve_ring_kernel(first.shape[0], v_out, k)
    # check_vma is disabled on the Pallas ring path: the pallas_call's
    # internal loop discharge doesn't propagate varying-axis metadata
    # (jax raises "mismatched varying manual axes ... as a temporary
    # workaround pass check_vma=False"). The jnp fold keeps the checker.

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), tuple(P() for _ in rest)),
        out_specs=(P(axis, None), P(axis, None)),
        check_vma=not use_pallas,
    )
    def run(first_local, rest_blocks):
        with jax.default_matmul_precision("highest"):
            c_local = first_local
            for b in rest_blocks:
                c_local = jnp.matmul(c_local, b)
            if variant == "rowsum":
                colsum_total = jax.lax.psum(jnp.sum(c_local, axis=0), axis)
                d_local = jnp.matmul(c_local, colsum_total)
            elif variant == "diagonal":
                d_local = jnp.sum(c_local * c_local, axis=1)
            else:
                raise ValueError(f"unknown PathSim variant {variant!r}")
        return ring_topk_rowblock(
            c_local, d_local, axis, k=k, n_true=n_true,
            mask_self=mask_self, use_pallas=use_pallas,
        )

    return run(first, tuple(rest))


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "variant")
)
def sharded_ring_state(
    first: jax.Array,
    rest: Sequence[jax.Array],
    mesh: Mesh,
    axis: str = "dp",
    variant: str = "rowsum",
):
    """The ring's fixed per-device state: the folded local factor block
    and its denominator rows (one psum for the rowsum variant). Cheap —
    recomputed on every resume so checkpoints never persist O(N·V)."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), tuple(P() for _ in rest)),
        out_specs=(P(axis, None), P(axis)),
    )
    def run(first_local, rest_blocks):
        with jax.default_matmul_precision("highest"):
            c_local = first_local
            for b in rest_blocks:
                c_local = jnp.matmul(c_local, b)
            if variant == "rowsum":
                colsum_total = jax.lax.psum(jnp.sum(c_local, axis=0), axis)
                d_local = jnp.matmul(c_local, colsum_total)
            elif variant == "diagonal":
                d_local = jnp.sum(c_local * c_local, axis=1)
            else:
                raise ValueError(f"unknown PathSim variant {variant!r}")
        return c_local, d_local

    return run(first, tuple(rest))


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis", "k", "n_true", "mask_self",
                     "use_pallas"),
)
def sharded_ring_step(
    c, d, block, d_block, best_v, best_i, t,
    mesh: Mesh,
    k: int,
    n_true: int,
    axis: str = "dp",
    mask_self: bool = True,
    use_pallas: bool = False,
):
    """One host-driven ring step over the mesh (ring.ring_topk_step
    inside shard_map) — the checkpointable unit of the stepwise pass.
    ``t`` is a traced step index, so all n_dev steps share one compiled
    program."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(axis, None), P(axis), P(axis, None), P(axis),
            P(axis, None), P(axis, None), P(),
        ),
        out_specs=(P(axis, None), P(axis), P(axis, None), P(axis, None)),
        check_vma=not use_pallas,  # same workaround as sharded_topk
    )
    def run(c_l, d_l, b_l, db_l, bv_l, bi_l, t_):
        from .ring import ring_topk_step

        return ring_topk_step(
            c_l, d_l, b_l, db_l, bv_l, bi_l, t_,
            axis=axis, k=k, n_true=n_true, mask_self=mask_self,
            use_pallas=use_pallas,
        )

    return run(c, d, block, d_block, best_v, best_i,
               jnp.asarray(t, dtype=jnp.int32))


@functools.partial(jax.jit, static_argnames=("shift",))
def _roll_rows(x: jax.Array, shift: int) -> jax.Array:
    """Global block-roll that rebuilds the ring's rotating state at
    resume: after s steps device i holds the block of device (i−s) mod
    d — exactly roll-by-(s·n_loc) of the row-sharded array (XLA lowers
    the cross-shard motion to a collective permute)."""
    return jnp.roll(x, shift, axis=0)


def _fetch_global(x) -> np.ndarray:
    """Full host copy of a (possibly cross-process) sharded array —
    np.asarray on an array spanning non-addressable devices raises, so
    multi-host gathers first (same hazard jax_sharded._fetch handles;
    the checkpointed bests are [N, k], small enough to replicate)."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def _put_global(arr: np.ndarray, sharding) -> jax.Array:
    """Place a full host copy (present on every process) as a sharded
    global array — per-device callback, so it works on multi-process
    meshes where a plain device_put of the global array would not."""
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def sharded_topk_stepwise(
    first: jax.Array,
    rest: Sequence[jax.Array],
    mesh: Mesh,
    k: int,
    n_true: int,
    axis: str = "dp",
    mask_self: bool = True,
    variant: str = "rowsum",
    use_pallas: bool | None = None,
    ckpt=None,
    every: int = 1,
):
    """sharded_topk with mid-ring checkpoint/resume: the ring runs one
    step per dispatch from the host; every ``every`` steps the [N, k]
    running bests land in the checkpoint (unit ``ring_bests_after_{t}``)
    — the mesh-scale analog of the reference's per-stage append-mode
    crash resilience (SURVEY.md §5). Resume reloads the newest unit,
    rebuilds C and the rotating block (a block-roll — never persisted),
    and continues from step t+1. Identical fold → identical results to
    :func:`sharded_topk` at any kill point.

    ``ckpt``: a utils.checkpoint.CheckpointManager (identity — graph
    digest, mesh size, compute path — is the CALLER's contract, like
    the jax-sparse tier's _run_config)."""
    if use_pallas is None:
        v_out = rest[-1].shape[1] if rest else first.shape[1]
        use_pallas = resolve_ring_kernel(first.shape[0], v_out, k)
    n_dev = mesh.shape[axis]
    c, d = sharded_ring_state(first, tuple(rest), mesh=mesh, axis=axis,
                              variant=variant)
    n_pad = c.shape[0]
    n_loc = n_pad // n_dev
    sharding2 = jax.NamedSharding(mesh, P(axis, None))

    start = 0
    prev_key = None
    if ckpt is not None:
        prefix = "ring_bests_after_"
        snaps = [key for key in ckpt.done_keys() if key.startswith(prefix)]
        if snaps:
            prev_key = max(snaps, key=lambda s: int(s[len(prefix):]))
            for stale in snaps:  # crash between save(new)/drop(old)
                if stale != prev_key:
                    ckpt.drop_unit(stale)
            after = int(prev_key[len(prefix):])
            unit = ckpt.load_unit(prev_key)
            # the units carry the run's own dtype (an f64/x64 run must
            # resume in f64 — a float32 cast here would break the
            # bit-identical-resume contract exactly in the high-count
            # regime; dtype is part of the caller's checkpoint identity)
            best_v = _put_global(
                np.asarray(unit["vals"], dtype=c.dtype), sharding2
            )
            best_i = _put_global(
                np.asarray(unit["idxs"], dtype=np.int32), sharding2
            )
            start = after + 1
    if start == 0:
        best_v = _put_global(
            np.full((n_pad, k), -np.inf, dtype=c.dtype), sharding2
        )
        best_i = _put_global(
            np.zeros((n_pad, k), dtype=np.int32), sharding2
        )
    if start:
        block = _roll_rows(c, start * n_loc)
        d_block = _roll_rows(d, start * n_loc)
    else:
        block, d_block = c, d

    from .. import resilience
    from ..resilience.preemption import handler as _preemption

    # Per-process retry and one-host preemption flushes are only sound
    # single-controller: in a multi-host job every process must issue
    # the identical sequence of SPMD programs, so a retry (or a flush
    # collective) on ONE host would desynchronize the cluster. There
    # the steps run bare — multi-host recovery is job-level (the
    # scheduler restarts all hosts; the checkpoint still resumes).
    single_controller = jax.process_count() == 1

    def _snapshot(after: int, prev_key):
        """Durable running-bests snapshot for resume at step after+1;
        drops the superseded snapshot only once the new one landed."""
        new_key = f"ring_bests_after_{after}"
        ckpt.save_unit(
            new_key,
            vals=_fetch_global(best_v),
            idxs=_fetch_global(best_i),
        )
        if prev_key is not None and prev_key != new_key:
            ckpt.drop_unit(prev_key)  # only after the new is durable
        return new_key

    for t in range(start, n_dev):
        # Preemption point (ring-step boundary): flush the running
        # bests as a fresh snapshot so the restart resumes at step t,
        # not at the last `every`-cadence snapshot.
        if single_controller and _preemption.requested():
            if ckpt is not None and t > start:
                prev_key = _snapshot(t - 1, prev_key)
            _preemption.check(
                checkpoint_dir=str(ckpt.dir) if ckpt is not None else None
            )
        # One ring step = one tile_execute attempt: the step is
        # functional (new carries returned, assigned on success), so a
        # transient dispatch failure retries without double-folding.
        step = (
            lambda t=t, block=block, d_block=d_block, bv=best_v,
            bi=best_i: sharded_ring_step(
                c, d, block, d_block, bv, bi, t,
                mesh=mesh, k=k, n_true=n_true, axis=axis,
                mask_self=mask_self, use_pallas=use_pallas,
            )
        )
        if single_controller:
            block, d_block, best_v, best_i = resilience.resilient_call(
                "tile_execute", step
            )
        else:
            block, d_block, best_v, best_i = step()
        if ckpt is not None and (t % every == every - 1 or t == n_dev - 1):
            prev_key = _snapshot(t, prev_key)
    return best_v, best_i
