"""Row-sharded commuting-matrix chain under shard_map.

SPMD design (BASELINE.json config 3): the first block of the chain (the
source-type × next-type adjacency, e.g. ``A_AP``) is sharded along its
rows over the ``dp`` mesh axis; the remaining (small, contracted) blocks
are replicated. Each device computes its row-block of the half-chain
``C = A_AP @ A_PV`` locally; then

- global column total  (Σ_x C[x, :]):  local colsum + ``psum`` over dp —
  this is the ONLY cross-device reduction the row sums need
- row sums:  ``C_local @ colsum_total``       (no communication)
- all-pairs M row-block:  ``C_local @ C_fullᵀ`` where ``C_full`` comes
  from ``all_gather`` (moderate N), or from a ``ppermute`` ring that
  streams peer blocks through ICI without ever holding all of M or all
  of C (large N — the ring-attention communication pattern applied to
  the author axis; see parallel/ring.py)

Padding: the row axis is padded to a device multiple with all-zero rows;
zero rows of ``A_AP`` produce zero rows of C and M and contribute zero to
every ``psum`` — tested, not assumed (tests/test_sharded.py).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import pad_to_multiple
from .ring import ring_allpairs_rowblock, ring_topk_rowblock


# all_gather materializes every peer's C block on every device — fast
# (one fused collective, maximal overlap) until the gathered [N_pad, V]
# factor starts to crowd HBM; the ppermute ring keeps only 2 peer
# blocks live at any time at the cost of D-1 dependent steps. Crossover
# measured on the virtual mesh (SHARDED_SCALING_r03.json): allgather
# wins at every size that fits; the ring exists for the sizes that
# don't. Budget: gathered C + local M row-block + working set, well
# under a v5e's 16 GB HBM.
_ALLGATHER_C_MAX_BYTES = 2 << 30


def choose_allpairs_strategy(
    n_rows: int, v_width: int, n_devices: int, itemsize: int = 4
) -> str:
    """Pick ``allgather`` vs ``ring`` for the all-pairs product.

    ``allgather`` until the gathered C ([N_pad, V] on EVERY device)
    exceeds the HBM budget; ``ring`` beyond. The fold/psum/top-k phases
    are identical under either choice.
    """
    n_pad = pad_to_multiple(n_rows, n_devices)
    gathered_bytes = n_pad * v_width * itemsize
    return "allgather" if gathered_bytes <= _ALLGATHER_C_MAX_BYTES else "ring"


def shard_first_block_rows(
    first: np.ndarray, mesh: Mesh, axis: str = "dp"
) -> jax.Array:
    """Pad the row axis to a device multiple and place with rows sharded
    over ``axis``. Returns the padded, sharded device array."""
    n_dev = mesh.shape[axis]
    n_pad = pad_to_multiple(first.shape[0], n_dev)
    if n_pad != first.shape[0]:
        first = np.pad(first, ((0, n_pad - first.shape[0]), (0, 0)))
    sharding = NamedSharding(mesh, P(axis, None))
    return jax.device_put(first, sharding)


def replicate(x: np.ndarray, mesh: Mesh) -> jax.Array:
    return jax.device_put(x, NamedSharding(mesh, P()))


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "allpairs_strategy", "want_m")
)
def sharded_chain_outputs(
    first: jax.Array,
    rest: Sequence[jax.Array],
    mesh: Mesh,
    axis: str = "dp",
    allpairs_strategy: str = "allgather",
    want_m: bool = True,
):
    """Compute (M_rowblocks, rowsums) for a *symmetric* chain, sharded.

    ``first`` is the row-sharded (padded) first half-block; ``rest`` are
    the remaining replicated half-chain blocks. Returns M with rows
    sharded over ``axis`` (or None if ``want_m`` is False) and the full
    rowsum vector, row-sharded.
    """

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), tuple(P() for _ in rest)),
        out_specs=(P(axis, None) if want_m else P(), P(axis)),
    )
    def run(first_local, rest_blocks):
        with jax.default_matmul_precision("highest"):
            c_local = first_local
            for b in rest_blocks:
                c_local = jnp.matmul(c_local, b)
            colsum_total = jax.lax.psum(jnp.sum(c_local, axis=0), axis)
            rowsums_local = jnp.matmul(c_local, colsum_total)
            if not want_m:
                return jnp.zeros((1, 1), dtype=c_local.dtype), rowsums_local
            if allpairs_strategy == "allgather":
                c_full = jax.lax.all_gather(c_local, axis, axis=0, tiled=True)
                m_local = jnp.matmul(c_local, c_full.T)
            elif allpairs_strategy == "ring":
                m_local = ring_allpairs_rowblock(c_local, axis)
            else:
                raise ValueError(
                    f"unknown allpairs_strategy {allpairs_strategy!r}"
                )
            return m_local, rowsums_local

    m, rowsums = run(first, tuple(rest))
    return (m if want_m else None), rowsums


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "axis", "k", "n_true", "mask_self", "variant", "use_pallas"
    ),
)
def sharded_topk(
    first: jax.Array,
    rest: Sequence[jax.Array],
    mesh: Mesh,
    k: int,
    n_true: int,
    axis: str = "dp",
    mask_self: bool = True,
    variant: str = "rowsum",
    use_pallas: bool | None = None,
):
    """Distributed per-row top-k without materializing any score block
    bigger than [n_loc, n_loc]: local half-chain fold, one ``psum`` for
    column totals, then the ``ppermute`` ring streams peer C-blocks and
    folds score tiles into each device's running top-k
    (ring.ring_topk_rowblock). Output is row-sharded [N_pad, k].

    ``variant`` picks the denominator the ring carries: "rowsum" needs
    the one psum above; "diagonal" (diag(M)[i] = Σ_v C[i,v]², textbook
    PathSim) is purely local — no collective at all."""
    if use_pallas is None:
        from ..ops import pallas_kernels as pk

        # feasibility must be part of the auto-gate: the rect kernel
        # serves any V (un-tiled stripe kernel to V ≤ 512, the K-tiled
        # variant beyond) but needs k < _CAND for self-exclusion
        # headroom; shapes it rejects fall back to the jnp ring fold
        v_out = rest[-1].shape[1] if rest else first.shape[1]
        use_pallas = pk.pallas_supported() and pk.rect_supported(v_out, k)
    # check_vma is disabled on the Pallas ring path: the pallas_call's
    # internal loop discharge doesn't propagate varying-axis metadata
    # (jax raises "mismatched varying manual axes ... as a temporary
    # workaround pass check_vma=False"). The jnp fold keeps the checker.

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), tuple(P() for _ in rest)),
        out_specs=(P(axis, None), P(axis, None)),
        check_vma=not use_pallas,
    )
    def run(first_local, rest_blocks):
        with jax.default_matmul_precision("highest"):
            c_local = first_local
            for b in rest_blocks:
                c_local = jnp.matmul(c_local, b)
            if variant == "rowsum":
                colsum_total = jax.lax.psum(jnp.sum(c_local, axis=0), axis)
                d_local = jnp.matmul(c_local, colsum_total)
            elif variant == "diagonal":
                d_local = jnp.sum(c_local * c_local, axis=1)
            else:
                raise ValueError(f"unknown PathSim variant {variant!r}")
        return ring_topk_rowblock(
            c_local, d_local, axis, k=k, n_true=n_true,
            mask_self=mask_self, use_pallas=use_pallas,
        )

    return run(first, tuple(rest))
