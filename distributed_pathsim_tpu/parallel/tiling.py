"""2-D block-tiled all-pairs scoring over a (dp, tp) device mesh.

For the million-author regime (BASELINE.json config 5) a 1-D row sharding
still makes every device hold a full [n_loc, N] strip of the score
matrix; 2-D tiling shards BOTH axes: device (i, j) owns the
[N/dp, N/tp] tile  S[i·N/dp:, j·N/tp:] = 2·(C_i C_jᵀ) / (d_i ⊕ d_j),
so per-device memory falls as 1/(dp·tp) and the output sharding matches
the mesh exactly (XLA keeps it resident, no gather).

Communication: one ``psum`` over ``dp`` for the column totals that feed
row sums — the C blocks arrive pre-sharded (rows over dp for the left
operand, rows over tp for the right), so the big product needs NO
collectives at all. The distributed top-k reduces each device's tile
locally, then ``all_gather``s only [n_loc, k] candidates over ``tp`` —
O(N·k/dp) traffic instead of O(N²/dp).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.sparse import chunked_row_topk
from ..utils.compat import shard_map
from .mesh import pad_to_multiple


def place_2d(c: np.ndarray, rowsums: np.ndarray, mesh: Mesh,
             axes: tuple[str, str] = ("dp", "tp")):
    """Pad and place the half-chain factor twice: row-sharded over dp
    (left operand) and over tp (right operand), plus the rowsum vector
    sharded to match. Padding rows are zero → rowsum 0 → score 0."""
    dp, tp = axes
    n = c.shape[0]
    n_pad = pad_to_multiple(n, int(np.lcm(mesh.shape[dp], mesh.shape[tp])))
    if n_pad != n:
        c = np.pad(c, ((0, n_pad - n), (0, 0)))
        rowsums = np.pad(rowsums, (0, n_pad - n))
    c_row = jax.device_put(c, NamedSharding(mesh, P(dp, None)))
    c_col = jax.device_put(c, NamedSharding(mesh, P(tp, None)))
    d_row = jax.device_put(rowsums, NamedSharding(mesh, P(dp)))
    d_col = jax.device_put(rowsums, NamedSharding(mesh, P(tp)))
    return c_row, c_col, d_row, d_col


def _score_tile(ci, cj, di, dj):
    """One score tile: 2·(C_i C_jᵀ) / (d_i ⊕ d_j), zero where the
    denominator is zero. Shared by both shard_map bodies so their
    numerics can never drift apart."""
    with jax.default_matmul_precision("highest"):
        m = jnp.matmul(ci, cj.T)
    denom = di[:, None] + dj[None, :]
    return jnp.where(denom > 0, 2.0 * m / jnp.where(denom > 0, denom, 1.0), 0.0)


@functools.partial(jax.jit, static_argnames=("mesh", "axes"))
def tiled_scores_2d(c_row, c_col, d_row, d_col, mesh: Mesh,
                    axes: tuple[str, str] = ("dp", "tp")):
    """All-pairs scores, output sharded (dp, tp) over the mesh."""
    dp, tp = axes

    run = functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(dp, None), P(tp, None), P(dp), P(tp)),
        out_specs=P(dp, tp),
    )(_score_tile)

    return run(c_row, c_col, d_row, d_col)


@functools.partial(jax.jit, static_argnames=("mesh", "axes", "k", "n_true"))
def tiled_topk_2d(c_row, c_col, d_row, d_col, mesh: Mesh, k: int,
                  n_true: int, axes: tuple[str, str] = ("dp", "tp")):
    """Distributed top-k: local tile top-k, then merge over the tp axis.

    Returns (values [N_pad, k], indices [N_pad, k]) row-sharded over dp.
    Self-pairs are masked; padding columns (≥ n_true) are masked; real
    zero-degree targets keep score 0 (oracle semantics).
    """
    dp, tp = axes

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(dp, None), P(tp, None), P(dp), P(tp)),
        out_specs=(P(dp, None), P(dp, None)),
        # After the all_gather over tp every device in a dp row group holds
        # identical top-k results; the varying-axis checker can't infer
        # that replication, so it is asserted here instead.
        check_vma=False,
    )
    def run(ci, cj, di, dj):
        n_loc_r, _ = ci.shape
        n_loc_c = cj.shape[0]
        i = jax.lax.axis_index(dp)
        j = jax.lax.axis_index(tp)
        s = _score_tile(ci, cj, di, dj)
        rows = i * n_loc_r + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = j * n_loc_c + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols >= n_true, -jnp.inf, s)  # padding columns
        s = jnp.where(rows == cols, -jnp.inf, s)    # self-pairs
        kk = min(k, n_loc_c)
        # Hierarchical prefilter instead of a flat sort of the whole
        # tile (same order contract; measured 4.3× on the ring fold).
        loc_v, loc_i = chunked_row_topk(s, cols, kk)  # [n_loc_r, kk]
        # gather candidates from every column tile of this row block
        cand_v = jax.lax.all_gather(loc_v, tp, axis=1, tiled=True)
        cand_i = jax.lax.all_gather(loc_i, tp, axis=1, tiled=True)
        # k can exceed the merged candidate width (tp·kk) on tiny graphs;
        # take what exists and pad to k with -inf, matching the 1-D
        # streaming path's k > N behavior.
        k_avail = min(k, kk * mesh.shape[tp])
        top_v, top_p = jax.lax.top_k(cand_v, k_avail)
        top_i = jnp.take_along_axis(cand_i, top_p, axis=1)
        if k_avail < k:
            pad = ((0, 0), (0, k - k_avail))
            top_v = jnp.pad(top_v, pad, constant_values=-jnp.inf)
            top_i = jnp.pad(top_i, pad)
        return top_v, top_i

    return run(c_row, c_col, d_row, d_col)
