"""Multi-host execution: DCN-aware meshes and host-local data placement.

The reference delegates every cross-machine concern to Spark's JVM
shuffle (netty RPC + block manager, invoked implicitly by
``gf.find(...).count()`` — ``DPathSim_APVPA.py:72-86``; SURVEY.md §5).
The TPU-native counterpart is multi-host SPMD: one program, a global
device mesh spanning hosts, XLA routing collectives over ICI inside a
slice and DCN between slices. This module provides the three pieces a
multi-host run needs — nothing here talks to a transport:

1. :func:`initialize_multihost` — ``jax.distributed`` bootstrap
   (coordinator rendezvous); an explicit no-op for single-process runs so
   the same driver script works on a laptop and a pod.
2. :func:`make_hybrid_mesh` — a ``(dp, tp)`` mesh whose ``dp`` (row)
   axis spans hosts over DCN while ``tp`` stays inside a slice on ICI.
   This matches the chain's communication profile: the only cross-``dp``
   collective is the column-total ``psum`` (an O(V) vector — cheap over
   DCN), while the heavy ``all_gather``/``ppermute`` of C row-blocks and
   the top-k candidate merge ride ``tp``'s ICI links.
3. :func:`host_row_range` / :func:`distributed_first_block` — each host
   loads ONLY its own rows of the first adjacency block and the global
   sharded array is assembled via
   ``jax.make_array_from_process_local_data``; no host ever materializes
   the full matrix.
"""

from __future__ import annotations

import os
from typing import Callable

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import pad_to_multiple

# Env vars that signal a jax.distributed cluster rendezvous is expected.
# Deliberately ONLY explicit coordinator addresses: markers like
# TPU_WORKER_HOSTNAMES or SLURM_JOB_ID are also set on single-host
# workers, where calling jax.distributed.initialize() after backend
# init would raise.
_CLUSTER_ENV_VARS = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
)


def _distributed_client_exists() -> bool:
    """True iff jax.distributed.initialize() already ran in this process
    (e.g. by a SLURM/GKE launcher) — calling it again would raise."""
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:
        return False


def _backends_initialized() -> bool:
    """Whether any XLA backend has been created. Probes the private
    xla_bridge helper when present (it avoids side effects); on JAX
    versions that moved it, conservatively reports False, in which case
    jax.distributed.initialize() itself still raises a clear error if
    called too late."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:
        return False


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> bool:
    """Bootstrap ``jax.distributed`` when running multi-process.

    Explicit arguments always initialize. With no arguments, initializes
    only if a known cluster environment is detected — otherwise this is a
    no-op so single-process runs need no special casing. Returns True iff
    the process is part of a multi-process job after the call.
    """
    explicit = coordinator_address is not None
    detected = any(v in os.environ for v in _CLUSTER_ENV_VARS)
    if not (explicit or detected):
        # No rendezvous requested: answer WITHOUT touching jax.process_count(),
        # which would trigger the first backend initialization — on hosts
        # whose accelerator tunnel can hang at init, a plain single-process
        # CPU run must never pay that cost just to learn it isn't a cluster.
        return _distributed_client_exists() and jax.process_count() > 1
    if _distributed_client_exists():
        return jax.process_count() > 1  # launcher already ran initialize()
    # Order matters: jax.process_count() itself initializes the XLA
    # backend, after which jax.distributed.initialize() raises — so the
    # rendezvous decision must come first, guarded only by the (backend-
    # neutral) initialized check.
    if _backends_initialized():
        if jax.process_count() > 1:
            return True  # cluster formed by other means
        raise RuntimeError(
            "initialize_multihost() must be called before any JAX backend "
            "use (jax.devices(), computations, device_put, …); move it to "
            "program start"
        )
    # The rendezvous is a network operation against a coordinator that
    # may not be up yet (hosts race at job start) — the multihost_init
    # resilience seam retries it with backoff. RuntimeError is added to
    # the retryable set here because jax.distributed surfaces transient
    # gRPC failures (UNAVAILABLE, DEADLINE_EXCEEDED) as RuntimeError;
    # InjectedCrash (a RuntimeError subclass meaning "hard kill") must
    # stay non-retryable or chaos 'crash' rules would be absorbed here.
    from .. import resilience

    policy = resilience.policy_from_env()
    policy = policy.replace(
        retryable=policy.retryable + (RuntimeError,),
        non_retryable=policy.non_retryable + (resilience.InjectedCrash,),
    )
    resilience.resilient_call(
        "multihost_init",
        lambda: jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        ),
        policy,
    )
    return jax.process_count() > 1


def make_hybrid_mesh(
    tp: int = 1, axes: tuple[str, str] = ("dp", "tp"), devices=None
) -> Mesh:
    """Build a ``(dp, tp)`` mesh with ``dp`` spanning hosts over DCN.

    ``tp`` devices per tile-column stay within one host's slice (ICI);
    the remaining device factor — local dp × number of hosts — forms the
    row axis, hosts outermost, so each host's processes own contiguous
    row ranges (see :func:`host_row_range`). Single-process: falls back
    to an ICI-optimised local mesh of the same shape.
    """
    devices = list(devices if devices is not None else jax.devices())
    n_local = len([d for d in devices if d.process_index == jax.process_index()])
    n_hosts = jax.process_count()
    if n_local % tp != 0:
        raise ValueError(
            f"tp={tp} must divide the per-host device count {n_local}"
        )
    if n_hosts > 1:
        # process_is_granule: DCN granules are PROCESSES, not TPU slices —
        # a multi-host single-slice pod (e.g. v4-32, 4 processes) has one
        # slice but four hosts, and row ownership must follow processes
        # for host_row_range()'s contiguity guarantee to hold.
        dev_mesh = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(n_local // tp, tp),
            dcn_mesh_shape=(n_hosts, 1),
            devices=devices,
            process_is_granule=True,
        )
    else:
        dev_mesh = mesh_utils.create_device_mesh(
            (n_local // tp, tp), devices=devices
        )
    return Mesh(dev_mesh, axes)


def host_row_range(n_rows: int, mesh: Mesh, axis: str = "dp") -> tuple[int, int]:
    """The contiguous [start, stop) slice of the (padded) global row axis
    owned by THIS process under ``axis``-sharding on ``mesh``.

    Row ownership follows the mesh's device order: with hosts outermost
    on ``dp`` (as :func:`make_hybrid_mesh` builds it), process p owns
    rows [p·n_pad/P, (p+1)·n_pad/P). The stop of the last host covers
    the padding; callers zero-fill rows beyond ``n_rows``.
    """
    n_pad = pad_to_multiple(n_rows, mesh.shape[axis])
    per_host = n_pad // jax.process_count()
    start = jax.process_index() * per_host
    return start, start + per_host


def distributed_first_block(
    load_rows: Callable[[int, int], np.ndarray],
    n_rows: int,
    n_cols: int,
    mesh: Mesh,
    axis: str = "dp",
    dtype=np.float32,
) -> jax.Array:
    """Assemble the row-sharded first chain block without any host ever
    holding it whole.

    ``load_rows(start, stop)`` returns this host's rows (rows past
    ``n_rows`` — padding — must not be requested from it; they are
    zero-filled here). The result is a global jax.Array sharded
    ``P(axis, None)`` over ``mesh``, ready for
    :func:`..parallel.sharded.sharded_chain_outputs`.
    """
    n_pad = pad_to_multiple(n_rows, mesh.shape[axis])
    start, stop = host_row_range(n_rows, mesh, axis)
    real_stop = min(stop, n_rows)
    local = np.zeros((stop - start, n_cols), dtype=dtype)
    if real_stop > start:
        local[: real_stop - start] = load_rows(start, real_stop)
    sharding = NamedSharding(mesh, P(axis, None))
    return jax.make_array_from_process_local_data(
        sharding, local, global_shape=(n_pad, n_cols)
    )
