"""Ring all-pairs: ppermute-streamed block outer products.

The ring-attention communication pattern (blockwise KV rotation over ICI)
applied to this workload's scaling axis — the author dimension of the
commuting matrix (SURVEY.md §5, long-context row). Each device holds one
row-block of the half-chain factor ``C``; the peer block rotates around
the ring while each device accumulates one ``C_local @ C_peerᵀ`` tile of
its M row-block per step. Communication per step is ``N/d × V`` — all of
``M`` (N×N) and all of ``C`` (N×V) never materialize on any one device,
which is what makes the 1M-author configuration reachable.

Compute/communication overlap: each step's matmul runs while XLA can
schedule the next ppermute; on TPU the permute rides neighbor ICI links
(the mesh axis order is the ring order).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils import compat

from ..ops.sparse import chunked_row_topk


def ring_allpairs_rowblock(c_local: jax.Array, axis: str) -> jax.Array:
    """Inside shard_map: compute this device's row-block of M = C Cᵀ by
    rotating peer blocks around the ``axis`` ring.

    c_local: [n_loc, V] — this device's rows of C.
    Returns [n_loc, n_dev * n_loc] — this device's rows of M (padded N).
    """
    n_dev = compat.axis_size(axis)
    my = jax.lax.axis_index(axis)
    n_loc = c_local.shape[0]
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def step(k, carry):
        block, m = carry
        # After k rotations device `my` holds the block originally owned
        # by device (my - k) mod n_dev — its tile lands at that column.
        owner = (my - k) % n_dev
        tile = jnp.matmul(c_local, block.T)
        col0 = (owner * n_loc).astype(jnp.int32)
        m = jax.lax.dynamic_update_slice(m, tile, (jnp.int32(0), col0))
        block = jax.lax.ppermute(block, axis, perm)
        return block, m

    # pcast: the accumulator is device-varying (each device builds different
    # rows of M) — shard_map's varying-axis tracking needs that declared.
    m0 = compat.pcast(
        jnp.zeros((n_loc, n_dev * n_loc), dtype=c_local.dtype),
        (axis,),
        to="varying",
    )
    # The final ppermute is wasted motion but keeps the loop uniform; XLA
    # dead-code-eliminates the unused last rotation's result only if we
    # drop it — we do.
    _, m = jax.lax.fori_loop(0, n_dev, step, (c_local, m0))
    return m


def _merge_topk_by_col(merged_v: jax.Array, merged_i: jax.Array, k: int):
    """Top-k of each row of ``merged_v``, ties broken by ascending global
    column index ``merged_i`` — the oracle's stable ``argsort(-scores)``
    order. A bare ``lax.top_k`` would break ties by *merge position*,
    which in a ring fold depends on the device's position and the device
    count; the lexicographic two-key sort makes the returned indices
    identical across backends and mesh sizes."""
    neg_v, idx = jax.lax.sort((-merged_v, merged_i), num_keys=2)
    return -neg_v[:, :k], idx[:, :k]


def ring_topk_rowblock(
    c_local: jax.Array,
    d_local: jax.Array,
    axis: str,
    k: int,
    n_true: int,
    mask_self: bool = True,
    use_pallas: bool | None = None,
):
    """Inside shard_map: per-row top-k PathSim scores for this device's
    row-block, streaming peer blocks around the ``axis`` ring.

    The blockwise-streaming analog of the fused top-k kernel at the
    mesh level: at each of the d ring steps a device holds one
    [n_loc, n_loc] score tile, folds it into its running [n_loc, k]
    best, and passes the peer block on. Peak memory is
    O(n_loc·(V + n_loc + k)) per device — neither M, the scores, nor
    all of C ever exist anywhere, which is what the million-author
    regime needs.

    ``use_pallas``: each ring step's score-and-extract runs through the
    rectangular two-pass Pallas kernel (MXU tile products + packed
    candidate extraction — the same kernel the single-chip tiers use,
    so a real slice keeps the single-chip kernel wins instead of
    falling back to a plain-jnp fold). Auto: on a real TPU whenever the
    kernel supports (V, k); pass True to force it in interpret mode
    (virtual-mesh tests). Both paths share tie-break semantics
    (lowest global column), so results are identical.

    c_local: [n_loc, V] — this device's rows of C.
    d_local: [n_loc] — this device's rows of the global rowsum vector.
    Returns (values [n_loc, k], indices [n_loc, k]) for this row-block.
    """
    from ..ops import pallas_kernels as pk

    if use_pallas is None:
        use_pallas = pk.pallas_supported() and pk.rect_supported(
            c_local.shape[1], k
        )
    n_dev = compat.axis_size(axis)
    my = jax.lax.axis_index(axis)
    n_loc = c_local.shape[0]
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def step(t, carry):
        block, d_block, best_v, best_i = carry
        return ring_topk_step(
            c_local, d_local, block, d_block, best_v, best_i, t,
            axis=axis, k=k, n_true=n_true, mask_self=mask_self,
            use_pallas=use_pallas,
        )

    best_v0 = compat.pcast(
        jnp.full((n_loc, k), -jnp.inf, dtype=c_local.dtype),
        (axis,),
        to="varying",
    )
    best_i0 = compat.pcast(
        jnp.zeros((n_loc, k), dtype=jnp.int32), (axis,), to="varying"
    )
    _, _, best_v, best_i = jax.lax.fori_loop(
        0, n_dev, step, (c_local, d_local, best_v0, best_i0)
    )
    return best_v, best_i


def ring_topk_step(
    c_local: jax.Array,
    d_local: jax.Array,
    block: jax.Array,
    d_block: jax.Array,
    best_v: jax.Array,
    best_i: jax.Array,
    t,
    axis: str,
    k: int,
    n_true: int,
    mask_self: bool = True,
    use_pallas: bool = False,
):
    """ONE ring step, inside shard_map: fold the currently-held peer
    block's score tile into the running bests, then rotate. Factored
    out of :func:`ring_topk_rowblock`'s fori_loop so the checkpointable
    stepwise driver (parallel/sharded.sharded_topk_stepwise) runs the
    IDENTICAL fold per step — the rotating block itself never needs
    persisting (after t steps device i holds the block of device
    (i−t) mod d, a pure block-roll of C reconstructed at resume).

    ``t`` is a traced step index. Returns the next
    (block, d_block, best_v, best_i)."""
    from ..ops import pallas_kernels as pk

    n_dev = compat.axis_size(axis)
    my = jax.lax.axis_index(axis)
    n_loc = c_local.shape[0]
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    rows = my * n_loc + jax.lax.broadcasted_iota(
        jnp.int32, (n_loc, n_loc), 0
    )
    owner = (my - t) % n_dev
    if use_pallas:
        # Self-pairs exist only while a device holds its OWN block
        # (owner == my); the kernel drops candidates whose column
        # equals their row id, and -1 never matches.
        if mask_self:
            row_ids = jnp.where(
                owner == my,
                jnp.arange(n_loc, dtype=jnp.int32),
                jnp.full((n_loc,), -1, dtype=jnp.int32),
            )
        else:
            row_ids = jnp.full((n_loc,), -1, dtype=jnp.int32)
        # n_true_cols=n_loc masks only the kernel's own lane/stripe
        # padding; RING padding (global col ≥ n_true, all in the
        # last owner's block) is masked after the global offset.
        tile_v, tile_loc = pk.fused_topk_twopass_rect(
            c_local, block, d_local, d_block, row_ids,
            k=k, n_true_cols=n_loc,
            interpret=not pk.pallas_supported(),
        )
        tile_i = (owner * n_loc).astype(jnp.int32) + tile_loc
        tile_v = jnp.where(tile_i >= n_true, -jnp.inf, tile_v)
    else:
        with jax.default_matmul_precision("highest"):
            m = jnp.matmul(c_local, block.T)
        denom = d_local[:, None] + d_block[None, :]
        s = jnp.where(
            denom > 0, 2.0 * m / jnp.where(denom > 0, denom, 1.0), 0.0
        )
        cols = (
            (owner * n_loc).astype(jnp.int32)
            + jax.lax.broadcasted_iota(jnp.int32, (n_loc, n_loc), 1)
        )
        s = jnp.where(cols >= n_true, -jnp.inf, s)  # padding columns
        if mask_self:
            s = jnp.where(rows == cols, -jnp.inf, s)
        # Hierarchical prefilter narrows this step's tile to k
        # candidates (ascending-column tie-breaks, same as the
        # final sort) BEFORE the lexicographic merge — sorting the
        # raw [n_loc, n_loc+k] concat each step costs
        # O(n_loc log n_loc) per row and was the fold's dominant
        # term at n_loc ≥ 4k (measured 4.3×).
        tile_v, tile_i = chunked_row_topk(s, cols, k)
    merged_v = jnp.concatenate([best_v, tile_v], axis=1)
    merged_i = jnp.concatenate([best_i, tile_i], axis=1)
    best_v, best_i = _merge_topk_by_col(merged_v, merged_i, k)
    block = jax.lax.ppermute(block, axis, perm)
    d_block = jax.lax.ppermute(d_block, axis, perm)
    return block, d_block, best_v, best_i
