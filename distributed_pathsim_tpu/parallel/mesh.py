"""Device-mesh construction.

The reference's entire distribution story is Spark's opaque JVM-side
partitioned join (SURVEY.md §2, parallelism inventory). Here distribution
is explicit: a `jax.sharding.Mesh` whose axes name the parallelism —

- ``"dp"``: the author/output-row axis of the commuting matrix (the analog
  of Spark's data partitioning — 1-D tensor parallelism of the chain)
- ``"tp"``: optional second axis for 2-D block tiling of all-pairs outputs
  at the 1M-author scale (BASELINE.json config 5)

Shardings ride ICI within a host slice and DCN across hosts — XLA inserts
the collectives; nothing here talks to a transport.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(
    n_devices: int | None = None, axis: str = "dp", devices=None
) -> Mesh:
    """1-D mesh over the first ``n_devices`` available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def make_mesh_2d(
    shape: tuple[int, int],
    axes: tuple[str, str] = ("dp", "tp"),
    devices=None,
) -> Mesh:
    """2-D mesh for block-tiled all-pairs computation."""
    devices = list(devices if devices is not None else jax.devices())
    n = shape[0] * shape[1]
    if n > len(devices):
        raise ValueError(f"mesh {shape} needs {n} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]).reshape(shape), axes)


def pad_to_multiple(n: int, k: int) -> int:
    """Smallest multiple of k that is >= n."""
    return ((n + k - 1) // k) * k
