"""Command-line driver.

Replaces the reference's hard-coded ``__main__`` block
(``DPathSim_APVPA.py:112-180``) with a real CLI::

    dpathsim --dataset dblp/dblp_small.gexf --source "Didier Dubois" \
             --backend jax --metapath APVPA --output out.log
"""

from __future__ import annotations

import argparse
import os
import sys

from .backends.base import available_backends
from .config import RunConfig
from .engine import build
from .ops.pathsim import VARIANTS
from .utils.logging import RunLogger


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dpathsim",
        description="TPU-native meta-path similarity (PathSim) over HINs",
    )
    p.add_argument("--dataset", default=RunConfig.dataset, help="GEXF file path")
    p.add_argument(
        "--backend",
        default="jax",
        choices=available_backends(),
        help="execution backend",
    )
    p.add_argument(
        "--metapath",
        default="APVPA",
        help="metapath spec, e.g. APVPA; comma-separate several "
        "(e.g. APVPA,APTPA,APA) for batched multi-path scoring",
    )
    p.add_argument(
        "--weights",
        default=None,
        help="comma-separated per-metapath ensemble weights (multi-path mode)",
    )
    p.add_argument("--variant", default="rowsum", choices=list(VARIANTS))
    p.add_argument(
        "--platform",
        default="auto",
        choices=("auto", "cpu", "tpu"),
        help="pin the JAX platform before any device touch: 'cpu' never "
        "initializes an accelerator (safe on hosts whose TPU tunnel can "
        "hang); 'tpu' fails loudly instead of silently falling back to "
        "CPU; 'auto' keeps JAX's own resolution",
    )
    p.add_argument(
        "--loader",
        default="auto",
        choices=("auto", "python", "native"),
        help="GEXF loader: 'native' requires the C++ parse+encode path, "
        "'python' forces the pure-Python pipeline (escape hatch), "
        "'auto' prefers native with clean fallback",
    )
    p.add_argument(
        "--tile-rows",
        type=int,
        default=None,
        help="jax-sparse: rows per streaming tile (memory/throughput "
        "trade-off for the million-author regime)",
    )
    p.add_argument(
        "--approx",
        action="store_true",
        help="jax / jax-sparse: waive the f32 exact-integer-count guard "
        "for graphs whose path counts exceed 2^24 (scores stay within "
        "the 1e-5 gate; only the guard is waived)",
    )
    p.add_argument(
        "--factor-format",
        default=None,
        choices=("coo", "blocked", "bitpacked"),
        help="jax-sparse: resident layout of the half-chain factor "
        "(DESIGN.md §29) — compressed layouts hold it in 1/3-1/6 of "
        "the COO bytes, bit-identically; default resolves through "
        "the tuning registry ('coo' when untuned)",
    )
    p.add_argument(
        "--headroom",
        type=float,
        default=0.0,
        help="index-capacity reserve (fraction per node type) so array "
        "shapes — and compiled programs — survive node appends; results "
        "are bit-identical either way (mainly for `serve` update flows; "
        "batch runs rarely need it)",
    )
    p.add_argument("--source", default=None, help="source node label (e.g. author name)")
    p.add_argument("--source-id", default=None, help="source node id (e.g. author_395340)")
    p.add_argument("--output", default=None, help="reference-grammar log file")
    p.add_argument("--metrics", default=None, help="JSONL metrics file")
    p.add_argument("--top-k", type=int, default=0, help="print top-k similar nodes")
    p.add_argument("--all-pairs", action="store_true", help="compute the full score matrix")
    p.add_argument("--n-devices", type=int, default=None, help="devices for sharded backends")
    p.add_argument("--dtype", default="float32", help="device dtype (float64 needs JAX_ENABLE_X64)")
    p.add_argument("--quiet", action="store_true", help="suppress stdout echo")
    p.add_argument(
        "--explain-plan",
        action="store_true",
        help="print the metapath evaluation plan (DP association "
        "order, estimated FLOPs/density per node) as JSON and exit "
        "without computing anything",
    )
    p.add_argument(
        "--profile-dir",
        default=None,
        help="write a jax.profiler device trace (TensorBoard/Perfetto) here",
    )
    p.add_argument(
        "--trace-out",
        default=None,
        help="enable host-side span tracing and write Chrome/Perfetto "
        "trace-event JSON here at exit (bootstrap + run stage tree; "
        "combine with --profile-dir for the device timeline)",
    )
    p.add_argument(
        "--metrics-file",
        default=None,
        help="write one Prometheus textfile snapshot of the obs "
        "registry here at exit (batch analog of serve's periodic "
        "--metrics-file)",
    )
    p.add_argument(
        "--ranking-out",
        default=None,
        help="with --top-k and no --source: write every node's top-k "
        "ranking as TSV here",
    )
    p.add_argument(
        "--checkpoint-dir",
        default=None,
        help="resumable ranking state (jax-sparse: completed row tiles "
        "skipped on restart; jax-sharded: mid-ring resume from the last "
        "checkpointed ring step)",
    )
    p.add_argument(
        "--tuning-table",
        default=None,
        help="measured dispatch table from `dpathsim tune` (JSON); "
        "absent/corrupt/version-mismatched tables degrade to the "
        "built-in heuristics with a tuning_fallback event. Default: "
        "the PATHSIM_TUNING_TABLE env var when set",
    )
    p.add_argument(
        "--no-tuning",
        action="store_true",
        help="ignore any tuning table (env included): every kernel/"
        "tile/bucket knob uses its built-in heuristic",
    )
    p.add_argument(
        "--max-retries",
        type=int,
        default=None,
        help="attempts per failure seam (GEXF load, compile, backend "
        "init, tile execute, checkpoint write); default from "
        "PATHSIM_MAX_RETRIES or 3. 1 disables retries",
    )
    p.add_argument(
        "--no-degrade",
        action="store_true",
        help="fail fast instead of stepping down the backend chain "
        "(jax-sharded→jax→numpy) when backend init keeps failing",
    )
    p.add_argument(
        "--coordinator-address",
        default=None,
        help="multi-host rendezvous address host:port (jax.distributed); "
        "run the same command on every host with its own --process-id",
    )
    p.add_argument(
        "--num-processes",
        type=int,
        default=None,
        help="total processes in the multi-host job",
    )
    p.add_argument(
        "--process-id",
        type=int,
        default=None,
        help="this process's rank in the multi-host job",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    from .resilience import PREEMPTED_EXIT_CODE, Preempted, preemption_handler

    # Subcommand routing: ``dpathsim serve ...`` is the online serving
    # entry point (serving/cli.py); everything else stays the classic
    # flag-driven batch CLI, so existing invocations are untouched.
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        from .serving.cli import serve_main

        try:
            return serve_main(argv[1:])
        except (KeyError, ValueError, FileNotFoundError) as exc:
            msg = exc.args[0] if exc.args else exc
            print(f"error: {msg}", file=sys.stderr)
            return 1
    if argv and argv[0] in ("worker", "router", "fleet-stats"):
        # ``dpathsim worker`` — one serving replica speaking the
        # router-facing async protocol; ``dpathsim router`` — the
        # fault-tolerant fan-out over N of them; ``dpathsim
        # fleet-stats`` — the one-shot merged-fleet summary
        # (router/cli.py).
        from .router.cli import fleet_stats_main, router_main, worker_main

        try:
            entry = {
                "worker": worker_main,
                "router": router_main,
                "fleet-stats": fleet_stats_main,
            }[argv[0]]
            return entry(argv[1:])
        except (KeyError, ValueError, FileNotFoundError) as exc:
            msg = exc.args[0] if exc.args else exc
            print(f"error: {msg}", file=sys.stderr)
            return 1
    if argv and argv[0] == "batch":
        # ``dpathsim batch topk-all/simjoin/resume`` — corpus-scale
        # campaigns with per-block checkpointed resume (batch/cli.py).
        # Preemption is handled inside batch_main (exit 75 + resume
        # hint), so only user-actionable errors are caught here.
        from .batch.cli import batch_main

        try:
            return batch_main(argv[1:])
        except (KeyError, ValueError, FileNotFoundError) as exc:
            msg = exc.args[0] if exc.args else exc
            print(f"error: {msg}", file=sys.stderr)
            return 1
    if argv and argv[0] == "index":
        # ``dpathsim index build/probe`` — MIPS candidate-generation
        # index artifacts for `serve --topk-mode ann` (index/cli.py).
        from .index.cli import index_main

        try:
            return index_main(argv[1:])
        except (KeyError, ValueError, FileNotFoundError) as exc:
            msg = exc.args[0] if exc.args else exc
            print(f"error: {msg}", file=sys.stderr)
            return 1
    if argv and argv[0] == "learned":
        # ``dpathsim learned train/inspect`` — two-tower serving
        # checkpoints distilled from the exact engine for
        # `serve --topk-mode learned` (learned/cli.py).
        from .learned.cli import learned_main

        try:
            return learned_main(argv[1:])
        except (KeyError, ValueError, FileNotFoundError) as exc:
            msg = exc.args[0] if exc.args else exc
            print(f"error: {msg}", file=sys.stderr)
            return 1
    if argv and argv[0] == "lint":
        # ``dpathsim lint`` — the unified invariant-checking static
        # analyzer (analysis/): recompile-safety, lock-discipline,
        # determinism, and wire-contract passes with one baseline/
        # suppression story (DESIGN.md §25). Pure AST work: never
        # initializes a backend.
        from .analysis.cli import lint_main

        try:
            return lint_main(argv[1:])
        except (KeyError, ValueError, FileNotFoundError) as exc:
            msg = exc.args[0] if exc.args else exc
            print(f"error: {msg}", file=sys.stderr)
            return 1
    if argv and argv[0] == "tune":
        # ``dpathsim tune`` — offline autotuner: measure every knob's
        # candidate arms on THIS device and write the dispatch table
        # that --tuning-table / PATHSIM_TUNING_TABLE consume.
        from .tuning.autotuner import tune_main

        try:
            return tune_main(argv[1:])
        except (KeyError, ValueError, FileNotFoundError) as exc:
            msg = exc.args[0] if exc.args else exc
            print(f"error: {msg}", file=sys.stderr)
            return 1

    # SIGTERM/SIGINT become a graceful preemption: the streaming tile
    # loop flushes its in-flight work through the CheckpointManager and
    # raises Preempted; we exit 75 (EX_TEMPFAIL — "re-run me") with a
    # one-line resume hint. A second signal aborts the drain.
    installed = preemption_handler.install()
    try:
        args = build_parser().parse_args(argv)
        _apply_platform(args.platform)  # before ANY backend touch
        _init_multihost(args)  # …and before the profiler, too
        from .utils.profiling import device_trace

        with device_trace(args.profile_dir):
            return _run(args)
    except Preempted as exc:
        print(f"preempted: {exc}", file=sys.stderr)
        return PREEMPTED_EXIT_CODE
    except (KeyError, ValueError, OverflowError, FileNotFoundError) as exc:
        # Known, user-actionable failures render as one clean line; anything
        # unexpected still gets a full traceback.
        msg = exc.args[0] if exc.args else exc
        print(f"error: {msg}", file=sys.stderr)
        return 1
    finally:
        if installed:
            preemption_handler.uninstall()
            preemption_handler.reset()


def _apply_platform(platform: str) -> None:
    """Pin the JAX platform before anything can initialize a backend.

    The reference pins its engine with a hard-coded env var
    (``DPathSim_APVPA.py:146-148``); this is the configurable analog.
    ``cpu`` hard-pins host execution — the Quickstart-safe mode on
    machines whose accelerator tunnel can hang inside device init.
    ``tpu`` only *clears* an inherited cpu pin rather than forcing the
    platform name (TPU plugins register under site-specific names);
    the accelerator presence check happens after backend init, in
    :func:`_require_tpu`.
    """
    if platform == "auto":
        return
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
        return
    if (os.environ.get("JAX_PLATFORMS", "") or "").strip().lower() == "cpu":
        # An inherited cpu pin would make --platform tpu a guaranteed
        # failure; clear it (None = JAX's own resolution, accelerators
        # first) — but only while no backend exists to re-resolve.
        try:
            from jax._src import xla_bridge

            initialized = bool(xla_bridge.backends_are_initialized())
        except Exception:
            initialized = False
        if not initialized:
            jax.config.update("jax_platforms", None)


def _require_tpu() -> None:
    import jax

    if jax.devices()[0].platform == "cpu":
        raise ValueError(
            "--platform tpu: no accelerator available (JAX resolved to "
            "cpu); run with --platform auto/cpu or fix the TPU runtime"
        )


def _init_multihost(args) -> None:
    """jax.distributed rendezvous — the product path onto a multi-host
    mesh (the reference reaches its distributed engine straight from
    ``__main__``, ``DPathSim_APVPA.py:146-168``; this is the analog).
    With no flags this is env-detection only and a single-process no-op,
    so the same command works on a laptop and on every host of a pod."""
    if (
        args.num_processes is not None or args.process_id is not None
    ) and args.coordinator_address is None:
        raise ValueError(
            "--num-processes/--process-id require --coordinator-address"
        )
    from .parallel.multihost import _CLUSTER_ENV_VARS, initialize_multihost

    rendezvous_requested = args.coordinator_address is not None or any(
        v in os.environ for v in _CLUSTER_ENV_VARS
    )
    if "," in args.metapath and rendezvous_requested:
        # Refuse BEFORE the rendezvous — whether requested by flag or by
        # a launcher's env vars: the batched multi-metapath scorer is
        # single-device, so forming a cluster for it would just run N
        # identical copies.
        raise ValueError(
            "multi-metapath mode does not support multi-host rendezvous "
            "(--coordinator-address flags or JAX_COORDINATOR_ADDRESS/"
            "COORDINATOR_ADDRESS env); it always runs the batched "
            "single-device scorer"
        )
    if rendezvous_requested and args.backend != "jax-sharded":
        # Same failure class for every other backend: none of them is
        # cluster-aware, so N processes would each run the identical full
        # computation and interleave appends into any shared --output/
        # --ranking-out/--checkpoint-dir path.
        raise ValueError(
            f"backend {args.backend!r} is single-process; multi-host "
            "rendezvous requires --backend jax-sharded"
        )

    multi = initialize_multihost(
        coordinator_address=args.coordinator_address,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    if multi:
        import jax

        if args.backend != "jax-sharded":
            # Covers clusters formed without any flags/env we can see —
            # e.g. a launcher that ran jax.distributed.initialize()
            # before main(). Same failure class as the pre-rendezvous
            # guard above: N identical single-process computations.
            raise ValueError(
                f"backend {args.backend!r} is single-process; this is a "
                f"{jax.process_count()}-process cluster — use "
                "--backend jax-sharded"
            )
        if jax.process_index() != 0:
            # SPMD compute spans all processes, but host-side artifacts
            # (reference-grammar log, ranking TSV, stdout echo) must be
            # written once — the same command runs on every host, so any
            # shared path would otherwise get N interleaved appends.
            args.output = None
            args.metrics = None
            args.ranking_out = None
            args.quiet = True


def _run(args) -> int:
    if args.max_retries is not None:
        # Seams deep in the stack (per-tile execute, checkpoint write,
        # ring steps) build their policy from the environment — export
        # the flag so EVERY seam honors it, not just the bootstrap ones
        # that receive the policy object explicitly.
        os.environ["PATHSIM_MAX_RETRIES"] = str(args.max_retries)
    if args.explain_plan:
        return _explain_plan(args)
    if "," in args.metapath:
        return _run_multipath(args)
    if args.ranking_out or args.checkpoint_dir:
        # Both flags belong to the all-sources ranking mode (--top-k with
        # no source); refuse bad combinations up front — the source
        # conflict first, since no --top-k value fixes that one.
        if args.source or args.source_id:
            raise ValueError(
                "--ranking-out/--checkpoint-dir rank ALL sources and "
                "cannot be combined with --source/--source-id"
            )
        if not args.top_k:
            raise ValueError(
                "--ranking-out/--checkpoint-dir require --top-k "
                "(the all-sources ranking mode)"
            )
    if args.tile_rows is not None and args.backend != "jax-sparse":
        raise ValueError(
            "--tile-rows tunes the streaming tiled path and requires "
            "--backend jax-sparse"
        )
    if args.factor_format is not None and args.backend != "jax-sparse":
        raise ValueError(
            "--factor-format selects the resident layout of the "
            "sparse half-chain factor and requires --backend jax-sparse"
        )
    if args.approx and args.backend not in ("jax", "jax-sparse"):
        raise ValueError(
            "--approx waives the f32 exact-count guard of the device "
            "backends (jax, jax-sparse); the numpy oracle is f64-exact"
        )
    config = RunConfig(
        dataset=args.dataset,
        backend=args.backend,
        metapath=args.metapath,
        variant=args.variant,
        source=args.source,
        source_id=args.source_id,
        output=args.output,
        metrics=args.metrics,
        all_pairs=args.all_pairs,
        top_k=args.top_k,
        n_devices=args.n_devices,
        dtype=args.dtype,
        loader=args.loader,
        tile_rows=args.tile_rows,
        approx=args.approx,
        factor_format=args.factor_format,
        headroom=args.headroom,
        echo=not args.quiet,
        max_retries=args.max_retries,
        degrade=not args.no_degrade,
        tuning_table=args.tuning_table,
        tuning=not args.no_tuning,
    )

    from . import obs
    from .utils.logging import set_event_sink
    from .utils.profiling import StageTimer

    if args.trace_out:
        obs.configure(tracing=True)

    # One logger + timer for the whole run: bootstrap stage timings
    # (load/encode, metapath compile, backend init) and compute stages
    # all land in the same --metrics JSONL. Registering it as the event
    # sink routes resilience events (retry/degrade/preempt/injection)
    # into the same JSONL stream.
    logger = RunLogger(
        output_path=config.output, echo=config.echo, metrics_path=config.metrics
    )
    set_event_sink(logger)
    timer = StageTimer(logger)
    try:
        return _run_modes(args, config, logger, timer)
    finally:
        set_event_sink(None)
        logger.close()
        if args.trace_out:
            print(obs.dump_trace(args.trace_out), file=sys.stderr)
        if args.metrics_file:
            obs.write_textfile(args.metrics_file)


def _explain_plan(args) -> int:
    """``--explain-plan``: load + compile + plan, never execute. The
    dump is the auditable record of every ordering choice (estimated
    FLOPs/density per node, DP vs left-to-right)."""
    import json

    from .engine import USE_NATIVE_BY_LOADER, load_dataset
    from .ops.metapath import compile_metapath
    from .ops.planner import plan_metapath

    hin = load_dataset(
        args.dataset, use_native=USE_NATIVE_BY_LOADER[args.loader]
    )
    out = {}
    for spec in [s.strip() for s in args.metapath.split(",") if s.strip()]:
        mp = compile_metapath(spec, hin.schema)
        out[mp.name] = plan_metapath(hin, mp).to_dict()
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0


def _run_modes(args, config, logger: RunLogger, timer) -> int:
    hin, metapath, backend, driver = build(config, timer=timer)
    if args.platform == "tpu":
        _require_tpu()  # backend init just resolved the platform
    if config.echo:
        counts = {t: hin.type_size(t) for t in hin.schema.node_types}
        # The reference prints totals at load (DPathSim_APVPA.py:126-127).
        print(f"Total nodes: {sum(counts.values())}")
        print(f"Total edges: {sum(b.nnz for b in hin.blocks.values())}")
        print(f"Node types: {counts}")
        print(f"Metapath {metapath.name}: {list(metapath.steps)} "
              f"(symmetric={metapath.is_symmetric}) backend={backend.name}")

    ran = False
    if args.source or args.source_id:
        result = driver.run_single_source(
            source=args.source or args.source_id,
            by_label=args.source is not None,
            logger=logger,
        )
        ran = True
        if args.top_k:
            print(f"Top-{args.top_k} similar to {result.source_label}:")
            for nid, label, score in driver.top_k(
                args.source or args.source_id,
                k=args.top_k,
                by_label=args.source is not None,
            ):
                print(f"  {score:.6f}  {label} ({nid})")

    if args.top_k and not (args.source or args.source_id):
        # No source = rank every node, the batched form of the
        # reference's whole program. Streaming + resumable on jax-sparse.
        with timer.stage("rank_all"):
            vals, idxs = driver.rank_all(
                k=args.top_k, checkpoint_dir=args.checkpoint_dir
            )
        print(f"Ranked top-{args.top_k} for all {vals.shape[0]} sources")
        if args.ranking_out:
            driver.write_ranking(args.ranking_out, vals, idxs)
            print(f"Ranking written to {args.ranking_out}")
        ran = True

    if args.all_pairs:
        with timer.stage("all_pairs"):
            scores = driver.run_all_pairs()
        n = scores.shape[0]
        print(f"All-pairs scores: {n}x{n}, mean={scores.mean():.6g}, "
              f"max offdiag={_max_offdiag(scores):.6g}")
        ran = True

    if not ran:
        print("Nothing to do: pass --source/--source-id, --top-k, "
              "and/or --all-pairs", file=sys.stderr)
        return 2
    return 0


def _run_multipath(args) -> int:
    """Batched multi-metapath mode: per-path + combined scores, top-k."""
    from .engine import load_dataset
    from .models.multipath import MultiMetapathScorer

    # The batched scorer is a fixed jax pipeline; reject flags it
    # would otherwise silently ignore.
    unsupported = {
        "--backend": args.backend != "jax",
        "--dtype": args.dtype != "float32",
        "--output": args.output is not None,
        "--metrics": args.metrics is not None,
        "--trace-out": args.trace_out is not None,
        "--metrics-file": args.metrics_file is not None,
        "--ranking-out": args.ranking_out is not None,
        "--checkpoint-dir": args.checkpoint_dir is not None,
        "--tile-rows": args.tile_rows is not None,
        "--approx": args.approx,
        "--factor-format": args.factor_format is not None,
        "--headroom": args.headroom != 0.0,
        # the batched scorer has no tuned knobs — refuse rather than
        # silently ignore a table the user thinks is active
        "--tuning-table": args.tuning_table is not None,
        "--no-tuning": args.no_tuning,
        # no backend chain to step down in this mode — refuse rather
        # than silently ignore
        "--no-degrade": args.no_degrade,
    }
    bad = [flag for flag, hit in unsupported.items() if hit]
    if bad:
        raise ValueError(
            f"multi-metapath mode does not support {', '.join(bad)} "
            "(it always runs the batched jax scorer)"
        )
    if args.n_devices is not None and not (
        args.top_k and not (args.source or args.source_id)
    ):
        # The flag must never be silently ignored: in this mode only the
        # all-sources ranking is sharded (--all-pairs and single-source
        # scoring run on the host).
        raise ValueError(
            "--n-devices in multi-metapath mode applies to the "
            "all-sources ranking (--top-k without --source)"
        )

    from . import resilience
    from .engine import USE_NATIVE_BY_LOADER

    hin = load_dataset(
        args.dataset,
        use_native=USE_NATIVE_BY_LOADER[args.loader],
        policy=resilience.policy_from_env(max_attempts=args.max_retries),
    )
    if args.platform == "tpu":
        _require_tpu()  # load_dataset stays host-side; check before compute
    names = [s.strip() for s in args.metapath.split(",") if s.strip()]
    weights = (
        [float(w) for w in args.weights.split(",")] if args.weights else None
    )
    scorer = MultiMetapathScorer(hin, names, variant=args.variant)
    if not args.quiet:
        print(f"Batched metapaths: {scorer.names}")
        gw = scorer.global_walks()
        denom_label = (
            "max global walk" if args.variant == "rowsum"
            else "max diag(M)"
        )
        for r, name in enumerate(scorer.names):
            print(f"  {name}: {denom_label} {int(gw[r].max())}")

    ran = False
    if args.source or args.source_id:
        node_type = scorer.metapaths[0].source_type
        idx = hin.resolve_source(
            node_type, label=args.source or None,
            node_id=args.source_id,
        )
        k = args.top_k or 10
        vals, idxs = scorer.topk_row(idx, k=k, weights=weights)
        labels = hin.indices[node_type].labels
        print(f"Top-{k} similar to {labels[idx]} (combined {scorer.names}):")
        for v, j in zip(vals, idxs):
            print(f"  {v:.6f}  {labels[j]} ({hin.indices[node_type].ids[j]})")
        ran = True
    if args.top_k and not (args.source or args.source_id):
        # All-sources ensemble ranking — sharded over a dp mesh when
        # --n-devices is given (models/multipath.topk_sharded), host
        # argpartition otherwise.
        if args.n_devices is not None:
            vals, idxs = scorer.topk_sharded(
                k=args.top_k, weights=weights, n_devices=args.n_devices
            )
            how = f"sharded over {args.n_devices} devices"
        else:
            vals, idxs = scorer.topk(k=args.top_k, weights=weights)
            how = "host"
        print(
            f"Ranked top-{vals.shape[1]} for all {vals.shape[0]} sources "
            f"(combined {scorer.names}, {how})"
        )
        ran = True
    if args.all_pairs:
        comb = scorer.combined_scores(weights)
        print(
            f"Combined all-pairs scores: {comb.shape[0]}x{comb.shape[1]}, "
            f"mean={comb.mean():.6g}, max offdiag={_max_offdiag(comb):.6g}"
        )
        ran = True
    if not ran:
        print("Nothing to do: pass --source/--source-id, --top-k, "
              "and/or --all-pairs", file=sys.stderr)
        return 2
    return 0


def _max_offdiag(scores) -> float:
    import numpy as np

    m = scores.copy()
    np.fill_diagonal(m, -np.inf)
    return float(m.max())


if __name__ == "__main__":
    raise SystemExit(main())
