"""The ``serve`` wire protocol: JSONL over stdin/stdout.

One JSON object per line in, one per line out — the same transport
every piece of this repo's tooling already speaks (metrics JSONL,
checkpoint manifests), and the tier-1 test suite can drive it through a
pipe with no network dependency. A network front (HTTP, gRPC) would be
a thin adapter over :func:`handle_request`; the protocol layer is
deliberately transport-free.

Requests::

    {"id": 1, "op": "topk", "source": "Didier Dubois", "k": 10}
    {"id": 2, "op": "topk", "row": 17}
    {"id": 3, "op": "scores", "source_id": "author_395340"}
    {"id": 4, "op": "stats"}
    {"id": 5, "op": "invalidate"}
    {"id": 6, "op": "ping"}
    {"id": 7, "op": "shutdown"}
    {"id": 8, "op": "update",
     "add_nodes": [{"type": "author", "id": "a_new", "label": "A. New"}],
     "add_edges": [{"rel": "author_of", "src": "a_new", "dst": "paper_7"}],
     "remove_edges": [{"rel": "author_of", "src_row": 4, "dst_row": 17}]}

The ``update`` op is the delta-ingestion entry point (data/delta.py): a
warm service absorbs the batch without a reload — O(Δ) patch, zero new
XLA compiles in steady state, and only the affected rows' cache entries
are invalidated. Its result reports which path ran (``mode``:
``delta`` | ``rebuild``), how many score rows the change touched, and
the new chained fingerprint.

Responses mirror the id and carry ``ok``; successes add ``result`` and
``latency_ms``, failures add ``error``. Unknown ops / bad JSON are
per-request errors, never process exits: one malformed client line must
not take the service down for everyone else.
"""

from __future__ import annotations

import json
import time
from typing import IO

from .service import PathSimService

_QUERY_KEYS = ("source", "source_id", "row")


def handle_request(service: PathSimService, req: dict) -> dict:
    """One request dict → one response dict (transport-free core)."""
    rid = req.get("id")
    op = req.get("op", "topk")
    t0 = time.perf_counter()
    try:
        if op == "ping":
            result = {"pong": True}
        elif op == "stats":
            result = service.stats()
        elif op == "invalidate":
            service.invalidate()
            result = {"invalidated": True}
        elif op == "topk":
            kwargs = {key: req.get(key) for key in _QUERY_KEYS}
            if all(v is None for v in kwargs.values()):
                raise KeyError(
                    "topk needs one of source / source_id / row"
                )
            hits = service.topk(k=req.get("k"), **kwargs)
            result = {
                "topk": [
                    {"id": i, "label": lab, "score": s}
                    for i, lab, s in hits
                ]
            }
        elif op == "update":
            from ..data.delta import delta_from_records

            delta = delta_from_records(
                service.hin,
                add_nodes=req.get("add_nodes", ()),
                add_edges=req.get("add_edges", ()),
                remove_edges=req.get("remove_edges", ()),
            )
            result = service.update(delta)
        elif op == "scores":
            row = service.resolve(
                source=req.get("source"),
                source_id=req.get("source_id"),
                row=req.get("row"),
            )
            result = {"row": row,
                      "scores": service.scores_index(row).tolist()}
        else:
            raise KeyError(f"unknown op {op!r}")
    except Exception as exc:  # per-request failure, not process failure
        msg = exc.args[0] if exc.args else repr(exc)
        return {"id": rid, "ok": False, "error": str(msg)}
    return {
        "id": rid,
        "ok": True,
        "result": result,
        "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
    }


def serve_loop(
    service: PathSimService, in_stream: IO[str], out_stream: IO[str]
) -> int:
    """Read JSONL requests until EOF or a ``shutdown`` op; write one
    JSONL response per request, flushed per line (a pipe peer must see
    the answer without waiting for buffering)."""
    for line in in_stream:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as exc:
            resp = {"id": None, "ok": False, "error": f"bad request: {exc}"}
            out_stream.write(json.dumps(resp) + "\n")
            out_stream.flush()
            continue
        if req.get("op") == "shutdown":
            out_stream.write(
                json.dumps({"id": req.get("id"), "ok": True,
                            "result": {"shutdown": True}}) + "\n"
            )
            out_stream.flush()
            return 0
        out_stream.write(json.dumps(handle_request(service, req)) + "\n")
        out_stream.flush()
    return 0
