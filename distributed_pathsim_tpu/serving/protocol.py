"""The ``serve`` wire protocol: JSONL over stdin/stdout.

One JSON object per line in, one per line out — the same transport
every piece of this repo's tooling already speaks (metrics JSONL,
checkpoint manifests), and the tier-1 test suite can drive it through a
pipe with no network dependency. A network front (HTTP, gRPC) would be
a thin adapter over :func:`handle_request`; the protocol layer is
deliberately transport-free.

Requests::

    {"id": 1, "op": "topk", "source": "Didier Dubois", "k": 10}
    {"id": 2, "op": "topk", "row": 17}
    {"id": 3, "op": "scores", "source_id": "author_395340"}
    {"id": 4, "op": "stats"}
    {"id": 5, "op": "invalidate"}
    {"id": 6, "op": "ping"}
    {"id": 7, "op": "shutdown"}
    {"id": 9, "op": "metrics"}
    {"id": 10, "op": "health"}
    {"id": 11, "op": "drain"}
    {"id": 12, "op": "topk", "row": 17, "request_id": "r42",
     "deadline_ms": 250.0}
    {"id": 13, "op": "topk", "row": 17, "mode": "ann"}
    {"id": 14, "op": "refresh_index"}
    {"id": 15, "op": "compact"}

``topk`` and ``scores`` accept an optional defaulted ``metapath``
(default: the service's ``--metapath``, itself defaulted to "APVPA"):
any closed metapath spec over the served schema (``"APA"``,
``"APTPA"``, …) is answered through a lazily-built, memo-sharing
engine on its own coalescer lane — bit-identical to a service built
with that ``--metapath`` (DESIGN.md §28). Yesterday's clients, which
never send the field, are untouched.

``topk`` accepts an optional ``mode`` (``"exact"`` | ``"ann"``,
default the service's ``--topk-mode``): ``ann`` answers through the
MIPS candidate index + exact f64 rerank (DESIGN.md §23) and silently
degrades to the exact path — counted per reason — whenever the index
can't vouch for the row (delta-staled, appended after the build,
recall confidence lost, or no index installed). ``refresh_index``
re-embeds delta-staled index rows in place and advances the index
epoch; it is the in-band twin of the automatic background refresh.

Two optional fields extend EVERY request, defaulted so yesterday's
clients keep working unchanged:

- ``request_id`` — a caller-chosen globally-unique identity (the
  router stamps one per admitted request). Responses echo it, and
  retried/hedged dispatches reuse it so duplicated work is
  *idempotent*: the receiver can dedup, and whoever fans responses
  back out keeps only the first. Absent → responses omit it.
- ``deadline_ms`` — the caller's remaining time budget, counted from
  receipt of the request. An expired budget fails fast with
  ``deadline_exceeded`` instead of dispatching; downstream waits and
  retries (:class:`~..resilience.Deadline` threaded into
  ``RetryPolicy``) are clamped so they can never overshoot it.

A third defaulted field, ``trace``, carries distributed trace context
(DESIGN.md §24): ``{"trace_id": ..., "span_id": ...}`` re-roots this
request's spans under the sender's span (the router stamps its
per-attempt dispatch span, so hedges and failovers become sibling
subtrees of one fleet trace), and ``{"sampled": false}`` propagates a
dropped-head sampling decision — the receiver creates zero spans for
the request, keeping the configured 1/N head rate fleet-wide. Absent,
tracing behaves exactly as before. The ``trace`` *op* is the matching
scrape endpoint: it returns this process's span ring (+ pid + wall
anchor) for the router's stitched Perfetto export and flight-recorder
dumps.

The ``health`` op is the heartbeat/probe endpoint: O(1) liveness plus
the load signals a router routes on (queue depth, in-flight count) and
the replica-consistency token ``(base_fp, delta_seq)`` that fences a
replica lagging on delta broadcasts. The ``drain`` op is the in-band
graceful-shutdown request (the protocol twin of SIGTERM): stop
accepting, complete in-flight, flush, exit 0.
    {"id": 8, "op": "update",
     "add_nodes": [{"type": "author", "id": "a_new", "label": "A. New"}],
     "add_edges": [{"rel": "author_of", "src": "a_new", "dst": "paper_7"}],
     "remove_edges": [{"rel": "author_of", "src_row": 4, "dst_row": 17}]}

The ``update`` op is the delta-ingestion entry point (data/delta.py): a
warm service absorbs the batch without a reload — O(Δ) patch, zero new
XLA compiles in steady state, and only the affected rows' cache entries
are invalidated. Its result reports which path ran (``mode``:
``delta`` | ``rebuild``), how many score rows the change touched, and
the new chained fingerprint.

The ``metrics`` op is the live-aggregates endpoint (obs/): per-op
latency quantiles (p50/p95/p99 from the streaming histograms — no
samples stored, no logs replayed), cache hit rates per tier, and the
full registry snapshot for tooling. Every op's wall time is also
observed into ``dpathsim_request_seconds{op=...}`` here — the protocol
layer is where "request latency per protocol op" is defined.

Responses mirror the id and carry ``ok``; successes add ``result`` and
``latency_ms``, failures add ``error``. Unknown ops / bad JSON are
per-request errors, never process exits: one malformed client line must
not take the service down for everyone else.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import IO

from ..obs.metrics import get_registry
from ..obs.trace import from_wire, get_tracer
from ..resilience import Deadline, DeadlineExceeded
from ..utils.logging import runtime_event
from .service import PathSimService

_QUERY_KEYS = ("source", "source_id", "row")

# The op vocabulary, registered in one place: scripts/lint_telemetry.py
# statically checks that every op string _dispatch_op handles appears
# here, and tests/test_fleet_obs.py drives every registered op through
# handle_request asserting the request_id echo — so a new op cannot
# land without the idempotency/dedup machinery (router retries, hedges)
# being able to correlate its responses.
PROTOCOL_OPS = frozenset({
    "ping", "stats", "metrics", "health", "invalidate", "topk",
    "refresh_index", "refresh_towers", "update", "scores", "trace",
    "compact",
    # partition-mode exchange ops (DESIGN.md §26): served by
    # PartitionService workers behind `dpathsim router --mode
    # partition`; on a replica service they fail as clean per-request
    # errors. part_update/set_colsum are MUTATING_OPS in the worker
    # runtime, so routed-delta retries dedup by request_id.
    "resolve", "part_info", "set_colsum", "tile_pull", "partial_topk",
    "partial_scores", "part_update",
    # batch-campaign block op (DESIGN.md §31): the router-side block
    # scheduler fans topk-all / simjoin row blocks across replicas;
    # idempotent and read-only, so straggler re-dispatch needs no dedup
    "batch_blocks",
})

# op → (latency-histogram cell, error-counter cell), bound on first use
# so the steady-state path pays cell increments, never registry/label
# lookups (the bind-once discipline service.py and cache.py follow).
# Op cardinality is the fixed protocol vocabulary plus whatever unknown
# op names clients send — those error out and are rare by definition.
_OP_CELLS: dict[str, tuple] = {}


def _op_cells(op: str) -> tuple:
    cells = _OP_CELLS.get(op)
    if cells is None:
        reg = get_registry()
        cells = _OP_CELLS[op] = (
            reg.histogram(
                "dpathsim_request_seconds",
                "protocol request wall time by op",
            ).labels(op=op),
            reg.counter(
                "dpathsim_request_errors_total", "failed protocol requests"
            ).labels(op=op),
        )
    return cells


def _hit_rate(hits: int, misses: int) -> float | None:
    total = hits + misses
    return None if total == 0 else round(hits / total, 6)


def metrics_snapshot(service: PathSimService) -> dict:
    """The ``metrics`` op payload: derived summaries first (what an
    operator asks for), full registry snapshot last (what tooling
    scrapes). The cache hit counts come from the same per-instance
    counters ``stats()`` reports, so the two views can never disagree."""
    reg = get_registry()
    snap = reg.snapshot()  # once: the op summaries below read from it
    ops: dict[str, dict] = {}
    fam = snap.get("dpathsim_request_seconds")
    if fam:
        for entry in fam["values"]:
            if not entry["count"]:
                continue  # bound-but-never-observed cell: no summary
            name = entry["labels"].get("op", "?")
            ops[name] = {
                "count": entry["count"],
                "p50_ms": round(entry["p50"] * 1e3, 4),
                "p95_ms": round(entry["p95"] * 1e3, 4),
                "p99_ms": round(entry["p99"] * 1e3, 4),
                "mean_ms": round(
                    entry["sum"] / max(entry["count"], 1) * 1e3, 4
                ),
            }
    rc, tc = service.result_cache, service.tile_cache
    return {
        "ops": ops,
        "caches": {
            "result": {
                "hits": rc.hits, "misses": rc.misses,
                "hit_rate": _hit_rate(rc.hits, rc.misses),
            },
            "tile": {
                "hits": tc.hits, "misses": tc.misses,
                "hit_rate": _hit_rate(tc.hits, tc.misses),
            },
        },
        "enabled": {
            "metrics": reg.enabled, "tracing": get_tracer().enabled,
        },
        "registry": snap,
    }


def _dispatch_op(
    service: PathSimService, op: str, req: dict,
    deadline: Deadline | None = None,
):
    """The op table: one request's work, exceptions propagating to the
    caller's per-request error envelope."""
    if op == "ping":
        return {"pong": True}
    if op == "stats":
        return service.stats()
    if op == "metrics":
        return metrics_snapshot(service)
    if op == "health":
        return service.health()
    if op == "invalidate":
        service.invalidate()
        return {"invalidated": True}
    if op == "topk":
        kwargs = {key: req.get(key) for key in _QUERY_KEYS}
        if all(v is None for v in kwargs.values()):
            raise KeyError("topk needs one of source / source_id / row")
        hits = service.topk(
            k=req.get("k"),
            timeout_s=deadline.remaining_s() if deadline else None,
            mode=req.get("mode"),
            metapath=req.get("metapath"),
            **kwargs,
        )
        return {
            "topk": [
                {"id": i, "label": lab, "score": s} for i, lab, s in hits
            ]
        }
    if op == "refresh_index":
        return service.refresh_index()
    if op == "refresh_towers":
        # absorb the patched graph into the learned tier (re-embed
        # stale + appended rows in place); idempotent — re-running
        # re-absorbs an already-current snapshot as a no-op
        return service.refresh_towers()
    if op == "compact":
        # force one background-style compaction synchronously
        # (serving/compact.py): re-encode with fresh pow-2 headroom,
        # hot-swap under the swap lock, token and caches preserved.
        # Idempotent by construction — re-running it re-encodes the
        # same logical graph — so router retries need no dedup.
        return service.compact()
    if op == "trace":
        # the span-ring scrape: the router's fleet-trace export and
        # flight-recorder dumps collect each worker's ring through
        # this op and stitch them (obs/fleet.py). Bounded payload —
        # the ring can hold 200k spans and the wire is one JSON line.
        limit = req.get("limit")
        return get_tracer().export_state(
            limit=int(limit) if limit else 20_000
        )
    if op == "update":
        from ..data.delta import delta_from_records

        delta = delta_from_records(
            service.hin,
            add_nodes=req.get("add_nodes", ()),
            add_edges=req.get("add_edges", ()),
            remove_edges=req.get("remove_edges", ()),
        )
        return service.update(delta, want_rows=bool(req.get("want_rows")))
    if op == "scores":
        row = service.resolve(
            source=req.get("source"),
            source_id=req.get("source_id"),
            row=req.get("row"),
            metapath=req.get("metapath"),
        )
        return {
            "row": row,
            "scores": service.scores_index(
                row, metapath=req.get("metapath")
            ).tolist(),
        }
    if op == "batch_blocks":
        # one batch-campaign row block (router/batch.py scheduler):
        # answered through the same backend calls the oracle parity
        # tests pin, fenced on the campaign's (base_fp, delta_seq)
        handler = getattr(service, "batch_blocks", None)
        if handler is None:
            raise KeyError(
                "op 'batch_blocks' requires a replica service "
                "(partition workers serve partial_* ops only)"
            )
        return handler(req)
    if op == "resolve":
        # label/id → global dense row; any worker answers (partition
        # workers keep FULL index spaces — only edges are sliced)
        return {
            "row": service.resolve(
                source=req.get("source"),
                source_id=req.get("source_id"),
                row=req.get("row"),
            )
        }
    if op == "part_info":
        return _partition_op(service, "part_info", req)
    if op == "set_colsum":
        return _partition_op(service, "set_colsum", req)
    if op == "tile_pull":
        return _partition_op(service, "tile_pull", req)
    if op == "partial_topk":
        return _partition_op(service, "partial_topk", req)
    if op == "partial_scores":
        return _partition_op(service, "partial_scores", req)
    if op == "part_update":
        return _partition_op(service, "part_update", req)
    raise KeyError(f"unknown op {op!r}")


def _partition_op(service, op: str, req: dict):
    """Partition-exchange dispatch: clean per-request error on a
    replica service (the op vocabulary is shared; the capability is
    not)."""
    handler = getattr(service, op, None)
    if handler is None:
        raise KeyError(
            f"op {op!r} requires a partition worker "
            "(dpathsim worker --partition-index ...)"
        )
    return handler(req)


def handle_request(service: PathSimService, req: dict) -> dict:
    """One request dict → one response dict (transport-free core)."""
    rid = req.get("id")
    op = req.get("op", "topk")
    # the end-to-end time budget, counted from receipt; expired budgets
    # fail fast — dispatching work nobody is still waiting for wastes
    # the very capacity an overloaded caller needs back
    deadline = Deadline.from_ms(req.get("deadline_ms"))
    request_id = req.get("request_id")
    latency_cell, error_cell = _op_cells(op)
    t0 = time.perf_counter()
    try:
        if deadline is not None and deadline.expired:
            raise DeadlineExceeded(
                f"deadline_ms={req.get('deadline_ms')} expired on arrival"
            )
        # protocol-level span: the outermost segment of a served
        # request's trace (the serve.request root parents under it on
        # query ops). A ``trace`` field on the wire re-roots it under
        # the SENDING process's span — the router's dispatch span —
        # so the fleet export stitches one cross-process tree; a
        # ``sampled: false`` context suppresses every span downstream
        # (the head decision travels with the request).
        rctx = from_wire(req.get("trace"))
        tracer = get_tracer()
        activation = (
            tracer.activate(rctx) if rctx is not None
            else contextlib.nullcontext()
        )
        with activation:
            with tracer.span("serve.op", op=op):
                result = _dispatch_op(service, op, req, deadline=deadline)
    except Exception as exc:  # per-request failure, not process failure
        latency_cell.observe(time.perf_counter() - t0)
        error_cell.inc()
        msg = exc.args[0] if exc.args else repr(exc)
        resp = {"id": rid, "ok": False, "error": str(msg)}
        if getattr(exc, "transient", False):
            # e.g. a partition worker mid colsum-exchange: the router
            # should retry/fence, not surface a hard failure
            resp["transient"] = True
        if isinstance(exc, DeadlineExceeded) or (
            deadline is not None and deadline.expired
        ):
            # machine-readable cause: a router must know "out of time"
            # (do NOT reroute) from "this replica failed" (do reroute)
            resp["deadline_exceeded"] = True
        if request_id is not None:
            resp["request_id"] = request_id
        return resp
    latency_s = time.perf_counter() - t0
    latency_cell.observe(latency_s)
    resp = {
        "id": rid,
        "ok": True,
        "result": result,
        "latency_ms": round(latency_s * 1e3, 3),
    }
    if request_id is not None:
        resp["request_id"] = request_id
    return resp


def _drain(service: PathSimService, reason: str) -> None:
    """The graceful-drain epilogue, shared by the in-band ``drain`` op
    and SIGTERM: wait out the in-flight pipeline (every accepted request
    completes — the zero-lost-request half of the contract), then emit
    the final accounting event so the metrics channel records the
    shutdown state."""
    service.coalescer.drain()
    runtime_event(
        "serve_drain",
        reason=reason,
        requests=service.coalescer.dispatched_requests,
        shed=service.coalescer.shed_count,
    )


def serve_loop(
    service: PathSimService, in_stream: IO[str], out_stream: IO[str]
) -> int:
    """Read JSONL requests until EOF or a ``shutdown`` op; write one
    JSONL response per request, flushed per line (a pipe peer must see
    the answer without waiting for buffering).

    SIGTERM (via the resilience preemption handler, installed by the
    serve CLI) and the in-band ``drain`` op both trigger a *graceful
    drain* instead of the batch CLI's checkpoint-and-exit-75: the
    current request completes and is answered, the coalescer pipeline
    flushes, a final ``serve_drain`` event lands on the metrics channel,
    and the loop returns 0 — no accepted request is ever dropped. Lines
    not yet read when the drain begins were never accepted; the closed
    response stream is the client's signal to fail them over. (A drain
    latched mid-wait takes effect at the next protocol event — the next
    request line or EOF — because the reader blocks in the stream.)"""
    from ..resilience import preemption_handler

    for line in in_stream:
        if preemption_handler.requested():
            # a signal landed while we were blocked on the read: the
            # just-read line was never accepted — drain and exit before
            # handling it (its sender sees EOF, not silence-then-drop)
            _drain(service, preemption_handler.reason or "signal")
            return 0
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as exc:
            resp = {"id": None, "ok": False, "error": f"bad request: {exc}"}
            out_stream.write(json.dumps(resp) + "\n")
            out_stream.flush()
            continue
        if req.get("op") == "shutdown":
            out_stream.write(
                json.dumps({"id": req.get("id"), "ok": True,
                            "result": {"shutdown": True}}) + "\n"
            )
            out_stream.flush()
            return 0
        if req.get("op") == "drain":
            out_stream.write(
                json.dumps({"id": req.get("id"), "ok": True,
                            "result": {"draining": True}}) + "\n"
            )
            out_stream.flush()
            _drain(service, "drain op")
            return 0
        out_stream.write(json.dumps(handle_request(service, req)) + "\n")
        out_stream.flush()
        if preemption_handler.requested():
            # SIGTERM during the request just answered: it completed
            # and its response is flushed — now drain and exit
            _drain(service, preemption_handler.reason or "signal")
            return 0
    return 0
