"""Request coalescing: concurrent queries → padded batch dispatches.

The serving hot loop is dispatch-overhead-bound, not FLOP-bound: a
single-row query pays a jit call (and on a tunneled TPU a ~70 ms RPC)
for a GEMV that is microseconds of arithmetic. The coalescer collapses
that overhead: requests land in a bounded queue; a dispatcher thread
forms a batch (up to ``max_batch``, waiting at most ``max_wait_ms`` for
stragglers once the first request arrives), pads it to a power-of-two
shape bucket (buckets.py), and issues ONE batched dispatch.

**Double buffering**: the dispatcher hands the in-flight result (a
device array under JAX's async dispatch) to a completion thread through
a depth-2 queue and immediately forms the next batch — so batch N+1's
GEMM is issued while batch N's results transfer to host and fan back
out to their futures. With a synchronous backend (numpy) the same
structure degenerates gracefully: issue computes, complete routes.

**Admission control**: the queue is bounded (``queue_depth``). When
it's full the submit fails immediately with :class:`LoadShedError` and
a structured ``serve_shed`` event — shedding at the door keeps the
latency of admitted requests bounded instead of letting the queue grow
without limit under overload (the JSONL event stream is how an operator
sees it happening).

Every result is routed to exactly the future whose request produced it
(request identity, not value: two concurrent queries for the same row
each get their own completion) — verified under concurrent submitters
by test.
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Sequence

import numpy as np

from ..utils.logging import runtime_event
from . import buckets as bk


class LoadShedError(RuntimeError):
    """Admission refused: the serving queue is at its bound."""


class ServiceClosed(RuntimeError):
    """The service shut down before (or while) handling the request."""


@dataclasses.dataclass
class Request:
    """One admitted query. ``k`` is the requested top-k; the batch is
    dispatched at the batch's max k and each request gets its prefix."""

    row: int
    k: int
    future: Future
    t_enqueue: float


@dataclasses.dataclass
class BatchStats:
    """Per-dispatch accounting, folded into the service's stats."""

    n_requests: int
    bucket: int
    wait_ms: float


class Coalescer:
    """Batch former + double-buffered dispatch pipeline.

    ``issue(rows_padded, k)`` runs on the dispatcher thread and returns
    an opaque in-flight handle (device array, host array — anything);
    ``complete(handle, rows, requests, k)`` runs on the completion
    thread and must resolve every request's future. Exceptions from
    either land on every future of the batch.
    """

    def __init__(
        self,
        issue: Callable[[np.ndarray, int], Any],
        complete: Callable[[Any, np.ndarray, Sequence[Request], int], None],
        max_batch: int = 32,
        max_wait_ms: float = 2.0,
        queue_depth: int = 256,
        bucket_ladder: tuple[int, ...] | None = None,
        on_batch: Callable[[BatchStats], None] | None = None,
    ):
        self._issue = issue
        self._complete = complete
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.queue_depth = int(queue_depth)
        self.buckets = bucket_ladder or bk.bucket_ladder(self.max_batch)
        if self.buckets[-1] < self.max_batch:
            raise ValueError(
                f"bucket ladder {self.buckets} cannot cover "
                f"max_batch={self.max_batch}"
            )
        self._on_batch = on_batch
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._queue: collections.deque[Request] = collections.deque()
        self._closing = False
        self.shed_count = 0
        self.batch_count = 0
        self.dispatched_requests = 0
        # Depth 2 = the double buffer: one batch completing + one in
        # flight; a third batch blocks at put() until a slot frees,
        # which back-pressures the dispatcher instead of racing ahead.
        self._inflight: queue.Queue = queue.Queue(maxsize=2)
        # Batches issued but not yet fully completed — the drain()
        # condition (a queue can look empty while the completion thread
        # is mid-batch, and reload must not swap state under it).
        self._inflight_n = 0
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="pathsim-serve-dispatch",
            daemon=True,
        )
        self._completer = threading.Thread(
            target=self._complete_loop, name="pathsim-serve-complete",
            daemon=True,
        )
        self._dispatcher.start()
        self._completer.start()

    # -- admission ---------------------------------------------------------

    def submit(self, row: int, k: int) -> Future:
        """Admit one query; returns its Future. Raises
        :class:`LoadShedError` immediately when the queue is at bound —
        overload must fail fast, not queue unboundedly."""
        fut: Future = Future()
        with self._lock:
            if self._closing:
                raise ServiceClosed("serving layer is shut down")
            if len(self._queue) >= self.queue_depth:
                self.shed_count += 1
                shed = self.shed_count
                # stderr echo only every 100th shed: under sustained
                # overload the event stream must not become the load
                runtime_event(
                    "serve_shed",
                    depth=self.queue_depth,
                    total_shed=shed,
                    echo=(shed == 1 or shed % 100 == 0),
                )
                raise LoadShedError(
                    f"serving queue at bound ({self.queue_depth}); "
                    "request shed"
                )
            self._queue.append(
                Request(row=int(row), k=int(k), future=fut,
                        t_enqueue=time.monotonic())
            )
            self._not_empty.notify()
        return fut

    # -- pipeline ----------------------------------------------------------

    def _take_batch(self) -> list[Request] | None:
        """Block for the first request, then coalesce stragglers up to
        ``max_batch`` or ``max_wait``. Returns None on shutdown."""
        with self._lock:
            while not self._queue:
                if self._closing:
                    return None
                self._not_empty.wait()
            # Counted as in flight from the moment the FIRST request
            # leaves the queue — before the straggler wait below, which
            # releases the lock: drain() must not report idle while a
            # batch is half-formed, or reload() could swap the backend
            # under it and dispatch old-graph rows against the new one.
            batch = [self._queue.popleft()]
            self._inflight_n += 1
            deadline = batch[0].t_enqueue + self.max_wait_s
            while len(batch) < self.max_batch:
                if self._queue:
                    batch.append(self._queue.popleft())
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closing:
                    break
                self._not_empty.wait(remaining)
        return batch

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                self._inflight.put(None)  # completion-thread shutdown
                return
            rows = np.array([r.row for r in batch], dtype=np.int64)
            k = max(r.k for r in batch)
            bucket = bk.bucket_for(rows.shape[0], self.buckets)
            padded = bk.pad_rows(rows, bucket)
            wait_ms = (
                time.monotonic() - batch[0].t_enqueue
            ) * 1e3
            try:
                handle = self._issue(padded, k)
            except BaseException as exc:  # route, don't kill the thread
                for r in batch:
                    r.future.set_exception(exc)
                with self._lock:
                    self._inflight_n -= 1
                continue
            self.batch_count += 1
            self.dispatched_requests += len(batch)
            if self._on_batch is not None:
                self._on_batch(
                    BatchStats(
                        n_requests=len(batch), bucket=bucket,
                        wait_ms=wait_ms,
                    )
                )
            self._inflight.put((handle, rows, batch, k))

    def _complete_loop(self) -> None:
        while True:
            item = self._inflight.get()
            if item is None:
                return
            handle, rows, batch, k = item
            try:
                self._complete(handle, rows, batch, k)
            except BaseException as exc:
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(exc)
            finally:
                with self._lock:
                    self._inflight_n -= 1

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout: float = 30.0) -> None:
        """Wait until the queue and the in-flight pipeline are empty
        (reload uses this: no batch may straddle a backend swap)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                idle = not self._queue and self._inflight_n == 0
            if idle:
                return
            time.sleep(0.002)
        raise TimeoutError("serving pipeline did not drain")

    def close(self) -> None:
        with self._lock:
            self._closing = True
            pending = list(self._queue)
            self._queue.clear()
            self._not_empty.notify_all()
        for r in pending:
            r.future.set_exception(ServiceClosed("serving layer shut down"))
        self._dispatcher.join(timeout=10)
        self._completer.join(timeout=10)
