"""Request coalescing: concurrent queries → padded batch dispatches.

The serving hot loop is dispatch-overhead-bound, not FLOP-bound: a
single-row query pays a jit call (and on a tunneled TPU a ~70 ms RPC)
for a GEMV that is microseconds of arithmetic. The coalescer collapses
that overhead: requests land in a bounded queue; a dispatcher thread
forms a batch (up to ``max_batch``, waiting at most ``max_wait_ms`` for
stragglers once the first request arrives), pads it to a power-of-two
shape bucket (buckets.py), and issues ONE batched dispatch.

**Double buffering**: the dispatcher hands the in-flight result (a
device array under JAX's async dispatch) to a completion thread through
a depth-2 queue and immediately forms the next batch — so batch N+1's
GEMM is issued while batch N's results transfer to host and fan back
out to their futures. With a synchronous backend (numpy) the same
structure degenerates gracefully: issue computes, complete routes.

**Admission control**: the queue is bounded (``queue_depth``). When
it's full the submit fails immediately with :class:`LoadShedError` and
a structured ``serve_shed`` event — shedding at the door keeps the
latency of admitted requests bounded instead of letting the queue grow
without limit under overload (the JSONL event stream is how an operator
sees it happening).

Every result is routed to exactly the future whose request produced it
(request identity, not value: two concurrent queries for the same row
each get their own completion) — verified under concurrent submitters
by test.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Sequence

import numpy as np

from ..obs.metrics import get_registry
from ..obs.trace import get_tracer
from ..utils.logging import runtime_event
from . import buckets as bk

# process-wide coalescer sequence: the `instance` label that keeps one
# service's queue-depth gauge / shed counter from merging with another's
_INSTANCE_IDS = itertools.count()


class LoadShedError(RuntimeError):
    """Admission refused: the serving queue is at its bound."""


class ServiceClosed(RuntimeError):
    """The service shut down before (or while) handling the request."""


@dataclasses.dataclass
class Request:
    """One admitted query. ``k`` is the requested top-k; the batch is
    dispatched at the batch's max k and each request gets its prefix.

    ``span`` is the request's ROOT tracing span (opened by whoever
    admitted the query, finished by whoever resolves the future — the
    completion thread on the happy path); ``enq_span`` covers the time
    the request sat in the queue, opened at submit and closed when the
    dispatcher picks it up. Both are None when tracing is off.
    ``t_submit`` is the admission timestamp from the SUBMITTER (taken
    before the cache lookups under the swap lock) — the origin the
    submit-to-resolve latency histogram measures from, shared with the
    cache-hit outcomes so the per-outcome distributions are
    origin-comparable; 0.0 when the caller didn't stamp one.

    ``lane`` routes the batch to one of the service's dispatch paths:
    a batch is single-lane (the batch former never mixes lanes), so
    e.g. ANN candidate-generation probes coalesce into their own
    batched matmul while exact queries keep theirs."""

    row: int
    k: int
    future: Future
    t_enqueue: float
    span: Any = None
    enq_span: Any = None
    t_submit: float = 0.0
    lane: str = "exact"


@dataclasses.dataclass
class BatchStats:
    """Per-dispatch accounting, folded into the service's stats."""

    n_requests: int
    bucket: int
    wait_ms: float


class Coalescer:
    """Batch former + double-buffered dispatch pipeline.

    ``issue(rows_padded, k, lane)`` runs on the dispatcher thread and
    returns an opaque in-flight handle (device array, host array —
    anything); ``complete(handle, rows, requests, k, lane)`` runs on
    the completion thread and must resolve every request's future.
    ``lane`` is the batch's (single) lane — the service dispatches on
    it. Exceptions from either land on every future of the batch.
    """

    def __init__(
        self,
        issue: Callable[[np.ndarray, int], Any],
        complete: Callable[[Any, np.ndarray, Sequence[Request], int], None],
        max_batch: int = 32,
        max_wait_ms: float = 2.0,
        queue_depth: int = 256,
        bucket_ladder: tuple[int, ...] | None = None,
        on_batch: Callable[[BatchStats], None] | None = None,
    ):
        self._issue = issue
        self._complete = complete
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.queue_depth = int(queue_depth)
        self.buckets = bucket_ladder or bk.bucket_ladder(self.max_batch)
        if self.buckets[-1] < self.max_batch:
            raise ValueError(
                f"bucket ladder {self.buckets} cannot cover "
                f"max_batch={self.max_batch}"
            )
        self._on_batch = on_batch
        # obs handles, bound once: per-submit cost is one gauge set;
        # per-batch cost is two histogram observes + a labels() lookup.
        # queue depth and sheds are labeled per coalescer instance —
        # two services in one process must not last-write-wins each
        # other's gauge (a second service's empty queue would mask the
        # first one's backlog) or pool their shed attribution.
        instance = str(next(_INSTANCE_IDS))
        reg = get_registry()
        self._m_queue_depth = reg.gauge(
            "dpathsim_serve_queue_depth", "admitted requests waiting"
        ).labels(instance=instance)
        self._m_shed = reg.counter(
            "dpathsim_serve_shed_total", "requests refused at the bound"
        ).labels(instance=instance)
        # fixed pow-2 ladder, NOT this coalescer's bucket tuple: the
        # family is process-wide and its geometry belongs to the first
        # registrant — two services with different max_batch must not
        # fight over it (the registry raises on conflicting bounds)
        self._m_occupancy = reg.histogram(
            "dpathsim_serve_batch_occupancy",
            "requests per dispatched batch, by shape bucket",
            bounds=tuple(float(1 << i) for i in range(11)),
        )
        # labeled per LANE, cells bound lazily on first dispatch of a
        # lane: an ann probe batch and an exact batch have different
        # wait-time economics (the probe's matmul is tiny, so queue
        # time dominates it sooner), and a fleet-level SLO over batch
        # wait must be able to tell them apart
        self._m_wait_family = reg.histogram(
            "dpathsim_serve_batch_wait_seconds",
            "first-enqueue to dispatch wait per batch, by lane",
        )
        self._m_wait_cells: dict[str, Any] = {}
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._queue: collections.deque[Request] = collections.deque()
        self._closing = False
        self.shed_count = 0
        self.batch_count = 0
        self.dispatched_requests = 0
        # Depth 2 = the double buffer: one batch completing + one in
        # flight; a third batch blocks at put() until a slot frees,
        # which back-pressures the dispatcher instead of racing ahead.
        self._inflight: queue.Queue = queue.Queue(maxsize=2)
        # Batches issued but not yet fully completed — the drain()
        # condition (a queue can look empty while the completion thread
        # is mid-batch, and reload must not swap state under it).
        self._inflight_n = 0
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="pathsim-serve-dispatch",
            daemon=True,
        )
        self._completer = threading.Thread(
            target=self._complete_loop, name="pathsim-serve-complete",
            daemon=True,
        )
        self._dispatcher.start()
        self._completer.start()

    # -- load signals ------------------------------------------------------

    @property
    def depth(self) -> int:
        """Admitted-but-undispatched requests right now (the health
        op's queue-depth signal; routers shed/route on it)."""
        with self._lock:
            return len(self._queue)

    @property
    def inflight(self) -> int:
        """Batches issued but not yet fully completed."""
        with self._lock:
            return self._inflight_n

    # -- admission ---------------------------------------------------------

    def submit(self, row: int, k: int, span=None,
               t_submit: float = 0.0, lane: str = "exact") -> Future:
        """Admit one query; returns its Future. Raises
        :class:`LoadShedError` immediately when the queue is at bound —
        overload must fail fast, not queue unboundedly.

        ``span``: the request's root tracing span, carried through the
        pipeline so the completion thread can finish it; an ``enqueue``
        child span opens here and closes when the dispatcher takes the
        request — queue time is where an overloaded server's p99 hides,
        so it must be its own segment in the trace."""
        fut: Future = Future()
        tracer = get_tracer()
        # only under a live root: an unsampled request (head sampling,
        # obs/trace.py) must create zero spans anywhere downstream —
        # a parentless enqueue here would start an orphan trace
        enq = (
            tracer.start_span(
                "serve.enqueue", parent=span.context, row=int(row)
            )
            if span is not None
            else None
        )
        with self._lock:
            if self._closing:
                # seal the just-opened enqueue segment before bailing:
                # an unfinished span never lands in the ring, and the
                # trace would silently lose its queue segment
                tracer.finish(enq, outcome="closed")
                raise ServiceClosed("serving layer is shut down")
            if len(self._queue) >= self.queue_depth:
                self.shed_count += 1
                shed = self.shed_count
                self._m_shed.inc()
                tracer.finish(enq, outcome="shed")
                # stderr echo only every 100th shed: under sustained
                # overload the event stream must not become the load
                runtime_event(
                    "serve_shed",
                    depth=self.queue_depth,
                    total_shed=shed,
                    echo=(shed == 1 or shed % 100 == 0),
                )
                raise LoadShedError(
                    f"serving queue at bound ({self.queue_depth}); "
                    "request shed"
                )
            self._queue.append(
                Request(row=int(row), k=int(k), future=fut,
                        t_enqueue=time.monotonic(), span=span,
                        enq_span=enq, t_submit=t_submit, lane=lane)
            )
            self._m_queue_depth.set(len(self._queue))
            self._not_empty.notify()
        return fut

    # -- pipeline ----------------------------------------------------------

    def _take_batch(self) -> list[Request] | None:
        """Block for the first request, then coalesce stragglers up to
        ``max_batch`` or ``max_wait``. Returns None on shutdown.

        Batches are single-lane: the head request's lane defines the
        batch, and coalescing stops at the first queued request of a
        different lane (FIFO order is preserved — the other lane heads
        the next batch), so an exact batch and an ANN probe batch can
        never be padded into one dispatch."""
        with self._lock:
            while not self._queue:
                if self._closing:
                    return None
                self._not_empty.wait()
            # Counted as in flight from the moment the FIRST request
            # leaves the queue — before the straggler wait below, which
            # releases the lock: drain() must not report idle while a
            # batch is half-formed, or reload() could swap the backend
            # under it and dispatch old-graph rows against the new one.
            batch = [self._queue.popleft()]
            self._inflight_n += 1
            lane = batch[0].lane
            deadline = batch[0].t_enqueue + self.max_wait_s
            while len(batch) < self.max_batch:
                if self._queue:
                    if self._queue[0].lane != lane:
                        break
                    batch.append(self._queue.popleft())
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closing:
                    break
                self._not_empty.wait(remaining)
        return batch

    def _dispatch_loop(self) -> None:
        tracer = get_tracer()
        while True:
            batch = self._take_batch()
            if batch is None:
                self._inflight.put(None)  # completion-thread shutdown
                return
            with self._lock:
                self._m_queue_depth.set(len(self._queue))
            rows = np.array([r.row for r in batch], dtype=np.int64)
            k = max(r.k for r in batch)
            bucket = bk.bucket_for(rows.shape[0], self.buckets)
            padded = bk.pad_rows(rows, bucket)
            wait_ms = (
                time.monotonic() - batch[0].t_enqueue
            ) * 1e3
            # The thread hop: the batch's dispatch span parents to the
            # first TRACED request's root (a span has exactly one
            # parent), so that head trace contains the device work
            # directly. Every member's enqueue span (opened on its
            # submitter thread) closes here carrying
            # batch_span=<trace>:<span> naming the shared dispatch span
            # — the link non-head traces reach the device work through,
            # and what the bench audit resolves. A batch with no traced
            # member (head sampling) creates no spans at all.
            head = next((r for r in batch if r.span is not None), None)
            dispatch = (
                tracer.start_span(
                    "serve.dispatch", parent=head.span.context,
                    n=len(batch), bucket=bucket, k=k,
                    lane=batch[0].lane,
                )
                if head is not None
                else None
            )
            link = (
                f"{dispatch.trace_id}:{dispatch.span_id}"
                if dispatch is not None else None
            )
            for r in batch:
                if link is not None:
                    tracer.finish(r.enq_span, batch_span=link)
                else:
                    tracer.finish(r.enq_span)
            self._m_occupancy.observe(len(batch), bucket=bucket)
            lane = batch[0].lane
            wait_cell = self._m_wait_cells.get(lane)
            if wait_cell is None:
                wait_cell = self._m_wait_cells[lane] = (
                    self._m_wait_family.labels(lane=lane)
                )
            wait_cell.observe(wait_ms / 1e3)
            try:
                dev = (
                    tracer.start_span(
                        "serve.device_execute",
                        parent=dispatch.context, bucket=bucket,
                    )
                    if dispatch is not None
                    else None
                )
                try:
                    handle = self._issue(padded, k, batch[0].lane)
                finally:
                    tracer.finish(dev)
            except BaseException as exc:  # route, don't kill the thread
                tracer.finish(dispatch, error=repr(exc))
                for r in batch:
                    r.future.set_exception(exc)
                    tracer.finish(r.span, outcome="error")
                with self._lock:
                    self._inflight_n -= 1
                continue
            tracer.finish(dispatch)
            self.batch_count += 1
            self.dispatched_requests += len(batch)
            if self._on_batch is not None:
                self._on_batch(
                    BatchStats(
                        n_requests=len(batch), bucket=bucket,
                        wait_ms=wait_ms,
                    )
                )
            self._inflight.put(
                (handle, rows, batch, k,
                 dispatch.context if dispatch else None)
            )

    def _complete_loop(self) -> None:
        tracer = get_tracer()
        while True:
            item = self._inflight.get()
            if item is None:
                return
            handle, rows, batch, k, dispatch_ctx = item
            try:
                # activate() re-roots this worker thread into the
                # batch's trace: spans the completion callback opens
                # (host transfer, cache fill) parent under it.
                # child_span, not span: a batch whose traced head was
                # sampled out (ctx None) must not start orphan traces.
                with tracer.activate(dispatch_ctx):
                    with tracer.child_span("serve.complete", n=len(batch)):
                        self._complete(handle, rows, batch, k,
                                       batch[0].lane)
            except BaseException as exc:
                for r in batch:
                    # same guard for span and future: members the
                    # completion callback already resolved (and whose
                    # root span it already finished) must not be
                    # re-marked as errors
                    if not r.future.done():
                        r.future.set_exception(exc)
                        tracer.finish(r.span, outcome="error")
            finally:
                with self._lock:
                    self._inflight_n -= 1

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout: float = 30.0) -> None:
        """Wait until the queue and the in-flight pipeline are empty
        (reload uses this: no batch may straddle a backend swap)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                idle = not self._queue and self._inflight_n == 0
            if idle:
                return
            time.sleep(0.002)
        raise TimeoutError("serving pipeline did not drain")

    def close(self) -> None:
        with self._lock:
            self._closing = True
            pending = list(self._queue)
            self._queue.clear()
            self._not_empty.notify_all()
        tracer = get_tracer()
        for r in pending:
            r.future.set_exception(ServiceClosed("serving layer shut down"))
            tracer.finish(r.enq_span, outcome="closed")
            tracer.finish(r.span, outcome="closed")
        self._dispatcher.join(timeout=10)
        self._completer.join(timeout=10)
