"""Serving-side ANN state: candidates → exact f64 rerank, with guards.

The service owns one :class:`AnnState` when ``--topk-mode ann`` (or an
``--index`` artifact) is configured. It bundles:

- the :class:`~..index.CentroidIndex` (probe = one batched matmul);
- the half-chain factor C and denominator vector d snapshotted at the
  index's consistency token — the exact-rerank inputs. Counts are
  integers, so the snapshot's candidate scores are bit-identical to
  the live backend's for every row the delta machinery has not marked
  affected (PR-3's affected-rows soundness is exactly the statement
  that unaffected rows' score rows did not change); affected rows are
  stale in the index and answer through the exact path until refresh.
- **shadow-recall confidence**: every Nth ANN dispatch also runs the
  exact oracle for its row and folds recall@k into
  ``dpathsim_ann_recall_ratio``. When the measured ratio drops below
  the floor (enough samples seen), ANN answering disables itself —
  every query falls back to exact until a refresh/rebuild restores
  confidence. "Automatic exact fallback when recall confidence is
  low" is this, measured, not a heuristic guess.

Fallback taxonomy (``dpathsim_ann_fallbacks_total{reason=...}``):
``stale`` (row touched by an un-refreshed delta), ``uncovered`` (row
appended after the build — the index has never seen it),
``degenerate`` (zero denominator: the exact path's all-zero answer is
already O(1)), ``low_confidence`` (shadow gate tripped), ``no_index``
(ann requested but no index installed).
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..obs.metrics import get_registry
from ..ops import pathsim
from ..utils.logging import runtime_event

FALLBACK_REASONS = (
    "stale", "uncovered", "degenerate", "low_confidence", "no_index",
)


class AnnState:
    """One service's ANN answering state. Thread discipline: eligibility
    checks run under the service's swap lock; rerank/shadow run on the
    coalescer's completion thread; refresh swaps the snapshots under
    the swap lock with the pipeline drained."""

    def __init__(
        self,
        index,
        c64: np.ndarray,
        d: np.ndarray,
        nprobe: int,
        cand_mult: int,
        variant: str = "rerank-all",
        shadow_every: int = 64,
        recall_floor: float = 0.98,
        min_shadow: int = 8,
    ):
        if variant not in ("rerank-all", "shortlist"):
            raise ValueError(f"unknown ann probe variant {variant!r}")
        self.index = index
        self.c64 = np.asarray(c64, dtype=np.float64)
        self.c64.flags.writeable = False
        self.d = np.asarray(d, dtype=np.float64)
        self.nprobe = int(nprobe)
        self.cand_mult = int(cand_mult)
        self.variant = variant
        # rerank-all reads the half-chain factor through per-cluster
        # packed blocks (contiguous [cap, V] slices per probed
        # cluster — a row-gather over random member ids measured ~2×
        # slower at 65k); rebuilt by rebind_counts after any refresh
        self._blocks: np.ndarray | None = None
        self.route_on_host = False
        if variant == "rerank-all":
            self.rebind_counts()
            import jax

            # tiny routing work: host numpy beats the XLA-CPU call
            # overhead at serving batch sizes; accelerators keep the
            # compiled route
            self.route_on_host = jax.default_backend() == "cpu"
        self.shadow_every = max(int(shadow_every), 0)
        self.recall_floor = float(recall_floor)
        self.min_shadow = int(min_shadow)
        self.enabled = True
        # per-request reranks inside one batch are independent — a
        # small pool keeps every core on the BLAS/numpy work (which
        # releases the GIL) instead of serializing ~1 ms reranks on
        # the single completion thread
        self.pool = ThreadPoolExecutor(
            max_workers=max(2, min(4, os.cpu_count() or 2)),
            thread_name_prefix="pathsim-ann-rerank",
        )
        self._lock = threading.Lock()
        self.shadow_n = 0
        self.recall_sum = 0.0
        self._since_shadow = 0
        reg = get_registry()
        self._m_requests = reg.counter(
            "dpathsim_ann_requests_total",
            "topk requests answered through the ANN path",
        ).labels()
        self._m_fallbacks = reg.counter(
            "dpathsim_ann_fallbacks_total",
            "ann-requested queries answered exactly instead, by reason",
        )
        self._m_recall = reg.gauge(
            "dpathsim_ann_recall_ratio",
            "measured shadow recall@k of the ANN path vs the exact "
            "oracle (cumulative over the shadow samples)",
        ).labels()
        self._m_recall.set(1.0)
        self._m_probe = reg.histogram(
            "dpathsim_ann_probe_seconds",
            "ANN candidate-generation (index probe) latency per batch",
        ).labels()
        self._m_rerank = reg.histogram(
            "dpathsim_ann_rerank_seconds",
            "exact candidate rerank latency per request",
        ).labels()

    # -- eligibility -------------------------------------------------------

    def peek(self, row: int) -> str | None:
        """Eligibility WITHOUT the counter side effect: the fallback
        reason that would apply, or None. Observers (the worker's
        response annotation, the flight recorder's classification)
        read this; only the answering path (:meth:`eligible`) counts —
        otherwise one degraded request would tick the fallback counter
        once per onlooker."""
        with self._lock:
            enabled = self.enabled
        if not enabled:
            return "low_confidence"
        if not self.index.covers(row):
            return "stale" if 0 <= row < self.index.n else "uncovered"
        if not (0 <= row < self.d.shape[0]) or self.d[row] <= 0:
            return "degenerate"
        return None

    def eligible(self, row: int) -> str | None:
        """None when the ANN path may answer ``row``; otherwise the
        fallback reason (also counted)."""
        reason = self.peek(row)
        if reason is not None:
            self.note_fallback(reason)
        return reason

    def note_fallback(self, reason: str) -> None:
        self._m_fallbacks.inc(reason=reason)

    # -- the exact rerank --------------------------------------------------

    def rebind_counts(self) -> None:
        """(Re)pack the C snapshot into index-aligned per-cluster
        blocks [K, cap, V] (f64; pad slots zero). Called at setup and
        after every refresh — the blocks must mirror the index's slot
        layout exactly, or a probed member would rerank against some
        other row's counts."""
        members = self.index.members
        safe = np.maximum(members, 0)
        blocks = self.c64[safe.reshape(-1)].reshape(
            members.shape[0], members.shape[1], self.c64.shape[1]
        )
        blocks[members < 0] = 0.0
        self._blocks = blocks

    def rerank_all(
        self, row: int, mem_row: np.ndarray, top_c_row: np.ndarray,
        k: int, n: int,
    ):
        """``rerank-all`` completion: exact f64 top-k over EVERY member
        of the probed clusters — no approximate shortlist cut at all,
        so recall equals cluster-routing recall. The counts matmul
        reads contiguous packed blocks; pads/self (−1) and
        beyond-logical-n rows (capacity padding) are masked out of the
        tie-ordered selection."""
        q = self.c64[row]
        cap = self._blocks.shape[1]
        counts = np.empty(top_c_row.shape[0] * cap, dtype=np.float64)
        # per-cluster GEMVs over contiguous block VIEWS — a fancy-index
        # gather of the probed blocks would copy ~nprobe·cap·V·8 bytes
        # per query before the matmul even reads them (measured ~40% of
        # the rerank at 65k)
        for j, cl in enumerate(top_c_row):
            counts[j * cap:(j + 1) * cap] = self._blocks[cl] @ q
        cols = mem_row.astype(np.int64)
        cols = np.where(cols >= n, -1, cols)
        d_cand = self.d[np.maximum(cols, 0)]
        scores = pathsim.score_candidates(
            counts[None, :], np.asarray([self.d[row]]), d_cand[None, :]
        )
        vals, idxs = pathsim.topk_from_candidate_scores(
            scores, cols[None, :], k
        )
        return vals[0], idxs[0]

    def candidates_for(
        self, sims_row: np.ndarray, mem_row: np.ndarray, k: int, n: int
    ) -> np.ndarray:
        """Top-C candidate ids for one probed row (C = cand_mult·k,
        clamped to the probed set and to N−1)."""
        n_cand = max(k, min(self.cand_mult * k, n - 1, sims_row.shape[0]))
        cand = self.index.select_candidates(sims_row, mem_row, n_cand)
        return cand[(cand >= 0) & (cand < n)]

    def rerank(self, row: int, cand: np.ndarray, k: int):
        """Exact f64 top-k over the candidate set: integer counts from
        the C snapshot (O(C·V)), shared normalize + tie order with the
        full exact path (ops/pathsim.score_candidates /
        topk_from_candidate_scores) — bit-identical to the full-row
        answer whenever the true top-k is inside ``cand``."""
        cand = np.asarray(cand, dtype=np.int64)
        counts = self.c64[cand] @ self.c64[row]
        scores = pathsim.score_candidates(
            counts[None, :], np.asarray([self.d[row]]), self.d[cand][None, :]
        )
        vals, idxs = pathsim.topk_from_candidate_scores(
            scores, cand[None, :], k
        )
        return vals[0], idxs[0]

    # -- shadow-recall confidence ------------------------------------------

    def should_shadow(self) -> bool:
        if self.shadow_every <= 0:
            return False
        with self._lock:
            self._since_shadow += 1
            if self._since_shadow >= self.shadow_every:
                self._since_shadow = 0
                return True
        return False

    def record_shadow(self, ann_vals, exact_vals, k: int) -> None:
        """Fold one shadow comparison into the confidence gate.
        Recall@k is SCORE recall: a returned item whose exact score is
        ≥ the oracle's k-th score is a hit. On integer-count graphs the
        top-k boundary routinely sits inside a large set of exactly
        tied scores, and the id-based metric would punish returning a
        tie-equivalent member — an answer the exact engine itself only
        prefers by the arbitrary ascending-column convention. A
        genuinely better-scoring member that the index missed is still
        a miss under this metric (ann scores are exact, so the
        comparison is bit-meaningful)."""
        ev = np.asarray(exact_vals)
        av = np.asarray(ann_vals)
        want = ev[np.isfinite(ev)]
        if want.size == 0:
            return
        kth = want.min()
        got = av[np.isfinite(av)]
        recall = min(
            float((got >= kth).sum()) / float(want.size), 1.0
        )
        with self._lock:
            self.shadow_n += 1
            self.recall_sum += recall
            ratio = self.recall_sum / self.shadow_n
            tripped = (
                self.enabled
                and self.shadow_n >= self.min_shadow
                and ratio < self.recall_floor
            )
            if tripped:
                self.enabled = False
            samples = self.shadow_n
        self._m_recall.set(ratio)
        if tripped:
            runtime_event(
                "ann_confidence_lost",
                recall=round(ratio, 4),
                floor=self.recall_floor,
                samples=samples,
            )

    def close(self) -> None:
        self.pool.shutdown(wait=False)

    def reset_confidence(self) -> None:
        """After a refresh/rebuild the old shadow evidence describes a
        different index state — start the gate fresh."""
        with self._lock:
            self.shadow_n = 0
            self.recall_sum = 0.0
            self._since_shadow = 0
            self.enabled = True
        self._m_recall.set(1.0)

    # -- accounting --------------------------------------------------------

    def count_answered(self) -> None:
        self._m_requests.inc()

    def observe_probe(self, seconds: float) -> None:
        self._m_probe.observe(seconds)

    def observe_rerank(self, seconds: float) -> None:
        self._m_rerank.observe(seconds)

    def snapshot(self) -> dict:
        with self._lock:
            ratio = (
                self.recall_sum / self.shadow_n if self.shadow_n else None
            )
            return {
                "enabled": self.enabled,
                "variant": self.variant,
                "nprobe": self.nprobe,
                "cand_mult": self.cand_mult,
                "centroids": self.index.n_centroids,
                "cluster_cap": self.index.cluster_cap,
                "dim": self.index.dim,
                "indexed_rows": self.index.n,
                "stale_rows": self.index.stale_count,
                "token": list(self.index.token),
                "embedding": self.index.meta.get("embedding"),
                "shadow_samples": self.shadow_n,
                "shadow_recall": (
                    round(ratio, 6) if ratio is not None else None
                ),
            }
