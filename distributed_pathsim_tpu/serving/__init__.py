"""Online serving layer: coalescing, shape buckets, multi-tier cache.

See DESIGN.md §18. Public surface:

- :class:`PathSimService` / :class:`ServeConfig` / :func:`build_service`
  — the warm query frontend (service.py);
- :class:`LoadShedError` / :class:`ServiceClosed` — admission and
  lifecycle failures callers handle (coalescer.py);
- :func:`graph_fingerprint` — the cache-identity hash (cache.py);
- :func:`serve_loop` / :func:`handle_request` — the JSONL protocol
  (protocol.py); the ``dpathsim serve`` subcommand lives in cli.py.
"""

from .cache import chain_fingerprint, graph_fingerprint
from .coalescer import LoadShedError, ServiceClosed
from .service import PathSimService, ServeConfig, build_service

__all__ = [
    "PathSimService",
    "ServeConfig",
    "build_service",
    "LoadShedError",
    "ServiceClosed",
    "chain_fingerprint",
    "graph_fingerprint",
]
