"""PartitionService: one shard of a graph too big for one worker.

Where a replica (:class:`PathSimService`) holds the WHOLE graph and
answers whole queries, a partition worker holds a contiguous row-range
slice of the half-chain factor and answers *parts* of queries
(DESIGN.md §26). The distributed pairwise multiply is the row-separable
identity ``M[s, j] = C[s, :] · C[j, :]``: the owner of source row ``s``
serves the V-length factor tile ``C[s, :]`` (``tile_pull``), every
partition scores its OWN rows ``j`` against that tile
(``partial_topk`` / ``partial_scores``), and the router merges with the
PR-7 candidate-restricted exact primitives — bit-identical to a
single-host oracle, ties included, because every number that enters the
merge (pairwise counts, denominators) is an exact integer in f64 and
the selection order is the shared ``ops.pathsim`` tie order at every
hop.

Wire ops served here (all registered in ``PROTOCOL_OPS``; the
request-id dedup/idempotency machinery of the worker runtime covers the
mutating ones):

- ``part_info``    — ownership map + per-held-range colsum contribution
- ``set_colsum``   — install (init) or patch (delta) the global column
                     sum ``g``; denominators ``d = C·g`` follow
- ``tile_pull``    — the source row's factor tile ``C[s, :]`` (sparse)
- ``partial_topk`` — this partition's top-k candidates for one range
- ``partial_scores`` — this partition's full score-row slice
- ``part_update``  — the ROUTED delta: apply the row-filtered edge
                     delta to the held slice (O(Δ) product-rule patch,
                     reusing plan_delta on the sliced HIN), return the
                     Δcolsum contribution the router aggregates
- ``resolve``      — label/id → global row (index spaces stay full)

Fencing state is per-partition: each held range carries a ``row_seq``
(bumped when a routed delta changes rows in that range) and the worker
carries a ``colsum_seq``/``update_seq`` (every delta moves the global
denominators). A partition that missed a broadcast lags the head and
the router fences + replays it in order — the PR-6 fencing story, one
level down.
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import Future

import numpy as np

from ..backends.partition_factors import (
    FactorSlice,
    build_factor_slice,
    patch_factor_slice,
    range_colsums,
)
from ..data.partition import PartitionMap, filter_axis_edges, slice_hin
from ..obs.metrics import get_registry
from ..ops import pathsim
from ..utils.logging import runtime_event
from .cache import graph_fingerprint


class _NotReady(RuntimeError):
    """Raised for partial ops before the colsum exchange (or between a
    staged update and its seal). ``transient = True`` rides into the
    protocol error envelope so the router retries/fences instead of
    failing the query."""

    transient = True


class _NullCoalescer:
    """Shape-compatible stand-in: partition ops are synchronous host
    matmuls on the read thread — there is no pipeline to drain."""

    depth = 0
    inflight = 0
    shed_count = 0
    batch_count = 0
    dispatched_requests = 0

    def drain(self) -> None:
        return None

    def close(self) -> None:
        return None


class _NullCache:
    hits = 0
    misses = 0


@dataclasses.dataclass
class PartitionConfig:
    """Partition-worker knobs (CLI-exposed via ``dpathsim worker``)."""

    variant: str = "rowsum"
    k_default: int = 10
    # Resident layout of the held factor slice (the factor_format
    # tuning knob, DESIGN.md §29): None resolves through the registry
    # with the dense-slice "coo" behavior as the documented default.
    factor_format: str | None = None


class _BackendShim:
    """What the worker loop's ready event and health payload read."""

    name = "partition[numpy]"


class PartitionService:
    """One partition worker's warm state: the held factor slice, the
    global colsum once exchanged, and the per-range fencing seqs.

    Single-threaded by construction: every op runs synchronously on the
    worker loop's read thread (the scatter-gather concurrency lives at
    the router), so there is no lock and no torn state to guard.
    """

    def __init__(
        self,
        hin_full,
        metapath,
        part_index: int,
        n_parts: int,
        replication: int = 2,
        config: PartitionConfig | None = None,
    ):
        self.config = config or PartitionConfig()
        self.variant = self.config.variant
        if self.variant != "rowsum":
            # the diagonal variant's denominator is local (no colsum
            # exchange) — supportable, but untested; refuse loudly
            raise ValueError(
                "partition mode currently serves variant='rowsum' "
                f"(got {self.variant!r})"
            )
        self.metapath = metapath
        self.node_type = metapath.source_type
        n = hin_full.type_size(self.node_type)
        self.pmap = PartitionMap(n=n, p=int(n_parts))
        self.part_index = int(part_index)
        self.replication = max(1, min(int(replication), self.pmap.p))
        self.held = self.pmap.held_by(self.part_index, self.replication)
        # fingerprint the FULL graph before slicing: every partition of
        # the same dataset agrees, which is the router's startup check
        self._base_fp = graph_fingerprint(hin_full)
        self._fp = self._base_fp
        self.hin = slice_hin(
            hin_full, self.node_type,
            [self.pmap.range_of(g) for g in self.held],
        )
        self.index = self.hin.indices[self.node_type]
        fmt = self.config.factor_format
        if fmt is None:
            from .. import tuning

            fmt = str(tuning.choose(
                "factor_format", n=n, default="coo",
            ))
        self.factor_format = fmt
        self.fs: FactorSlice = build_factor_slice(
            self.hin, metapath, self.pmap, self.held,
            factor_format=fmt,
        )
        self.n = self.pmap.n
        # fencing state: per-held-range row epochs + the global
        # denominator epoch (every routed delta advances colsum_seq;
        # row_seq[g] advances only when rows in g re-encode)
        self.row_seq = {g: 0 for g in self.held}
        self.colsum_seq = 0
        self.update_seq = 0
        self._g: np.ndarray | None = None       # global colsum [V]
        self._d_held: np.ndarray | None = None  # denominators, held rows
        # a part_update staged but not yet sealed: {seq, attempt, plan}.
        # Staging mutates NOTHING (prepare/commit): the patch, the hin
        # adoption, and the denominator update all happen at the seal,
        # so an aborted attempt (the router found a range with no live
        # current holder) is discarded for free, and a superseding
        # attempt of the same seq simply replaces the stage.
        self._staged: dict | None = None
        self.coalescer = _NullCoalescer()
        self.result_cache = _NullCache()
        self.tile_cache = _NullCache()
        self.backend = _BackendShim()
        reg = get_registry()
        self._m_partial = reg.histogram(
            "dpathsim_partition_partial_seconds",
            "partition-local partial op wall time by op",
        )
        self._m_score_backend = reg.counter(
            "dpathsim_partition_score_backend_total",
            "partial-op scorings by execution backend (numpy = counted "
            "fallback: no jax or no x64 mode)",
        )
        # jax-backed partial scoring (ROADMAP item 2 debt): the window
        # matvec + candidate normalize run on device when f64 survives
        # there (x64 mode), else the numpy arm — both produce identical
        # bytes because counts are exact integers in f64 and
        # score_candidates is elementwise. Resolved once: the answer
        # cannot change mid-process and the hot path shouldn't re-probe.
        self._jax = pathsim.jax_exact()
        self._win_dev = {}      # (lo, hi) → device mirror of the window
        self._win_seq = None    # update_seq the mirrors were cut at
        reg.gauge(
            "dpathsim_partition_rows_held",
            "factor rows resident on this partition worker",
        ).labels(
            ranges="+".join(str(g) for g in self.held)
        ).set(float(self.fs.n_held))
        reg.gauge(
            "dpathsim_factor_bytes",
            "resident half-chain factor bytes by layout format",
        ).labels(format=self.factor_format).set(
            float(self.fs.factor_bytes())
        )
        runtime_event(
            "partition_ready",
            part_index=self.part_index, partitions=self.pmap.p,
            replication=self.replication, held=list(self.held),
            rows_held=self.fs.n_held, n=self.n, v=self.fs.v,
            base_fp=self._base_fp,
        )

    # -- identity / protocol surface ---------------------------------------

    @property
    def consistency_token(self) -> tuple[str, int]:
        return (self._base_fp, self.update_seq)

    @property
    def ready(self) -> bool:
        return self._d_held is not None

    def resolve(self, source: str | None = None,
                source_id: str | None = None,
                row: int | None = None) -> int:
        if row is not None:
            if not 0 <= int(row) < self.n:
                raise KeyError(f"row {row} out of range [0, {self.n})")
            return int(row)
        return self.hin.resolve_source(
            self.node_type, label=source, node_id=source_id
        )

    def _ident(self, i: int) -> tuple[str, str]:
        if i < len(self.index.ids):
            return self.index.ids[i], self.index.labels[i]
        return f"{self.node_type}_{i}", f"{self.node_type}_{i}"

    def ann_fallback_reason(self, row: int, mode=None):
        return None

    def submit_topk(self, row: int, k: int | None = None, mode=None):
        """Partition workers answer ``partial_topk``, never whole
        queries — a stray replicate-mode dispatch fails cleanly."""
        fut: Future = Future()
        fut.set_exception(RuntimeError(
            "partition worker serves partial_topk, not topk — route "
            "through `dpathsim router --mode partition`"
        ))
        return fut

    def health(self) -> dict:
        return {
            "ok": True,
            "n": self.n,
            "queue_depth": 0,
            "inflight": 0,
            "shed": 0,
            "base_fp": self._base_fp,
            "delta_seq": self.update_seq,
            "fingerprint": self._fp,
            "backend": self.backend.name,
            "index": None,
            "partition": self.partition_state(),
            "compiles": int(
                get_registry().counter(
                    "dpathsim_xla_compiles_total",
                    "XLA backend compilations since process start",
                ).labels().value
            ),
        }

    def partition_state(self) -> dict:
        return {
            "index": self.part_index,
            "partitions": self.pmap.p,
            "replication": self.replication,
            "held": list(self.held),
            "ranges": {
                str(g): list(self.pmap.range_of(g)) for g in self.held
            },
            "rows_held": self.fs.n_held,
            "row_seq": {str(g): self.row_seq[g] for g in self.held},
            "colsum_seq": self.colsum_seq,
            "update_seq": self.update_seq,
            "ready": self.ready,
        }

    def stats(self) -> dict:
        return {
            "n": self.n,
            "metapath": self.metapath.name,
            "variant": self.variant,
            "backend": self.backend.name,
            "fingerprint": self._fp,
            "partition": self.partition_state(),
            "factor_bytes": self.fs.factor_bytes(),
            "factor_format": self.factor_format,
            "obs": {
                "metrics": get_registry().enabled,
            },
        }

    def invalidate(self) -> None:
        return None  # no cache tiers on a partition worker

    def close(self) -> None:
        return None

    # -- colsum exchange ----------------------------------------------------

    def part_info(self, req: dict) -> dict:
        """Ownership map + this worker's colsum contribution per held
        range (exact integer sums — any holder's contribution for a
        range is bit-identical to any other's)."""
        return {
            "partition": self.partition_state(),
            "v": self.fs.v,
            "colsum": {
                str(g): payload
                for g, payload in range_colsums(self.fs, self.held).items()
            },
        }

    def set_colsum(self, req: dict) -> dict:
        """Install (``mode: "init"``), seal (``mode: "delta"``), or
        abort (``mode: "abort"``) — the commit side of the two-phase
        routed delta. A seal applies the staged plan atomically: patch
        the factor slice, adopt the new HIN, patch the colsum, then
        the denominators — unaffected rows get the incremental
        ``d += C·Δg`` (exact: integer dot), re-encoded rows a full
        ``d[i] = C[i]·g_new``. An abort just drops the stage (nothing
        was mutated at stage time)."""
        mode = req.get("mode", "init")
        cols = np.asarray(req.get("cols") or [], dtype=np.int64)
        vals = np.asarray(req.get("vals") or [], dtype=np.float64)
        if mode == "init":
            g = np.zeros(self.fs.v, dtype=np.float64)
            g[cols] = vals
            self._g = g
            self._d_held = self.fs.matvec(g)
            runtime_event(
                "partition_colsum_init", part_index=self.part_index,
                nnz=int(cols.shape[0]), echo=False,
            )
            return {"ready": True, "colsum_seq": self.colsum_seq}
        seq = int(req.get("seq") or 0)
        attempt = int(req.get("attempt") or 0)
        if mode == "abort":
            if self._staged is not None and (
                self._staged["seq"] == seq
                and self._staged["attempt"] == attempt
            ):
                self._staged = None
                runtime_event(
                    "partition_update_aborted",
                    part_index=self.part_index, seq=seq,
                    attempt=attempt, echo=False,
                )
            # an already-dropped/superseded stage aborts idempotently
            return {"aborted": seq, "attempt": attempt}
        if mode != "delta":
            raise ValueError(f"unknown set_colsum mode {mode!r}")
        if self._g is None or self._d_held is None:
            raise ValueError("set_colsum delta before init")
        if self._staged is None or self._staged["seq"] != seq or (
            self._staged["attempt"] != attempt
        ):
            raise ValueError(
                f"set_colsum seq {seq}/attempt {attempt} does not seal "
                "the staged update (staged: "
                f"{None if self._staged is None else (self._staged['seq'], self._staged['attempt'])})"
            )
        plan = self._staged["plan"]
        changed = patch_factor_slice(self.fs, plan.delta_c, self.n)
        self.hin = plan.hin_new
        self.index = self.hin.indices[self.node_type]
        self._fp = plan.fingerprint
        dg = np.zeros(self.fs.v, dtype=np.float64)
        dg[cols] = vals
        self._g = self._g + dg
        if cols.shape[0]:
            self._d_held = self._d_held + self.fs.matvec(dg)
        if changed.shape[0]:
            slots = self.fs.held_slot_of[changed]
            self._d_held[slots] = self.fs.rows_matvec(slots, self._g)
            for g_idx in sorted({
                self.pmap.owner_of(int(r)) for r in changed
            }):
                if g_idx in self.row_seq:
                    self.row_seq[g_idx] += 1
        self._staged = None
        self.colsum_seq = seq
        self.update_seq = seq
        # packed slices may re-bucket patched chunks — keep the
        # memory-headroom gauge current
        get_registry().gauge(
            "dpathsim_factor_bytes",
            "resident half-chain factor bytes by layout format",
        ).labels(format=self.factor_format).set(
            float(self.fs.factor_bytes())
        )
        runtime_event(
            "partition_update_sealed", part_index=self.part_index,
            seq=seq, re_encoded=int(changed.shape[0]), echo=False,
        )
        return {
            "sealed": seq,
            "row_seq": {str(g): self.row_seq[g] for g in self.held},
            "colsum_seq": self.colsum_seq,
        }

    # -- the distributed half-chain multiply --------------------------------

    def tile_pull(self, req: dict) -> dict:
        """The source row's factor tile ``C[s, :]`` (sparse) plus its
        denominator — the boundary exchange every peer partition scores
        against. A pull for a row outside the held ranges redirects
        (the router re-aims at the owner)."""
        row = self.resolve(
            source=req.get("source"), source_id=req.get("source_id"),
            row=req.get("row"),
        )
        if not self.fs.holds(row):
            return {
                "wrong_owner": True, "row": int(row),
                "owner": self.pmap.owner_of(row),
            }
        self._require_ready()
        slot = int(self.fs.held_slot_of[row])
        crow = self.fs.row_dense(slot)
        nz = np.flatnonzero(crow)
        return {
            "row": int(row),
            "cols": [int(c) for c in nz],
            "vals": [float(crow[c]) for c in nz],
            "d_source": float(self._d_held[slot]),
            "seq": self.update_seq,
        }

    def _require_ready(self) -> None:
        if not self.ready:
            # transient: the router retries elsewhere / after catch-up
            raise _NotReady(
                "partition awaiting colsum exchange / update seal"
            )

    def _window(self, g: int):
        if g not in self.fs.range_slots:
            raise KeyError(
                f"partition worker p{self.part_index} does not hold "
                f"range {g} (held: {list(self.held)})"
            )
        lo_slot, hi_slot = self.fs.range_slots[g]
        glo, ghi = self.pmap.range_of(g)
        return lo_slot, hi_slot, glo, ghi

    def _source_tile(self, req: dict):
        cols = np.asarray(req.get("cols") or [], dtype=np.int64)
        vals = np.asarray(req.get("vals") or [], dtype=np.float64)
        c_s = np.zeros(self.fs.v, dtype=np.float64)
        c_s[cols] = vals
        return c_s, float(req.get("d_source") or 0.0)

    def _window_counts(
        self, lo_slot: int, hi_slot: int, c_s: np.ndarray
    ) -> np.ndarray:
        """``C_held[lo:hi] @ c_s`` — exact integer-valued f64 counts on
        the fastest exact arm. The jax arm mirrors the held window to
        the device once per update_seq (a delta invalidates every
        mirror) and is bit-identical to the numpy arm because the
        products and sums are exact integers in f64 under any
        association order; without x64 the mirror would downcast to
        f32, so that configuration takes the counted numpy fallback."""
        if self._jax is None:
            self._m_score_backend.inc(backend="numpy")
            return self.fs.window_dense(lo_slot, hi_slot) @ c_s
        if self._win_seq != self.update_seq:
            self._win_dev.clear()
            self._win_seq = self.update_seq
        dev = self._win_dev.get((lo_slot, hi_slot))
        if dev is None:
            dev = self._jax.device_put(
                self.fs.window_dense(lo_slot, hi_slot)
            )
            self._win_dev[(lo_slot, hi_slot)] = dev
        self._m_score_backend.inc(backend="jax")
        return np.asarray(
            self._jax.numpy.matmul(dev, self._jax.device_put(c_s))
        )

    def partial_topk(self, req: dict) -> dict:
        """This partition's top-k candidates for range ``g``: exact
        integer pairwise counts against the source tile, f64 scores via
        the shared candidate primitive, local top-k in the oracle tie
        order. Global top-k ⊆ union of per-range top-k (the order is
        total), so the router's merge over these candidates is exact."""
        t0 = time.perf_counter()
        self._require_ready()
        g = int(req.get("range") or 0)
        k = int(req.get("k") or self.config.k_default)
        row = int(req.get("row") or 0)
        lo_slot, hi_slot, glo, ghi = self._window(g)
        if hi_slot == lo_slot:
            return {"range": g, "cands": [], "seq": self.update_seq}
        c_s, d_source = self._source_tile(req)
        d_win = self._d_held[lo_slot:hi_slot]
        # exact integer-valued f64 products, jax-backed when x64 holds
        m = self._window_counts(lo_slot, hi_slot, c_s)
        scores = pathsim.score_candidates(
            m[None, :], np.asarray([d_source]), d_win[None, :], xp=np
        )
        cols_global = np.arange(glo, ghi, dtype=np.int64)
        if glo <= row < ghi:
            cols_global = cols_global.copy()
            cols_global[row - glo] = -1  # self pair never ranks
        vals, idxs = pathsim.topk_from_candidate_scores(
            scores, cols_global[None, :], min(k, max(ghi - glo, 1))
        )
        cands = []
        for v, j in zip(vals[0], idxs[0]):
            if not np.isfinite(v):
                continue
            i_id, lab = self._ident(int(j))
            cands.append({
                "col": int(j),
                "m": float(m[int(j) - glo]),
                "d": float(d_win[int(j) - glo]),
                "id": i_id,
                "label": lab,
            })
        self._m_partial.observe(
            time.perf_counter() - t0, op="partial_topk"
        )
        return {"range": g, "cands": cands, "seq": self.update_seq}

    def partial_scores(self, req: dict) -> dict:
        """The full count/denominator slice for range ``g`` — the
        ``scores`` op's partition share (self pair included, exactly as
        the single-host score row has it)."""
        t0 = time.perf_counter()
        self._require_ready()
        g = int(req.get("range") or 0)
        lo_slot, hi_slot, glo, ghi = self._window(g)
        c_s, _ = self._source_tile(req)
        m = self._window_counts(lo_slot, hi_slot, c_s)
        d_win = self._d_held[lo_slot:hi_slot]
        self._m_partial.observe(
            time.perf_counter() - t0, op="partial_scores"
        )
        return {
            "range": g,
            "lo": glo,
            "counts": [float(x) for x in m],
            "denoms": [float(x) for x in d_win],
            "seq": self.update_seq,
        }

    # -- routed deltas -------------------------------------------------------

    def part_update(self, req: dict) -> dict:
        """Phase 1 (PREPARE) of a routed delta: plan the row-filtered
        edge delta against the held slice (plan_delta's product rule on
        the sliced HIN — its ΔC support is confined to held rows by
        construction, so the eventual patch is O(Δ)), stage the plan
        WITHOUT mutating anything, and return the Δcolsum contribution
        per held range for the router to aggregate. Phase 2 commits
        (``set_colsum`` mode=delta) or discards (mode=abort — e.g. the
        router found an affected range with no live current holder,
        where sealing would silently lose that range's contribution).
        A new attempt of the same seq supersedes a stale stage, so a
        lost abort self-heals."""
        from ..data.delta import delta_from_records, plan_delta

        if req.get("add_nodes"):
            raise ValueError(
                "partition mode routes edge deltas only; node appends "
                "re-shape the ownership map — reload the fleet "
                "(DESIGN.md §26)"
            )
        seq = int(req.get("seq") or 0)
        attempt = int(req.get("attempt") or 0)
        if seq != self.update_seq + 1:
            raise ValueError(
                f"part_update seq {seq} out of order "
                f"(applied: {self.update_seq})"
            )
        add, remove = filter_axis_edges(
            self.hin, self.node_type,
            [self.pmap.range_of(g) for g in self.held],
            add_edges=req.get("add_edges") or (),
            remove_edges=req.get("remove_edges") or (),
        )
        delta = delta_from_records(
            self.hin, add_edges=add, remove_edges=remove
        )
        plan = plan_delta(
            self.hin, delta, self.metapath, max_delta_fraction=1.0
        )
        if plan.fallback:
            raise ValueError(
                f"partition delta needs a rebuild ({plan.reason}) — "
                "unsupported in partition mode"
            )
        dc_rows = plan.delta_c.rows.astype(np.int64)
        contrib: dict[str, dict] = {}
        affected: set[int] = set()
        for g in self.held:
            glo, ghi = self.pmap.range_of(g)
            mask = (dc_rows >= glo) & (dc_rows < ghi)
            if not mask.any():
                continue
            affected.add(g)
            dg = np.zeros(self.fs.v, dtype=np.float64)
            np.add.at(
                dg, plan.delta_c.cols[mask],
                plan.delta_c.weights[mask].astype(np.float64),
            )
            nz = np.flatnonzero(dg)
            if nz.shape[0]:
                contrib[str(g)] = {
                    "cols": [int(c) for c in nz],
                    "vals": [float(dg[c]) for c in nz],
                }
        self._staged = {"seq": seq, "attempt": attempt, "plan": plan}
        runtime_event(
            "partition_update_staged", part_index=self.part_index,
            seq=seq, attempt=attempt,
            edge_changes=plan.n_edge_changes,
            ranges=sorted(affected), echo=False,
        )
        return {
            "staged": seq,
            "attempt": attempt,
            "contrib": contrib,
            "re_encoded": int(
                np.unique(dc_rows[dc_rows < self.n]).shape[0]
            ),
            "affected_ranges": sorted(affected),
            "held": list(self.held),
        }
