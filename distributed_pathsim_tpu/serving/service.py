"""PathSimService: a warm, coalescing, caching online query frontend.

Every entry point before this PR was a one-shot batch job: each top-k
query re-paid graph load, backend init, jit compile, and an unbatched
dispatch. The service inverts that. Construction does the expensive
work ONCE — the backend's half factor is assembled and left resident on
device, the denominator vector is prefetched to host f64, and every
serving shape bucket is pre-compiled (``utils.xla_flags.
warm_compile_cache``) — and then queries flow through three tiers:

1. result LRU (cache.py) — repeated (row, k) queries are a dict lookup;
2. hot-tile score cache — a known score row re-selects top-k on host
   for any k, no dispatch;
3. coalesced batched dispatch (coalescer.py) — misses from concurrent
   clients are padded into power-of-two buckets and served by ONE
   batched backend call, double-buffered so bucket N+1's GEMM overlaps
   bucket N's host transfer.

Served results are bit-identical to the offline driver's ``top_k``:
both route through the backend's ``topk_row``/``topk_rows`` arithmetic
(exact integer counts, f64 normalization, (descending score, ascending
column) tie order) — verified by test, padding and batching included.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Sequence

import numpy as np

from ..backends.base import PathSimBackend
from ..obs.metrics import get_registry
from ..obs.trace import get_tracer
from ..ops import pathsim
from ..ops import planner
from ..utils.logging import runtime_event
from .cache import HotTileCache, ResultCache, graph_fingerprint
from .coalescer import BatchStats, Coalescer, Request

# Lane prefix of secondary-metapath dispatches: the coalescer never
# mixes lanes in one batch, so each metapath's queries pad into their
# own batched GEMM against that metapath's engine (the "new coalescer
# lane axis" of the metapath workload design, DESIGN.md §28).
_MP_LANE = "mp:"

# The compaction-swap doorway surface (analysis rule CP001, DESIGN.md
# §30): these internals perform the token-preserving hot-swap and are
# only sound inside _apply_compaction under the swap lock with the
# mid-build replay log in hand. serving/compact.py is the one
# sanctioned caller; everything else compacts via service.compact() or
# the 'compact' protocol op. Parsed by the analyzer as a literal, so
# the rule and this registry cannot drift.
COMPACTION_SURFACE = frozenset({
    "_apply_compaction",
    "_swap_compacted",
})


@dataclasses.dataclass
class MetapathEngine:
    """One lazily-built secondary-metapath serving engine: a warm
    backend for a non-default metapath, sharing the service's
    sub-chain memo, caches, and coalescer. ``fallback_from`` records a
    backend-class degrade (e.g. an asymmetric chain on jax-sparse
    serving through numpy) — results are bit-identical either way."""

    metapath: object
    backend: PathSimBackend
    d: np.ndarray  # f64 denominators, prefetched like the primary's
    n: int
    fallback_from: str | None = None


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs (all CLI-exposed via the ``serve`` subcommand)."""

    max_batch: int = 32
    max_wait_ms: float = 2.0
    queue_depth: int = 256
    cache_entries: int = 4096        # tier-1 result LRU capacity
    tile_cache_bytes: int = 64 << 20  # tier-2 hot-tile budget
    tile_rows: int = 64               # tier-2 eviction granularity
    k_default: int = 10
    warm: bool = True                 # pre-compile buckets at startup
    request_timeout_s: float = 60.0
    batch_events: bool = False        # per-batch JSONL events
    # Delta ingestion: a batch changing more than this fraction of the
    # graph's edges (or exhausting index headroom) rebuilds instead of
    # patching — past it the O(Δ) machinery converges on rebuild cost.
    delta_threshold: float = 0.05
    # -- ANN candidate generation (index/ subsystem, DESIGN.md §23) ----
    # Default answer path: "exact" scores the full O(N) row; "ann"
    # probes the MIPS index for C ≫ k candidates and exact-reranks
    # them (per-request override via the protocol's ``mode`` field).
    topk_mode: str = "exact"
    # Prebuilt `dpathsim index build` artifact; None + mode "ann"
    # builds the struct-embedded index in-process at startup.
    index_path: str | None = None
    # Index geometry / probe knobs: None resolves through the tuning
    # registry (ann_nprobe / ann_cand_mult / ann_centroids /
    # ann_cluster_cap) with the documented heuristics as defaults.
    ann_nprobe: int | None = None
    ann_cand_mult: int | None = None
    ann_centroids: int | None = None
    ann_cluster_cap: int | None = None
    ann_variant: str | None = None   # rerank-all | shortlist
    # Shadow-recall confidence: every Nth ANN dispatch also runs the
    # exact oracle and folds recall@k into dpathsim_ann_recall_ratio;
    # below the floor (after min samples) ANN disables itself until a
    # refresh/rebuild. 0 disables shadowing (benches own their oracle).
    ann_shadow_every: int = 64
    ann_recall_floor: float = 0.98
    ann_min_shadow: int = 8
    # Re-embed delta-staled rows in a background thread after each
    # patch update (stale rows answer exactly in the meantime either
    # way); off = refresh only via the refresh_index op/method.
    ann_auto_refresh: bool = True
    # -- learned candidate generation (learned/ subsystem, §32) --------
    # "learned" topk_mode: trained two-tower candidates, exact-f64
    # reranked — same never-wrong contract as ann, plus cold-start
    # answering (appended rows embed inductively, no full re-embed).
    # A `dpathsim learned train` artifact; None + mode "learned"
    # distills a tower in-process at startup (exact-teacher mining).
    learned_checkpoint: str | None = None
    # In-process training geometry (None resolves the tuned
    # learned_dim / learned_neg_ratio knobs); steps stay small — the
    # exact rerank carries correctness, the tower only needs recall.
    learned_dim: int | None = None
    learned_steps: int = 200
    learned_neg_ratio: float | None = None
    learned_cand_mult: int | None = None
    # Shadow-recall confidence gate (the ann gate's twin): every Nth
    # learned dispatch also runs the exact oracle; measured
    # score-recall below the floor disables the learned arm until a
    # refresh. None floor → the tuned learned_conf_floor knob.
    learned_shadow_every: int = 64
    learned_recall_floor: float | None = None
    learned_min_shadow: int = 8
    # Re-embed delta-staled/appended rows in a background thread after
    # patch updates (stale/cold rows answer by counted fallback in the
    # meantime); cadence (every Nth delta) is the tuned
    # learned_refresh_deltas knob.
    learned_auto_refresh: bool = True
    # -- multi-metapath workload (ops/planner.py, DESIGN.md §28) -------
    # Sub-chain memo budget shared by every metapath engine (None →
    # the tuned ``plan_memo_budget_mb`` knob; 0 disables memoization).
    memo_budget_mb: float | None = None
    # Bound on lazily-built secondary metapath engines: each holds a
    # warm backend (device factor + compiled buckets), so the set must
    # not grow with attacker-chosen request fields.
    max_metapaths: int = 8
    # -- background compaction (serving/compact.py, DESIGN.md §30) -----
    # Re-encode with fresh pow-2 headroom and hot-swap in the
    # background when the capacity reserve runs low or the delta chain
    # grows long — the firehose alternative to the synchronous
    # headroom-exhausted rebuild. The swap preserves the consistency
    # token and both cache tiers (the logical graph is unchanged).
    compact_auto: bool = True
    # deltas absorbed since the last re-encode before a chain-triggered
    # compaction (None → the tuned ``compact_chain_len`` knob)
    compact_chain_len: int | None = None
    # trigger when min type headroom falls below this fraction of the
    # logical size (only for types that reserved capacity at build)
    compact_headroom_frac: float = 0.10
    # fresh capacity reserve target of the re-encode (None → the tuned
    # ``compact_headroom`` knob); padded to pow-2 buckets either way
    compact_headroom: float | None = None
    compact_cooldown_s: float = 5.0
    # bounded build retries when deltas keep landing mid-build
    compact_attempts: int = 3


class PathSimService:
    """Holds one warm backend and serves single-source top-k / score
    queries against it, coalescing concurrent requests."""

    def __init__(
        self,
        backend: PathSimBackend,
        variant: str = "rowsum",
        config: ServeConfig | None = None,
        backend_factory=None,
    ):
        self.config = config or ServeConfig()
        self.variant = variant
        self._swap_lock = threading.Lock()  # serializes reload vs admit
        self.result_cache = ResultCache(self.config.cache_entries)
        self.tile_cache = HotTileCache(
            self.config.tile_cache_bytes, tile_rows=self.config.tile_rows
        )
        self._bucket_hist: dict[int, int] = {}
        self._wait_ms_sum = 0.0
        # update()'s full-rebuild fallback needs a fresh backend for the
        # delta-applied graph. The default rebuilds with the incumbent's
        # class and pass-through options; build_service installs a
        # factory that replays the full RunConfig knobs (dtype,
        # tile_rows, …).
        # (the default factory threads the sub-chain memo into the
        # rebuild so a delta-fallback refold hits the still-valid
        # entries; build_service installs its own memo-threading
        # factory for the RunConfig path)
        self._backend_factory = backend_factory or (
            lambda hin: type(self.backend)(
                hin, self.metapath,
                **{**self.backend.options, "subchain_memo": self.memo},
            )
        )
        self._update_stats = {"deltas": 0, "rebuilds": 0, "purged_rows": 0}
        # obs handles, bound once per service (hot-path discipline: a
        # request pays cell increments, never registry lookups)
        reg = get_registry()
        self._m_latency = {
            outcome: reg.histogram(
                "dpathsim_serve_request_seconds",
                "submit-to-resolve request latency by outcome",
            ).labels(outcome=outcome)
            for outcome in (
                "hit_result", "hit_tile", "dispatch", "ann", "learned"
            )
        }
        self._m_updates = reg.counter(
            "dpathsim_serve_updates_total",
            "delta-update outcomes (patch vs rebuild)",
        )
        # XLA compiles visible live: a steady-state serving process
        # whose counter moves is violating the shape-bucket contract
        from ..utils.xla_flags import install_compile_metrics

        install_compile_metrics()
        if self.config.topk_mode not in ("exact", "ann", "learned"):
            raise ValueError(
                f"unknown topk_mode {self.config.topk_mode!r}; "
                "choose 'exact', 'ann' or 'learned'"
            )
        self._ann = None  # AnnState once _setup_ann builds/loads one
        self._ann_refresh_inflight = False  # background-refresh debounce
        self._learned = None  # LearnedState once _setup_learned runs
        self._learned_refresh_inflight = False
        self._learned_deltas = 0  # deltas since the last tower refresh
        self._learned_refresh_every = 1  # tuned cadence (re-set at setup)
        # Workload-level sub-chain memo + lazily-built per-metapath
        # engines (per-request ``metapath`` field). Built BEFORE the
        # backend install so a rebuild-time engine flush finds them.
        n0 = backend.hin.type_size(backend.metapath.source_type)
        budget = (
            planner.default_memo_budget_bytes(n0)
            if self.config.memo_budget_mb is None
            else int(self.config.memo_budget_mb * (1 << 20))
        )
        # Memo entries follow the backend's resident factor layout
        # (the factor_format tuning knob, DESIGN.md §29): when the
        # backend holds its factor packed, the shared sub-chain memo
        # stores packed spans too — same byte budget, 3-6× more shared
        # sub-chains resident.
        memo_fmt = (backend.factor_info() or {}).get("format", "coo")
        self.memo = (
            planner.SubchainCache(budget, factor_format=memo_fmt)
            if budget > 0 else None
        )
        # _engines is read on coalescer threads mid-dispatch, where
        # taking _swap_lock would deadlock against update()'s
        # hold-and-drain — so the dict gets its own LEAF lock (never
        # held across another acquisition; builds still serialize
        # under _swap_lock, only the dict ops take this one).
        self._engines_lock = threading.Lock()
        self._engines: dict[str, MetapathEngine] = {}
        self._m_engines = get_registry().counter(
            "dpathsim_plan_engines_total",
            "secondary metapath engines built, by metapath",
        )
        self._install_backend(backend, warm=self.config.warm)
        # background compaction (serving/compact.py): triggered per
        # absorbed delta under _swap_lock; built AFTER the first
        # install so its tuned thresholds see the real n
        from .compact import Compactor

        self._compactor = Compactor(self)
        self.coalescer = Coalescer(
            issue=self._issue,
            complete=self._complete,
            max_batch=self.config.max_batch,
            max_wait_ms=self.config.max_wait_ms,
            queue_depth=self.config.queue_depth,
            on_batch=self._record_batch,
            bucket_ladder=self._bucket_ladder,
        )

    # -- warm state --------------------------------------------------------

    def _install_backend(self, backend: PathSimBackend, warm: bool) -> None:
        """Make a backend serving-warm: denominators prefetched (for
        jax backends this also assembles C and leaves it device-
        resident), fingerprint computed, buckets pre-compiled."""
        # a wholesale install re-bases the consistency token: the
        # compaction chain restarts and any in-flight build is stale
        # (absent only during the constructor's first install)
        compactor = getattr(self, "_compactor", None)
        if compactor is not None:
            compactor.note_rebuild()
        self.backend = backend
        self.hin = backend.hin
        self.metapath = backend.metapath
        # Secondary engines bind the OLD hin/backend generation: drop
        # them (they rebuild lazily against the new graph, re-hitting
        # the sub-chain memo for factors whose content didn't change).
        with self._engines_lock:
            self._engines.clear()
        self.node_type = backend.metapath.source_type
        self.index = self.hin.indices[self.node_type]
        self.n = self.index.size
        self._base_fp = graph_fingerprint(self.hin)
        self._fp = self._base_fp
        self._delta_seq = 0
        # Per-row cache versions (sized to CAPACITY so node appends have
        # slots): a delta update bumps only the rows it affects, so
        # entries for every other row stay reachable — the row-granular
        # alternative to flushing both tiers. The (base_fp, version) key
        # pair can never resurrect a stale answer: versions only grow,
        # and a rebuild/reload swaps base_fp itself.
        self._row_ver = np.zeros(self.index.padded_size, dtype=np.int64)
        self._d = np.asarray(
            backend._denominators(self.variant), dtype=np.float64
        )
        # Bucket-ladder geometry is a tuned knob (``serve_buckets``,
        # keyed on (graph size, batch ceiling) — the ceiling rides the
        # key's V axis): 'pow2' is the default ladder, 'coarse' halves
        # the programs warmup must compile at <4x pad waste. The SAME
        # ladder feeds warmup and the coalescer — a mismatch would
        # dispatch a bucket warmup never compiled.
        from .. import tuning

        geometry = tuning.choose(
            "serve_buckets", n=self.n, v=self.config.max_batch,
            default="pow2",
        )
        if geometry not in tuning.KNOBS["serve_buckets"].candidates(
            {"n": self.n}
        ):
            # unknown geometry from a stale table: heuristics, loudly.
            # (Validated by name, not by catching resolve_ladder's
            # ValueError — that would also swallow a max_batch config
            # error and falsely blame the tuning table for it.)
            runtime_event("tuning_bad_choice", knob="serve_buckets",
                          choice=geometry)
            geometry = "pow2"
        self._bucket_ladder = tuning.resolve_ladder(
            geometry, self.config.max_batch
        )
        # a reload/rebuild can land on a different ladder (n crossed a
        # key bucket, or a table arrived): the LIVE coalescer must
        # follow, or it would keep dispatching bucket sizes this warmup
        # never compiled
        coal = getattr(self, "coalescer", None)
        if coal is not None:
            coal.buckets = self._bucket_ladder
        if warm:
            from ..utils.xla_flags import warm_compile_cache

            warm_compile_cache(
                backend,
                self._bucket_ladder,
                k=self.config.k_default,
                variant=self.variant,
            )
        self._setup_ann(warm=warm)
        self._setup_learned()

    def _setup_ann(self, warm: bool) -> None:
        """(Re)build or load the ANN candidate index for the freshly
        installed backend (DESIGN.md §23). Every defect degrades to
        exact serving with a loud event, never a crash — exact is the
        ground truth, so losing the index only loses the speedup."""
        cfg = self.config
        if self._ann is not None:
            # a reload/rebuild replaces the state: release the old
            # rerank pool (and drop its C/blocks snapshots) instead of
            # leaking one executor per swap
            self._ann.close()
        self._ann = None
        if cfg.topk_mode != "ann" and cfg.index_path is None:
            return
        from .. import tuning
        from ..index import CentroidIndex, IndexMismatch, build_index
        from ..index.build import half_chain_and_denominators
        from .ann import AnnState

        t0 = time.perf_counter()
        try:
            c, d = half_chain_and_denominators(
                self.hin, self.metapath, self.variant
            )
        except (ValueError, MemoryError) as exc:
            runtime_event("ann_unavailable", reason=str(exc))
            return
        if cfg.index_path is not None:
            try:
                index = CentroidIndex.load(
                    cfg.index_path, expect_base_fp=self._base_fp
                )
            except (IndexMismatch, OSError, KeyError, ValueError) as exc:
                runtime_event(
                    "ann_index_rejected", path=cfg.index_path,
                    reason=str(exc),
                )
                return
            if tuple(index.token) != self.consistency_token:
                # an artifact persisted mid-delta-stream: its rows may
                # lag this replica's graph — refuse rather than serve
                # candidates from an unverifiable epoch
                runtime_event(
                    "ann_index_rejected", path=cfg.index_path,
                    reason=f"index token {index.token} != service "
                    f"token {self.consistency_token}",
                )
                return
            # the fingerprint pins the GRAPH; the embedding geometry
            # must also match the served score function — candidates
            # from a different variant/metapath would silently degrade
            # recall while the exact rerank hides the mismatch
            for axis, want in (("variant", self.variant),
                               ("metapath", self.metapath.name)):
                got = index.meta.get(axis)
                if got is not None and got != want:
                    runtime_event(
                        "ann_index_rejected", path=cfg.index_path,
                        reason=f"index {axis} {got!r} != served "
                        f"{want!r}",
                    )
                    return
        else:
            index = build_index(
                c=c, d=d, variant=self.variant, metapath=self.metapath,
                n_centroids=cfg.ann_centroids,
                cluster_cap=cfg.ann_cluster_cap,
                token=self.consistency_token,
            )
        # scale-aware nprobe heuristic: K/3 clamped to [16, 96]
        # (measured score-recall ≥ 0.99 with margin at the default
        # geometry from 768 through 65k authors). At small N that
        # scans much of the corpus — where ann doesn't matter anyway;
        # at large N it is the sublinear regime. The default is the
        # RECALL-SAFE point; a measured table trades it down per box
        # (the tuner's recall floor keeps any tuned arm honest)
        nprobe = cfg.ann_nprobe or tuning.choose(
            "ann_nprobe", n=self.n,
            default=min(max(16, index.n_centroids // 3), 96),
        )
        cand_mult = cfg.ann_cand_mult or tuning.choose(
            "ann_cand_mult", n=self.n, default=16
        )
        variant = cfg.ann_variant or tuning.choose(
            "ann_probe_variant", n=self.n, default="rerank-all"
        )
        self._ann = AnnState(
            index, c, d,
            nprobe=int(nprobe), cand_mult=int(cand_mult),
            variant=str(variant),
            shadow_every=cfg.ann_shadow_every,
            recall_floor=cfg.ann_recall_floor,
            min_shadow=cfg.ann_min_shadow,
        )
        if warm and not (
            self._ann.variant == "rerank-all" and self._ann.route_on_host
        ):
            # the ANN analog of the bucket warmup: one compiled probe
            # per serving bucket, so steady state compiles nothing
            # (host routing compiles nothing to begin with)
            index.warm(self._bucket_ladder, self._ann.nprobe,
                       variant=self._ann.variant)
        runtime_event(
            "ann_ready",
            n=index.n, centroids=index.n_centroids,
            cluster_cap=index.cluster_cap, dim=index.dim,
            nprobe=self._ann.nprobe, cand_mult=self._ann.cand_mult,
            variant=self._ann.variant,
            source="file" if cfg.index_path else "built",
            startup_s=round(time.perf_counter() - t0, 3),
        )

    def _setup_learned(self) -> None:
        """(Re)build or load the learned-tower candidate state for the
        freshly installed backend (DESIGN.md §32). The ann discipline:
        every defect degrades to ann/exact serving with a loud event,
        never a crash. In-process training (no checkpoint) pays its
        jit compiles HERE, at install time — the query path afterwards
        is pure host numpy, so steady state compiles nothing."""
        cfg = self.config
        if self._learned is not None:
            self._learned.close()
        self._learned = None
        if cfg.topk_mode != "learned" and cfg.learned_checkpoint is None:
            return
        from .. import tuning
        from ..index.build import half_chain_and_denominators
        from ..learned import (
            LearnedState, TowerMismatch, load_towers, train_towers,
        )

        t0 = time.perf_counter()
        try:
            c, d = half_chain_and_denominators(
                self.hin, self.metapath, self.variant
            )
        except (ValueError, MemoryError) as exc:
            runtime_event("learned_unavailable", reason=str(exc))
            return
        encoder = token = None
        source = "file"
        if cfg.learned_checkpoint is not None:
            try:
                encoder, token = load_towers(
                    cfg.learned_checkpoint, expect_base_fp=self._base_fp
                )
            except (TowerMismatch, OSError, KeyError, ValueError) as exc:
                runtime_event(
                    "learned_towers_rejected",
                    path=cfg.learned_checkpoint, reason=str(exc),
                )
            if encoder is not None and tuple(token) != self.consistency_token:
                # an artifact trained mid-delta-stream: its towers may
                # lag this replica's graph — refuse rather than serve
                # candidates from an unverifiable epoch
                runtime_event(
                    "learned_towers_rejected",
                    path=cfg.learned_checkpoint,
                    reason=f"towers token {list(token)} != service "
                    f"token {self.consistency_token}",
                )
                encoder = None
            if encoder is not None:
                for axis, want in (("variant", self.variant),
                                   ("metapath", self.metapath.name)):
                    got = getattr(encoder, axis)
                    if got != want:
                        runtime_event(
                            "learned_towers_rejected",
                            path=cfg.learned_checkpoint,
                            reason=f"towers {axis} {got!r} != served "
                            f"{want!r}",
                        )
                        encoder = None
                        break
            if encoder is None and cfg.topk_mode != "learned":
                # the learned arm was optional here — degrade quietly
                return
        if encoder is None:
            # no checkpoint, or a rejected one on a learned-mode
            # service: distill in-process (the rejection already
            # shouted; a learned-mode replica must still come up
            # serving learned, not limp along exact-only)
            dim = cfg.learned_dim or int(tuning.choose(
                "learned_dim", n=self.n, default=32
            ))
            neg_ratio = (
                cfg.learned_neg_ratio
                if cfg.learned_neg_ratio is not None
                else float(tuning.choose(
                    "learned_neg_ratio", n=self.n, default=0.5
                ))
            )
            try:
                encoder, _ = train_towers(
                    self.hin, self.metapath, variant=self.variant,
                    dim=dim, steps=cfg.learned_steps,
                    hard_frac=1.0 - neg_ratio,
                    hard_sources=min(self.n, 512),
                    token=self.consistency_token,
                )
            except (ValueError, MemoryError) as exc:
                runtime_event("learned_unavailable", reason=str(exc))
                return
            token = self.consistency_token
            source = "trained"
        cand_mult = cfg.learned_cand_mult or int(tuning.choose(
            "learned_cand_mult", n=self.n, default=16
        ))
        recall_floor = (
            cfg.learned_recall_floor
            if cfg.learned_recall_floor is not None
            else float(tuning.choose(
                "learned_conf_floor", n=self.n, default=0.98
            ))
        )
        self._learned = LearnedState(
            encoder, c, d,
            cand_mult=cand_mult,
            shadow_every=cfg.learned_shadow_every,
            recall_floor=recall_floor,
            min_shadow=cfg.learned_min_shadow,
            token=token,
        )
        self._learned_deltas = 0
        self._learned_refresh_every = max(int(tuning.choose(
            "learned_refresh_deltas", n=self.n, default=1
        )), 1)
        runtime_event(
            "learned_ready",
            n=self._learned.n, dim=encoder.dim, hidden=encoder.hidden,
            cand_mult=cand_mult, recall_floor=recall_floor,
            source=source,
            startup_s=round(time.perf_counter() - t0, 3),
        )

    def _epoch_for(self, row: int) -> tuple:
        """Cache-identity prefix for one source row: install-time base
        fingerprint + this row's delta version (+ the query identity
        axes). Versioned per ROW, not per graph — that is what lets a
        delta keep unaffected rows' entries live."""
        return (
            self._base_fp,
            self.metapath.name,
            self.variant,
            int(self._row_ver[row]),
        )

    # -- secondary metapath engines (per-request ``metapath`` field) -------

    def _canon_metapath(self, metapath: str | None) -> str:
        """Per-request metapath name → canonical name (None → the
        service default). Cheap; full validation happens at engine
        build."""
        if metapath is None:
            return self.metapath.name
        name = str(metapath).strip()
        if not name:
            return self.metapath.name
        return name

    def _mp_epoch(self, name: str) -> tuple:
        """Cache-identity prefix of a secondary metapath's entries:
        the CHAINED fingerprint (not the base) — any delta advances it,
        so secondary answers invalidate wholesale per delta while the
        primary keeps its row-granular story. Coarse but sound: the
        affected-row analysis is derived per half-chain, and secondary
        engines rebuild lazily anyway."""
        return (self._fp, name, self.variant)

    def _engine_for(self, name: str) -> MetapathEngine:
        """Get or lazily build the serving engine for a non-default
        metapath. Caller holds ``_swap_lock`` (engine builds must not
        interleave with a backend swap) — so a FIRST build of a new
        metapath blocks admissions for its backend-init + warmup, the
        same stall discipline a reload already has. Post-delta
        rebuilds are cheap by design: the refold hits the sub-chain
        memo (measured ~90x warm vs cold) and the warmup re-dispatches
        already-compiled executables. The engine shares the service's
        sub-chain memo, so concurrent metapath lanes share common
        sub-chain folds (APVPA/APA/APTPA all reuse the A·P factor)."""
        with self._engines_lock:
            eng = self._engines.get(name)
            n_engines = len(self._engines)
        if eng is not None:
            return eng
        if n_engines >= self.config.max_metapaths:
            raise ValueError(
                f"metapath engine limit ({self.config.max_metapaths}) "
                "reached; raise --max-metapaths or restart with the "
                "needed default"
            )
        from ..backends.base import create_backend
        from ..ops.metapath import compile_metapath

        t0 = time.perf_counter()
        mp = compile_metapath(name, self.hin.schema)
        if mp.source_type != mp.target_type:
            raise ValueError(
                f"metapath {name!r} is not closed "
                f"({mp.source_type!r} → {mp.target_type!r}); serving "
                "scores rows of the source type against itself, so a "
                "served metapath must start and end on one type"
            )
        options = dict(self.backend.options)
        options["subchain_memo"] = self.memo
        fallback_from = None
        try:
            backend = create_backend(
                self.backend.name, self.hin, mp, **options
            )
        except ValueError as exc:
            # e.g. an asymmetric-but-closed chain on jax-sparse /
            # jax-sharded: degrade to the numpy oracle for THIS engine
            # only — bit-identical results, only slower.
            fallback_from = self.backend.name
            runtime_event(
                "metapath_engine_fallback", metapath=name,
                from_=self.backend.name, to="numpy", reason=str(exc),
            )
            backend = create_backend(
                "numpy", self.hin, mp, subchain_memo=self.memo
            )
        if self.config.warm:
            from ..utils.xla_flags import warm_compile_cache

            warm_compile_cache(
                backend, self._bucket_ladder,
                k=self.config.k_default, variant=self.variant,
            )
        d = np.asarray(backend._denominators(self.variant), dtype=np.float64)
        eng = MetapathEngine(
            metapath=mp, backend=backend, d=d, n=backend.n_sources,
            fallback_from=fallback_from,
        )
        with self._engines_lock:
            self._engines[name] = eng
        self._m_engines.inc(metapath=name)
        runtime_event(
            "metapath_engine_ready",
            metapath=name, backend=backend.name, n=eng.n,
            order=backend.plan.order(),
            est_flops=round(float(backend.plan.est_flops), 1),
            startup_s=round(time.perf_counter() - t0, 3),
        )
        return eng

    # -- dispatch plumbing (runs on coalescer threads) ---------------------

    def _issue(self, rows_padded: np.ndarray, k: int, lane: str = "exact"):
        """Dispatcher-thread half of a batch: returns the in-flight
        counts handle. jax backends return an un-fetched device array
        (async dispatch → the double buffer overlaps transfer with the
        next bucket's GEMM); others return host counts directly. The
        ``ann`` lane issues the index probe instead — one batched
        matmul over the packed cluster blocks, same async-handle
        contract."""
        if lane == "learned":
            # tower probe: one host matmul over the f32 embeddings —
            # no device round-trip, no compile, returns the sealed
            # handle the completion half reranks from
            t0 = time.perf_counter()
            handle = self._learned.probe_batch(rows_padded)
            self._learned.observe_probe(time.perf_counter() - t0)
            return handle
        if lane == "ann":
            if self._ann.variant == "rerank-all":
                if self._ann.route_on_host:
                    return self._ann.index.route_batch_host(
                        rows_padded, self._ann.nprobe
                    )
                return self._ann.index.route_batch_device(
                    rows_padded, self._ann.nprobe
                )
            return self._ann.index.probe_batch_device(
                rows_padded, self._ann.nprobe
            )
        if lane.startswith(_MP_LANE):
            # secondary-metapath lane: same batched-counts contract,
            # against that metapath's engine (present by construction —
            # submit built it under the swap lock, and update/reload
            # drain the pipeline before dropping engines)
            with self._engines_lock:
                eng = self._engines[lane[len(_MP_LANE):]]
            issue_device = getattr(eng.backend, "pairwise_rows_device", None)
            if issue_device is not None:
                handle = issue_device(rows_padded)
                if handle is not None:
                    return handle
            return eng.backend.pairwise_rows(rows_padded)
        issue_device = getattr(self.backend, "pairwise_rows_device", None)
        if issue_device is not None:
            handle = issue_device(rows_padded)
            if handle is not None:
                return handle
        return self.backend.pairwise_rows(rows_padded)

    def _complete_ann(
        self, handle, rows: np.ndarray, batch: Sequence[Request]
    ) -> None:
        """Completion half of an ``ann`` batch: fetch the probed
        similarities, select each request's C = cand_mult·k candidates
        on host, exact-f64-rerank them against the C/d snapshot, fill
        the ann result-cache tier, resolve futures. Every Nth dispatch
        also runs the exact oracle for its row (shadow sampling) to
        keep the recall-confidence gate honest."""
        tracer = get_tracer()
        ann = self._ann
        t0 = time.perf_counter()
        with tracer.child_span(
            "serve.ann_probe_transfer", n=int(rows.shape[0])
        ):
            first = np.asarray(handle[0])
            second = np.asarray(handle[1])
        ann.observe_probe(time.perf_counter() - t0)

        def _rerank_one(b: int):
            row = int(rows[b])
            k_eff = min(batch[b].k, max(self.n - 1, 1))
            t1 = time.perf_counter()
            if ann.variant == "rerank-all":
                # (mem, top_c): exact-rerank every probed member
                vals, idxs = ann.rerank_all(
                    row, first[b], second[b], k_eff, self.n
                )
            else:
                # (sims, mem): approximate shortlist → exact rerank
                cand = ann.candidates_for(
                    first[b], second[b], k_eff, self.n
                )
                vals, idxs = ann.rerank(row, cand, k_eff)
            ann.observe_rerank(time.perf_counter() - t1)
            return k_eff, vals, idxs

        with tracer.child_span("serve.ann_rerank", n=len(batch)):
            # per-request reranks are independent: fan them over the
            # ann pool (numpy/BLAS release the GIL), resolve in order
            reranked = list(ann.pool.map(_rerank_one, range(len(batch))))
            shadows = []
            for b, req in enumerate(batch):
                row = int(rows[b])
                k_eff, vals, idxs = reranked[b]
                ann.count_answered()
                if ann.should_shadow():
                    # deferred: the O(N) oracle scan must never sit in
                    # front of a waiting future — the sampled request's
                    # (and the rest of the batch's) latency is exactly
                    # what the ANN path exists to shrink
                    shadows.append((row, k_eff, vals))
                self.result_cache.put(self._ann_key(row, req.k), vals, idxs)
                if not req.future.done():
                    req.future.set_result((vals, idxs))
                self._m_latency["ann"].observe(
                    time.monotonic() - (req.t_submit or req.t_enqueue)
                )
                tracer.finish(req.span, outcome="ann")
            for row, k_eff, vals in shadows:  # every future resolved
                evals, _ = self.backend.topk_row(
                    row, k=k_eff, variant=self.variant
                )
                ann.record_shadow(vals, evals, k_eff)

    def _complete_learned(
        self, handle, rows: np.ndarray, batch: Sequence[Request]
    ) -> None:
        """Completion half of a ``learned`` batch: exact-f64 rerank the
        tower shortlist for each request INSIDE learned/ (the LN001
        doorway — this method never reads the handle's raw
        similarities), fill the learned result-cache tier, resolve
        futures. Every Nth dispatch also runs the exact oracle (shadow
        sampling) to keep the recall-confidence gate honest — deferred
        past future resolution like the ANN path, because an O(N)
        oracle scan must never sit in front of a waiting caller."""
        tracer = get_tracer()
        lr = self._learned

        def _rerank_one(b: int):
            row = int(rows[b])
            k_eff = min(batch[b].k, max(self.n - 1, 1))
            t1 = time.perf_counter()
            vals, idxs = lr.answer_from_handle(handle, b, row, k_eff)
            lr.observe_rerank(time.perf_counter() - t1)
            return k_eff, vals, idxs

        with tracer.child_span("serve.learned_rerank", n=len(batch)):
            reranked = list(lr.pool.map(_rerank_one, range(len(batch))))
            shadows = []
            for b, req in enumerate(batch):
                row = int(rows[b])
                k_eff, vals, idxs = reranked[b]
                lr.count_answered()
                if lr.should_shadow():
                    shadows.append((row, k_eff, vals))
                self.result_cache.put(
                    self._learned_key(row, req.k), vals, idxs
                )
                if not req.future.done():
                    req.future.set_result((vals, idxs))
                self._m_latency["learned"].observe(
                    time.monotonic() - (req.t_submit or req.t_enqueue)
                )
                tracer.finish(req.span, outcome="learned")
            for row, k_eff, vals in shadows:  # every future resolved
                evals, _ = self.backend.topk_row(
                    row, k=k_eff, variant=self.variant
                )
                lr.record_shadow(vals, evals, k_eff)

    def _complete(
        self,
        handle,
        rows: np.ndarray,
        batch: Sequence[Request],
        k: int,
        lane: str = "exact",
    ) -> None:
        """Completion-thread half: fetch counts, normalize in f64, top-k
        per request (each gets the k-prefix it asked for), fill both
        cache tiers, resolve futures. The tracer spans opened here
        parent into the batch's ``serve.complete`` span — the coalescer
        activated its context on this thread before calling."""
        if lane == "learned":
            return self._complete_learned(handle, rows, batch)
        if lane == "ann":
            return self._complete_ann(handle, rows, batch)
        if lane.startswith(_MP_LANE):
            return self._complete_metapath(
                handle, rows, batch, k, lane[len(_MP_LANE):]
            )
        tracer = get_tracer()
        with tracer.child_span("serve.host_transfer", n=int(rows.shape[0])):
            # column trim to the logical width: device handles from a
            # capacity-padded backend carry zero-count pad columns.
            # np.asarray is where an async device handle actually
            # blocks — the transfer segment of the trace.
            counts = np.asarray(handle, dtype=np.float64)[
                : rows.shape[0], : self.n
            ]
        scores = pathsim.score_rows(counts, self._d[rows], self._d, xp=np)
        masked = scores.copy()
        masked[np.arange(rows.shape[0]), rows] = -np.inf
        k_eff = min(k, max(self.n - 1, 1))
        vals, idxs = pathsim.topk_from_score_rows(masked, k_eff)
        with tracer.child_span("serve.cache_fill", n=len(batch)):
            for b, req in enumerate(batch):
                epoch = self._epoch_for(int(rows[b]))
                # copy, not a view: a cached view would pin the whole
                # [B, N] batch array long past the byte budget's
                # accounting
                self.tile_cache.put_row(
                    epoch, int(rows[b]), scores[b].copy()
                )
                kr = min(req.k, k_eff)
                rv, ri = vals[b, :kr], idxs[b, :kr]
                self.result_cache.put(
                    (*epoch, int(rows[b]), req.k), rv, ri
                )
                if not req.future.done():
                    req.future.set_result((rv, ri))
                # observed AFTER the future resolves, from the
                # SUBMITTER's clock reading (t_submit, same origin the
                # hit_result/hit_tile outcomes use): the histogram's
                # claim is submit-to-resolve, so cache-fill time and
                # swap-lock wait must be inside it, not carved out
                self._m_latency["dispatch"].observe(
                    time.monotonic() - (req.t_submit or req.t_enqueue)
                )
                tracer.finish(req.span, outcome="dispatch")

    def _complete_metapath(
        self,
        handle,
        rows: np.ndarray,
        batch: Sequence[Request],
        k: int,
        name: str,
    ) -> None:
        """Completion half of a secondary-metapath batch: the primary
        path's arithmetic (f64 normalize, oracle tie order, both cache
        tiers) against the engine's counts/denominators and the
        metapath's own cache epoch."""
        with self._engines_lock:
            eng = self._engines[name]
        tracer = get_tracer()
        with tracer.child_span(
            "serve.host_transfer", n=int(rows.shape[0]), metapath=name
        ):
            counts = np.asarray(handle, dtype=np.float64)[
                : rows.shape[0], : eng.n
            ]
        scores = pathsim.score_rows(counts, eng.d[rows], eng.d, xp=np)
        masked = scores.copy()
        masked[np.arange(rows.shape[0]), rows] = -np.inf
        k_eff = min(k, max(eng.n - 1, 1))
        vals, idxs = pathsim.topk_from_score_rows(masked, k_eff)
        epoch = self._mp_epoch(name)
        with tracer.child_span("serve.cache_fill", n=len(batch)):
            for b, req in enumerate(batch):
                self.tile_cache.put_row(
                    epoch, int(rows[b]), scores[b].copy()
                )
                kr = min(req.k, k_eff)
                rv, ri = vals[b, :kr], idxs[b, :kr]
                self.result_cache.put((*epoch, int(rows[b]), req.k), rv, ri)
                if not req.future.done():
                    req.future.set_result((rv, ri))
                self._m_latency["dispatch"].observe(
                    time.monotonic() - (req.t_submit or req.t_enqueue)
                )
                tracer.finish(req.span, outcome="dispatch")

    def _record_batch(self, stats: BatchStats) -> None:
        self._bucket_hist[stats.bucket] = (
            self._bucket_hist.get(stats.bucket, 0) + 1
        )
        self._wait_ms_sum += stats.wait_ms
        if self.config.batch_events:
            runtime_event(
                "serve_batch",
                echo=False,
                n=stats.n_requests,
                bucket=stats.bucket,
                wait_ms=round(stats.wait_ms, 3),
            )

    # -- query API ---------------------------------------------------------

    def resolve(self, source: str | None = None,
                source_id: str | None = None,
                row: int | None = None,
                metapath: str | None = None) -> int:
        """Label / node-id / raw row → dense row index (in the
        requested metapath's SOURCE type space — a per-request
        ``metapath`` may start on a different node type than the
        service default)."""
        name = self._canon_metapath(metapath)
        if name == self.metapath.name:
            node_type, n = self.node_type, self.n
        else:
            # compile only: source type and row bound need the SPEC,
            # not an engine — building one here would stall admissions
            # (and burn an engine slot) just to range-check a row
            from ..ops.metapath import compile_metapath

            node_type = compile_metapath(
                name, self.hin.schema
            ).source_type
            n = self.hin.type_size(node_type)
        if row is not None:
            if not 0 <= int(row) < n:
                raise KeyError(f"row {row} out of range [0, {n})")
            return int(row)
        return self.hin.resolve_source(
            node_type, label=source, node_id=source_id
        )

    def _resolve_mode(self, mode: str | None) -> str:
        """Per-request mode override → effective answer path."""
        if mode is None:
            mode = self.config.topk_mode
        if mode not in ("exact", "ann", "learned"):
            raise ValueError(
                f"unknown topk mode {mode!r}; choose 'exact', 'ann' or "
                "'learned'"
            )
        return mode

    def ann_fallback_reason(self, row: int,
                            mode: str | None = None) -> str | None:
        """Would an (effective-)``ann`` query for ``row`` degrade to
        the exact path right now, and why? A side-effect-free peek —
        no fallback counters tick — for observers: the worker annotates
        responses with it so the router's flight recorder can
        tail-keep ann-degraded requests. None = the ANN path answers
        (or the effective mode is exact, where "fallback" is
        meaningless)."""
        if self._resolve_mode(mode) != "ann":
            return None
        if self._ann is None:
            return "no_index"
        return self._ann.peek(int(row))

    def learned_fallback_reason(self, row: int,
                                mode: str | None = None) -> str | None:
        """Would a learned-mode query for ``row`` degrade right now,
        and why? Side-effect-free peek (no counters), mirror of
        :meth:`ann_fallback_reason` — the worker annotates responses
        with it so the router's flight recorder can tail-keep
        learned-degraded requests. None = the learned path answers (or
        the effective mode isn't learned)."""
        if self._resolve_mode(mode) != "learned":
            return None
        if self._learned is None:
            return "no_towers"
        return self._learned.peek(int(row))

    def _learned_key(self, row: int, k: int) -> tuple:
        """Learned result-cache key: the exact epoch prefix plus a
        ``learned`` axis and the knobs that shape the candidate set —
        a learned answer can never be served to an exact or ann query
        (and vice versa), and retuning cand_mult can't replay old
        shortlists."""
        lr = self._learned
        return (*self._epoch_for(row), "learned", lr.encoder.dim,
                lr.cand_mult, int(row), int(k))

    def _ann_key(self, row: int, k: int) -> tuple:
        """ANN result-cache key: the exact path's epoch prefix (base
        fp + per-row delta version — a delta on this row invalidates
        both tiers' entries the same way) plus an ``ann`` axis so an
        approximate answer can never be served to an exact query or
        vice versa."""
        return (*self._epoch_for(row), "ann", self._ann.variant,
                self._ann.nprobe, self._ann.cand_mult, int(row), int(k))

    def submit_topk(self, row: int, k: int | None = None,
                    mode: str | None = None,
                    metapath: str | None = None) -> Future:
        """Admit a top-k query; returns a Future of (values, indices).
        Cache hits resolve immediately; misses ride the coalescer.
        Raises :class:`coalescer.LoadShedError` at the queue bound.

        ``mode`` (None → the service default ``config.topk_mode``)
        picks the answer path: ``exact`` scores the full row; ``ann``
        probes the candidate index and exact-reranks — and silently
        degrades to exact (counted, per reason) whenever the index
        can't vouch for this row (stale/unseen/degenerate/confidence
        lost/no index). Exact is ground truth, so degrading is always
        safe; it only costs the speedup.

        Every admission opens a root ``serve.request`` span: cache hits
        finish it here; coalesced misses carry it across the
        dispatcher/completer thread hop, so one request = one connected
        trace (enqueue → dispatch → device → transfer → cache fill)."""
        k = int(k or self.config.k_default)
        mode = self._resolve_mode(mode)
        name = self._canon_metapath(metapath)
        tracer = get_tracer()
        root = tracer.start_span(
            "serve.request", row=int(row), k=k, mode=mode, metapath=name
        )
        t0 = time.monotonic()
        try:
            with self._swap_lock:
                if name != self.metapath.name:
                    if mode == "ann":
                        # the candidate index embeds the DEFAULT
                        # metapath's geometry; other chains answer
                        # exactly (counted like every other fallback)
                        get_registry().counter(
                            "dpathsim_ann_fallbacks_total",
                            "ann-requested queries answered exactly "
                            "instead, by reason",
                        ).inc(reason="metapath")
                    elif mode == "learned":
                        # same story for the towers: they were
                        # distilled against the default chain only
                        get_registry().counter(
                            "dpathsim_learned_fallbacks_total",
                            "learned-requested queries degraded to "
                            "ann/exact, by reason",
                        ).inc(reason="metapath")
                    return self._submit_metapath_locked(
                        int(row), k, name, root, t0
                    )
                return self._submit_topk_locked(int(row), k, root, t0, mode)
        except BaseException as exc:
            tracer.finish(root, outcome=type(exc).__name__)
            raise

    def _submit_metapath_locked(self, row: int, k: int, name: str,
                                root=None, t0: float = 0.0) -> Future:
        """Secondary-metapath admission (under ``_swap_lock``): same
        three tiers as the primary path — result LRU, hot-tile
        re-select, coalesced dispatch on the metapath's own lane."""
        tracer = get_tracer()
        eng = self._engine_for(name)
        if not 0 <= row < eng.n:
            raise KeyError(f"row {row} out of range [0, {eng.n})")
        epoch = self._mp_epoch(name)
        key = (*epoch, int(row), k)
        hit = self.result_cache.get(key)
        if hit is not None:
            fut: Future = Future()
            fut.set_result(hit)
            self._m_latency["hit_result"].observe(time.monotonic() - t0)
            tracer.finish(root, outcome="hit_result")
            return fut
        srow = self.tile_cache.get_row(epoch, int(row))
        if srow is not None:
            masked = srow.copy()
            masked[int(row)] = -np.inf
            k_eff = min(k, max(eng.n - 1, 1))
            vals, idxs = pathsim.topk_from_score_rows(masked[None, :], k_eff)
            self.result_cache.put(key, vals[0], idxs[0])
            fut = Future()
            fut.set_result((vals[0], idxs[0]))
            self._m_latency["hit_tile"].observe(time.monotonic() - t0)
            tracer.finish(root, outcome="hit_tile")
            return fut
        return self.coalescer.submit(
            int(row), k, span=root, t_submit=t0, lane=f"{_MP_LANE}{name}"
        )

    def _submit_topk_locked(self, row: int, k: int, root=None,
                            t0: float = 0.0, mode: str = "exact") -> Future:
        # Under _swap_lock: a reload drains the pipeline then swaps the
        # backend — admissions must not interleave with that swap (the
        # drain would never finish, and a request could resolve rows
        # against one graph and dispatch against another).
        tracer = get_tracer()
        if mode == "learned":
            if self._learned is None:
                get_registry().counter(
                    "dpathsim_learned_fallbacks_total",
                    "learned-requested queries degraded to ann/exact, "
                    "by reason",
                ).inc(reason="no_towers")
            elif self._learned.eligible(row) is None:
                key = self._learned_key(row, k)
                hit = self.result_cache.get(key)
                if hit is not None:
                    fut: Future = Future()
                    fut.set_result(hit)
                    self._m_latency["hit_result"].observe(
                        time.monotonic() - t0
                    )
                    tracer.finish(root, outcome="hit_result")
                    return fut
                return self.coalescer.submit(
                    int(row), k, span=root, t_submit=t0, lane="learned"
                )
            # ineligible (counted by reason): degrade ANN-then-exact —
            # the ann cascade below re-checks its own eligibility and
            # counts its own fallbacks, so a doubly-degraded query
            # lands on exact with both arms' accounting intact
            if self._ann is not None:
                mode = "ann"
        if mode == "ann":
            if self._ann is None:
                get_registry().counter(
                    "dpathsim_ann_fallbacks_total",
                    "ann-requested queries answered exactly instead, "
                    "by reason",
                ).inc(reason="no_index")
            elif self._ann.eligible(row) is None:
                key = self._ann_key(row, k)
                hit = self.result_cache.get(key)
                if hit is not None:
                    fut: Future = Future()
                    fut.set_result(hit)
                    self._m_latency["hit_result"].observe(
                        time.monotonic() - t0
                    )
                    tracer.finish(root, outcome="hit_result")
                    return fut
                return self.coalescer.submit(
                    int(row), k, span=root, t_submit=t0, lane="ann"
                )
            # ineligible (already counted by reason): exact fallback
        epoch = self._epoch_for(row)
        key = (*epoch, int(row), k)
        hit = self.result_cache.get(key)
        if hit is not None:
            fut: Future = Future()
            fut.set_result(hit)
            self._m_latency["hit_result"].observe(time.monotonic() - t0)
            tracer.finish(root, outcome="hit_result")
            return fut
        srow = self.tile_cache.get_row(epoch, int(row))
        if srow is not None:
            masked = srow.copy()
            masked[int(row)] = -np.inf
            k_eff = min(k, max(self.n - 1, 1))
            vals, idxs = pathsim.topk_from_score_rows(
                masked[None, :], k_eff
            )
            self.result_cache.put(key, vals[0], idxs[0])
            fut = Future()
            fut.set_result((vals[0], idxs[0]))
            self._m_latency["hit_tile"].observe(time.monotonic() - t0)
            tracer.finish(root, outcome="hit_tile")
            return fut
        return self.coalescer.submit(int(row), k, span=root, t_submit=t0)

    def topk_index(self, row: int, k: int | None = None,
                   timeout_s: float | None = None,
                   mode: str | None = None,
                   metapath: str | None = None):
        """Synchronous top-k by dense row index → (values, indices).
        ``timeout_s`` caps the wait below the service-wide default —
        the protocol's ``deadline_ms`` budget lands here, so a request
        whose caller has given up stops blocking a worker slot."""
        timeout = self.config.request_timeout_s
        if timeout_s is not None:
            timeout = min(timeout, max(timeout_s, 0.0))
        return self.submit_topk(
            row, k, mode=mode, metapath=metapath
        ).result(timeout=timeout)

    def _ident(self, i: int, node_type: str | None = None) -> tuple[str, str]:
        """(id, label) for a dense index — huge synthetic graphs carry
        implicit range ids (TypeIndex.size_override, no string tables),
        so serving must synthesize the canonical name rather than index
        an empty tuple."""
        node_type = node_type or self.node_type
        idx = self.hin.indices[node_type]
        if i < len(idx.ids):
            return idx.ids[i], idx.labels[i]
        return f"{node_type}_{i}", f"{node_type}_{i}"

    def topk(self, source: str | None = None, source_id: str | None = None,
             row: int | None = None, k: int | None = None,
             timeout_s: float | None = None, mode: str | None = None,
             metapath: str | None = None):
        """Synchronous top-k by label / id / row, resolved to ids:
        list of (target_id, target_label, score). ``metapath``
        overrides the served chain per request (default: the service's
        ``--metapath``)."""
        name = self._canon_metapath(metapath)
        # node_type is captured BEFORE dispatch: an update()/reload
        # racing the request may drop the engine dict entry after the
        # future resolves, and a successfully-answered query must not
        # crash on the id-mapping step
        if name == self.metapath.name:
            node_type = self.node_type
        else:
            from ..ops.metapath import compile_metapath

            node_type = compile_metapath(name, self.hin.schema).source_type
        r = self.resolve(
            source=source, source_id=source_id, row=row, metapath=name
        )
        vals, idxs = self.topk_index(
            r, k, timeout_s=timeout_s, mode=mode, metapath=name
        )
        return [
            (*self._ident(int(i), node_type), float(v))
            for v, i in zip(vals, idxs)
            if np.isfinite(v)
        ]

    def scores_index(self, row: int, metapath: str | None = None) -> np.ndarray:
        """Full normalized score row (self pair included, as the
        driver's all-pairs row would have it). Tile-cache hit or one
        coalesced dispatch."""
        row = int(row)
        name = self._canon_metapath(metapath)
        if name != self.metapath.name:
            with self._swap_lock:
                self._engine_for(name)
            srow = self.tile_cache.get_row(self._mp_epoch(name), row)
            if srow is not None:
                return srow.copy()
            self.topk_index(row, self.config.k_default, metapath=name)
            # re-fetch engine AND epoch: a delta racing the dispatch
            # advanced _fp and dropped the engine — reading the
            # pre-dispatch snapshot here would serve pre-delta scores
            # as the current answer
            with self._swap_lock:
                eng = self._engine_for(name)
            srow = self.tile_cache.get_row(self._mp_epoch(name), row)
            if srow is not None:
                return srow.copy()
            return eng.backend.scores_rows(
                np.asarray([row]), variant=self.variant
            )[0]
        # copies on the hit paths: callers mutate score rows (self-
        # masking is the natural first move), and handing out the
        # cache's own array would poison every later tier-2 hit
        srow = self.tile_cache.get_row(self._epoch_for(row), row)
        if srow is not None:
            return srow.copy()
        # ride the normal dispatch path (fills the tile cache), then
        # read the row back out of it
        self.topk_index(row, self.config.k_default)
        srow = self.tile_cache.get_row(self._epoch_for(row), row)
        if srow is not None:
            return srow.copy()
        # tile cache disabled (budget 0): compute directly
        return self.backend.scores_rows(
            np.asarray([row]), variant=self.variant
        )[0]

    # -- lifecycle ---------------------------------------------------------

    def invalidate(self, memo: bool = True) -> None:
        """Drop both cache tiers (explicit operator action or reload).
        ``memo=False`` keeps the sub-chain memo — update()'s rebuild
        path uses it after SELECTIVELY invalidating the changed
        factors, so the rebuild's refold still hits the entries whose
        content did not move."""
        self.result_cache.clear()
        self.tile_cache.clear()
        if memo and self.memo is not None:
            self.memo.clear()
        runtime_event("serve_invalidate", fingerprint=self._fp)

    @property
    def consistency_token(self) -> tuple[str, int]:
        """The replica-consistency token ``(base_fp, delta_seq)``: two
        replicas with equal tokens have applied the same delta chain to
        the same base graph and therefore serve bit-identical answers.
        A router fences a replica whose token lags the broadcast head
        (DESIGN.md §22)."""
        return (self._base_fp, self._delta_seq)

    def batch_blocks(self, req: dict) -> dict:
        """One batch-campaign block, served off the replica's backend.

        The campaign scheduler (router/batch.py) fans row blocks
        ``[lo, hi)`` here. ``topk`` mode answers through
        ``backend.topk_rows`` — the SAME call the oracle parity tests
        pin — so fleet shards are bit-identical to single-host shards
        by construction; ``simjoin`` mode filters the exact score rows
        at ``tau`` (strictly-upper triangle, the block's share of the
        join). The request's ``(base_fp, delta_seq)`` is the
        campaign's graph identity: a mismatch against this replica's
        consistency token refuses loudly ("stale batch campaign") so a
        delta landing mid-campaign can never mix graph versions into
        one manifest."""
        want_fp = req.get("base_fp")
        if want_fp is not None:
            want = (str(want_fp), int(req.get("delta_seq", 0)))
            if want != self.consistency_token:
                raise ValueError(
                    "stale batch campaign: request pinned graph "
                    f"{want}, replica serves {self.consistency_token}"
                )
        want_mp = req.get("metapath")
        if want_mp is not None and str(want_mp) != self.metapath.name:
            # same fence as the token: a campaign over a different
            # metapath must never mix into this replica's answers
            raise ValueError(
                f"stale batch campaign: request metapath {want_mp!r}, "
                f"replica serves {self.metapath.name!r}"
            )
        lo = int(req.get("lo", 0))
        hi = min(int(req.get("hi", 0)), self.n)
        mode = str(req.get("mode", "topk"))
        variant = str(req.get("variant", self.variant))
        if hi <= lo:
            # an empty range is a valid (if useless) block — the
            # protocol echo test drives every op with no fields
            return {"lo": lo, "hi": hi, "vals": [], "idxs": []}
        rows = np.arange(lo, hi, dtype=np.int64)
        if mode == "topk":
            k = int(req.get("k", self.config.k_default))
            vals, idxs = self.backend.topk_rows(
                rows, min(k, max(self.n - 1, 1)), variant=variant
            )
            return {
                "lo": lo, "hi": hi,
                "vals": vals.tolist(), "idxs": idxs.tolist(),
            }
        if mode == "simjoin":
            tau = float(req.get("tau", 0.5))
            scores = self.backend.scores_rows(rows, variant=variant)
            keep = scores >= tau
            keep &= rows[:, None] < np.arange(scores.shape[1])[None, :]
            bi, gj = np.nonzero(keep)
            return {
                "lo": lo, "hi": hi,
                "rows": rows[bi].tolist(), "cols": gj.tolist(),
                "scores": scores[bi, gj].tolist(),
            }
        raise ValueError(f"unknown batch_blocks mode {mode!r}")

    def health(self) -> dict:
        """The heartbeat payload: O(1) liveness + the load signals a
        router routes on + the consistency token that fences a lagging
        replica. Deliberately cheap — a probe must stay answerable even
        when the query path is saturated."""
        c = self.coalescer
        return {
            "ok": True,
            "n": self.n,
            "queue_depth": c.depth,
            "inflight": c.inflight,
            "shed": c.shed_count,
            "base_fp": self._base_fp,
            "delta_seq": self._delta_seq,
            "fingerprint": self._fp,
            "backend": self.backend.name,
            # compaction heartbeat bits: a router (or operator) can see
            # a replica mid-build — the token above is UNCHANGED by a
            # compaction swap, so fencing never reacts to one
            "compaction": {
                "inflight": self._compactor.inflight,
                "count": self._compactor.compactions,
            },
            # index epoch: lets a router (or operator) see which
            # replicas hold a fresh ANN index — a replica without one
            # still answers every query, exactly (None = exact-only)
            "index": (
                {
                    "mode": self.config.topk_mode,
                    "epoch": list(self._ann.index.token),
                    "stale_rows": self._ann.index.stale_count,
                    "enabled": self._ann.enabled,
                }
                if self._ann is not None
                else None
            ),
            # per-mode index-epoch map (generalizes the ANN-only
            # "index" key above, which stays for back-compat): one
            # entry per answer path this replica can serve, each with
            # its own consistency epoch — a router re-dispatching a
            # learned query onto a tower-less replica reads this, and
            # the fallback story guarantees the answer is exact either
            # way
            "modes": {
                "exact": {
                    "epoch": [self._base_fp, self._delta_seq],
                    "stale_rows": 0,
                    "enabled": True,
                },
                "ann": (
                    {
                        "epoch": list(self._ann.index.token),
                        "stale_rows": self._ann.index.stale_count,
                        "enabled": self._ann.enabled,
                    }
                    if self._ann is not None else None
                ),
                "learned": (
                    {
                        "epoch": list(self._learned.token),
                        "stale_rows": self._learned.stale_count,
                        "pending_appends": self._learned.pending_appends,
                        "enabled": self._learned.enabled,
                    }
                    if self._learned is not None else None
                ),
            },
            # process-lifetime XLA compile count: a steady-state worker
            # whose number moves is violating the shape-bucket contract
            # (the router smoke's zero-recompile gate reads this)
            "compiles": int(
                get_registry().counter(
                    "dpathsim_xla_compiles_total",
                    "XLA backend compilations since process start",
                ).labels().value
            ),
        }

    def update(self, delta, want_rows: bool = False) -> dict:
        """Absorb a :class:`~..data.delta.DeltaBatch` into the WARM
        service — the recompile-free alternative to :meth:`reload`.

        Fast path (plan says patch): drain the pipeline, patch the
        backend's half factor/denominators in place (O(Δ + affected
        rows), zero new XLA compiles in steady state), bump the cache
        version of exactly the affected score rows, and purge only
        their entries — every unaffected row keeps its cached answers.
        Fallback (headroom exhausted / Δ over threshold / backend or
        chain without a patch path): build a fresh backend for the
        delta-applied graph and swap it in, reload-style.

        Returns an accounting dict (mode, affected rows, purges,
        chained fingerprint) — also the JSONL ``update`` op's result."""
        from ..backends.base import DeltaUnsupported
        from ..data.delta import plan_delta

        t0 = time.perf_counter()
        with self._swap_lock:
            self.coalescer.drain()
            plan = plan_delta(
                self.hin, delta, self.metapath,
                max_delta_fraction=self.config.delta_threshold,
            )
            mode, reason = "delta", plan.reason
            if not plan.fallback:
                try:
                    self.backend.apply_delta(plan)
                except DeltaUnsupported as exc:
                    mode, reason = "rebuild", str(exc)
            else:
                mode = "rebuild"
            # Sub-chain memo: drop exactly the entries whose factors
            # changed (keys are content fingerprints, so untouched
            # sub-chains keep hitting across the delta); secondary
            # engines bind the pre-delta graph and rebuild lazily.
            changed_rels = sorted({e.relationship for e in delta.edges})
            memo_dropped = (
                self.memo.invalidate_relationships(changed_rels)
                if self.memo is not None else 0
            )
            with self._engines_lock:
                engines_dropped = len(self._engines)
                self._engines.clear()
            affected_list: list[int] | None = None
            if mode == "rebuild":
                self._install_backend(
                    self._backend_factory(plan.hin_new),
                    warm=self.config.warm,
                )
                # answer caches go wholesale; the sub-chain memo was
                # already SELECTIVELY invalidated above — its surviving
                # entries are content-addressed (still bit-valid for
                # untouched factors), and the rebuild's refold just
                # hit them through the factory's threaded memo
                self.invalidate(memo=False)
                self._update_stats["rebuilds"] += 1
                affected_n, purged = self.n, -1  # everything went
            else:
                self.hin = plan.hin_new
                self.index = self.hin.indices[self.node_type]
                self.n = self.index.size
                self._d = np.asarray(
                    self.backend._denominators(self.variant),
                    dtype=np.float64,
                )
                affected = plan.affected_rows
                self._row_ver[affected] += 1
                purged = self.result_cache.purge_rows(
                    affected
                ) + self.tile_cache.purge_rows(affected)
                self._delta_seq += 1
                self._fp = plan.fingerprint
                affected_n = int(affected.shape[0])
                if self._ann is not None:
                    # the index's rows for this delta are now a graph
                    # behind: fence them onto the exact path until the
                    # (background) refresh re-embeds them. Appended
                    # rows are uncovered by construction.
                    self._ann.index.mark_stale(affected)
                if self._learned is not None:
                    # same fence for the towers: affected rows answer
                    # exactly until absorb() re-embeds them; appended
                    # source rows (headroom slots made real) go
                    # cold-start pending (the SLO gauge tracks them)
                    self._learned.mark_stale(affected)
                    self._learned.note_appends(sum(
                        a.n for a in plan.delta.nodes
                        if a.node_type == self.node_type
                    ))
                    self._learned_deltas += 1
                if want_rows:
                    # the router's fencing machinery needs the SET, not
                    # the count: a replica that missed this delta is
                    # fenced for exactly these rows until caught up
                    affected_list = [int(r) for r in affected]
                self._update_stats["deltas"] += 1
                self._update_stats["purged_rows"] += purged
            # compaction bookkeeping + trigger check (we hold the swap
            # lock): a patch feeds an in-flight build's replay log; a
            # long chain or thin headroom spawns the background build
            self._compactor.note_update(delta, mode)
            ms = round((time.perf_counter() - t0) * 1e3, 3)
            self._m_updates.inc(mode=mode)
            get_registry().histogram(
                "dpathsim_serve_update_seconds",
                "delta-update end-to-end latency by mode",
            ).observe((time.perf_counter() - t0), mode=mode)
            runtime_event(
                "serve_update",
                mode=mode,
                reason=reason,
                edge_changes=plan.n_edge_changes,
                node_appends=plan.delta.n_node_appends,
                affected_rows=affected_n,
                purged_entries=purged,
                delta_seq=self._delta_seq,
                fingerprint=self._fp,
                ms=ms,
            )
            result = {
                "mode": mode,
                "reason": reason,
                "edge_changes": plan.n_edge_changes,
                "node_appends": plan.delta.n_node_appends,
                "affected_rows": affected_n,
                "purged_entries": purged,
                "memo_invalidated": memo_dropped,
                "engines_dropped": engines_dropped,
                "delta_seq": self._delta_seq,
                "base_fp": self._base_fp,
                "fingerprint": self._fp,
                "n": self.n,
                "ms": ms,
            }
            if want_rows:
                # None under rebuild: "all rows" — the fence must cover
                # everything, not an empty set
                result["affected_row_list"] = affected_list
            if self._ann is not None:
                result["ann_stale_rows"] = self._ann.index.stale_count
                if (
                    mode == "delta"
                    and self.config.ann_auto_refresh
                    and self._ann.index.stale_count
                    # learned indexes can't re-embed in place; they
                    # stay on the exact path for stale rows until an
                    # offline rebuild (refresh_index reports the same)
                    and self._ann.index.meta.get("embedding") == "struct"
                    # debounced: one refresh in flight at a time — a
                    # sustained delta stream must not spawn a thread
                    # (and pay a half-chain fold) per delta only to
                    # abandon at the token check; the in-flight
                    # refresh snapshots the token AFTER taking the
                    # lock, so it folds the newest graph state anyway
                    and not self._ann_refresh_inflight
                ):
                    # background re-embed: stale rows answer exactly in
                    # the meantime, so serving correctness never waits
                    # on this thread (it blocks on the swap lock we
                    # still hold, then runs). The spawning update's
                    # span context rides along as a LINK: the refresh
                    # runs as its own trace (it outlives the update's
                    # request), but its root span names the update
                    # span that caused it, so the fleet export can
                    # join cause to effect (DESIGN.md §24).
                    cur = get_tracer().current()
                    link = (
                        f"{cur.trace_id}:{cur.span_id}"
                        if cur is not None and cur.span_id else None
                    )
                    self._ann_refresh_inflight = True
                    threading.Thread(
                        target=self._refresh_index_quietly,
                        args=(link,),
                        name="pathsim-ann-refresh", daemon=True,
                    ).start()
            if self._learned is not None:
                result["learned_stale_rows"] = self._learned.stale_count
                result["learned_pending_appends"] = (
                    self._learned.pending_appends
                )
                if (
                    mode == "delta"
                    and self.config.learned_auto_refresh
                    and (
                        self._learned.stale_count
                        or self._learned.pending_appends
                    )
                    # cadence knob: a sustained delta stream re-embeds
                    # every Nth landing, not every landing (the fold is
                    # the expensive input; staled rows answer exactly
                    # in the meantime, so batching refreshes costs
                    # speed only, never correctness)
                    and self._learned_deltas >= self._learned_refresh_every
                    # debounced like the ann refresh: one in flight
                    and not self._learned_refresh_inflight
                ):
                    cur = get_tracer().current()
                    link = (
                        f"{cur.trace_id}:{cur.span_id}"
                        if cur is not None and cur.span_id else None
                    )
                    self._learned_refresh_inflight = True
                    self._learned_deltas = 0
                    threading.Thread(
                        target=self._refresh_towers_quietly,
                        args=(link,),
                        name="pathsim-learned-refresh", daemon=True,
                    ).start()
            return result

    def _refresh_index_quietly(self, link: str | None = None) -> None:
        try:
            # its own root span (head sampling applies — a refresh is a
            # background job, not a request), LINKED to the update that
            # scheduled it via the ``link`` arg ("trace:span")
            with get_tracer().span("ann.refresh", link=link):
                while True:
                    # an abandoned attempt (a newer delta landed
                    # mid-fold) retries against the newer token —
                    # deltas that arrived while we were the debounced
                    # in-flight refresh must not be left stale until
                    # some future update happens by
                    result = self.refresh_index()
                    if result.get("abandoned"):
                        continue
                    # Close the debounce window: a delta landing after
                    # refresh_index released the swap lock but before
                    # we clear the flag saw inflight=True and skipped
                    # scheduling — its staleness is ours to absorb.
                    # Re-check under the lock that owns both the flag
                    # and the index; only hand the flag back when no
                    # refreshable staleness remains. The progress guard
                    # (refreshed > 0) keeps rows refresh CANNOT clear
                    # (unsupported embedding, unplaced) from spinning
                    # this thread forever.
                    with self._swap_lock:
                        ann = self._ann
                        more = (
                            ann is not None
                            and ann.index.meta.get("embedding") == "struct"
                            and ann.index.stale_count
                            and result.get("refreshed", 0) > 0
                        )
                        if not more:
                            self._ann_refresh_inflight = False
                            return
        except Exception as exc:  # background thread: report, never die
            runtime_event("ann_refresh_failed", error=repr(exc))
            with self._swap_lock:
                self._ann_refresh_inflight = False

    def refresh_index(self) -> dict:
        """Re-embed every delta-staled index row in place and advance
        the index's consistency token to the service's — the
        "background refresh" half of the staleness contract (DESIGN.md
        §23). Embeddings come from the PATCHED graph on the index's
        pinned quadrature grid/projection, so refreshed rows stay
        inner-product-consistent with the rest of the index. Rows the
        index cannot hold (appended past the build) stay on the exact
        path; the accounting reports them. Also re-snapshots C/d for
        the exact rerank and resets the shadow-recall gate (old
        evidence described the old index state).

        The expensive inputs (half-chain fold, re-embedding) are
        computed OUTSIDE the swap lock against a token snapshot —
        serving keeps flowing while they build — and applied under the
        lock only if no further delta landed meanwhile (a newer delta
        has already scheduled its own refresh, so abandoning is
        correct, not lossy)."""
        from ..index.build import (
            half_chain_and_denominators, refresh_embeddings,
        )

        t0 = time.perf_counter()
        with self._swap_lock:
            ann = self._ann
            if ann is None:
                return {"ann": False, "refreshed": 0}
            if ann.index.meta.get("embedding") != "struct":
                # learned indexes re-embed by re-running the tower
                # offline — surface "rebuild required" instead of
                # paying the fold just to hit build.py's ValueError
                result = {
                    "ann": True, "refreshed": 0,
                    "stale_remaining": ann.index.stale_count,
                    "unsupported": "learned-embedding index: rebuild "
                    "offline (dpathsim index build) and reload",
                }
                runtime_event("ann_refresh_unavailable", **result)
                return result
            token0 = self.consistency_token
            hin = self.hin
            stale_rows = np.flatnonzero(ann.index.stale)
        tracer = get_tracer()
        with tracer.child_span(
            "index.half_chain_fold", stale=int(stale_rows.size)
        ):
            c, d = half_chain_and_denominators(
                hin, self.metapath, self.variant
            )
        emb = (
            refresh_embeddings(ann.index, stale_rows, c, d)
            if stale_rows.size else None
        )
        with self._swap_lock:
            if self._ann is not ann or self.consistency_token != token0:
                runtime_event(
                    "ann_refresh_abandoned", token=list(token0),
                    reason="newer delta landed during the re-embed",
                )
                return {"ann": True, "refreshed": 0, "abandoned": True}
            # drained like update(): the probe reads the index arrays
            # this refresh mutates, and a batch must never straddle it
            self.coalescer.drain()
            unplaced: list[int] = []
            if emb is not None:
                unplaced = ann.index.refresh_rows(
                    stale_rows, emb, token=token0
                )
            else:
                ann.index.token = token0
            c.flags.writeable = False
            ann.c64 = c
            ann.d = d
            if ann.variant == "rerank-all":
                ann.rebind_counts()  # blocks must mirror the new slots
            ann.reset_confidence()
            ms = round((time.perf_counter() - t0) * 1e3, 3)
            result = {
                "ann": True,
                "refreshed": int(stale_rows.size) - len(unplaced),
                "unplaced": len(unplaced),
                "stale_remaining": ann.index.stale_count,
                "uncovered_rows": max(self.n - ann.index.n, 0),
                "token": list(ann.index.token),
                "ms": ms,
            }
            runtime_event("ann_refresh", **result)
            return result

    def _refresh_towers_quietly(self, link: str | None = None) -> None:
        try:
            with get_tracer().span("learned.refresh", link=link):
                while True:
                    # abandoned attempts (a newer delta landed mid-
                    # fold) retry against the newer token — the newer
                    # update saw inflight=True and skipped scheduling,
                    # so its staleness is ours to absorb
                    result = self.refresh_towers()
                    if result.get("abandoned"):
                        continue
                    with self._swap_lock:
                        lr = self._learned
                        more = (
                            lr is not None
                            and (lr.stale_count or lr.pending_appends)
                            and result.get("refreshed", 0) > 0
                        )
                        if not more:
                            self._learned_refresh_inflight = False
                            return
        except Exception as exc:  # background thread: report, never die
            runtime_event("learned_refresh_failed", error=repr(exc))
            with self._swap_lock:
                self._learned_refresh_inflight = False

    def refresh_towers(self) -> dict:
        """Absorb the patched graph into the learned tier: swap in the
        current C/d snapshot and re-embed exactly the stale + appended
        rows through the inductive encoder — O(Δ) tower work, zero XLA
        compiles, the cold-start path that makes a never-seen appended
        author answerable in learned mode before any full re-embed
        (DESIGN.md §32). Mirrors :meth:`refresh_index`'s locking: the
        expensive half-chain fold runs OUTSIDE the swap lock against a
        token snapshot, the absorb applies under the lock with the
        pipeline drained only if no further delta landed meanwhile.
        A contraction-width change (new venue vocabulary moved the
        feature space) is reported, not raised — affected service
        keeps degrading those rows, correctly, until retrained."""
        from ..index.build import half_chain_and_denominators

        t0 = time.perf_counter()
        with self._swap_lock:
            lr = self._learned
            if lr is None:
                return {"learned": False, "refreshed": 0}
            token0 = self.consistency_token
            hin = self.hin
            stale_n = lr.stale_count
            pending = lr.pending_appends
        tracer = get_tracer()
        with tracer.child_span(
            "learned.half_chain_fold", stale=stale_n, appends=pending
        ):
            c, d = half_chain_and_denominators(
                hin, self.metapath, self.variant
            )
        with self._swap_lock:
            if self._learned is not lr or self.consistency_token != token0:
                runtime_event(
                    "learned_refresh_abandoned", token=list(token0),
                    reason="newer delta landed during the fold",
                )
                return {"learned": True, "refreshed": 0, "abandoned": True}
            # drained like update(): the probe reads the embedding
            # array absorb swaps, and a batch must never straddle it
            self.coalescer.drain()
            try:
                with tracer.child_span("learned.absorb"):
                    acct = lr.absorb(c, d, token0)
            except ValueError as exc:
                result = {
                    "learned": True, "refreshed": 0,
                    "stale_remaining": lr.stale_count,
                    "unsupported": str(exc),
                }
                runtime_event("learned_refresh_unavailable", **result)
                return result
            # old shadow evidence described the pre-absorb towers
            lr.reset_confidence()
            ms = round((time.perf_counter() - t0) * 1e3, 3)
            result = {
                "learned": True,
                "refreshed": acct["re_embedded"],
                "appended": acct["appended"],
                "stale_remaining": lr.stale_count,
                "pending_appends": lr.pending_appends,
                "token": list(lr.token),
                "ms": ms,
            }
            runtime_event("learned_refresh", **result)
            return result

    def reload(self, backend: PathSimBackend) -> None:
        """Swap in a freshly built backend (graph reload): drain the
        in-flight pipeline, install + rewarm, invalidate both cache
        tiers. Queries submitted after return are answered from the new
        graph — and the epoch key guarantees no stale entry can ever be
        served even across the swap."""
        with self._swap_lock:
            self.coalescer.drain()
            old_fp = self._fp
            self._install_backend(backend, warm=self.config.warm)
            self.invalidate()
            runtime_event(
                "serve_reload", from_fingerprint=old_fp,
                to_fingerprint=self._fp,
            )

    def _apply_compaction(self, backend: PathSimBackend, hin_c,
                          token0: tuple) -> dict:
        """The compaction-swap doorway: the ONLY path by which a
        compaction-built backend enters service (serving/compact.py is
        the sole caller — analyzer-sealed, CP001). Under the swap
        lock: verify the build's token snapshot still chains to the
        live token (a reload/rebuild re-based it → abandon), replay
        the deltas that landed mid-build onto the new backend (O(Δ)
        each; the build pre-folded the half chain), drain the
        pipeline, and hot-swap. Returns either ``{"abandoned":
        reason}`` or the swap accounting (replayed count, pause
        seconds, new capacities)."""
        from ..backends.base import DeltaUnsupported
        from ..data.delta import plan_delta

        comp = self._compactor
        with self._swap_lock:
            log = comp._log
            want = (
                (token0[0], token0[1] + len(log))
                if log is not None else None
            )
            if want is None or self.consistency_token != want:
                return {"abandoned": "token moved during build"}
            t_pause = time.perf_counter()
            hin_cur = hin_c
            for delta in log:
                plan = plan_delta(
                    hin_cur, delta, self.metapath,
                    max_delta_fraction=self.config.delta_threshold,
                )
                if plan.fallback:
                    return {"abandoned": "replayed delta fell back"}
                try:
                    backend.apply_delta(plan)
                except DeltaUnsupported:
                    return {"abandoned": "replayed delta unsupported"}
                hin_cur = plan.hin_new
            with get_tracer().child_span(
                "compact.swap", replayed=len(log)
            ):
                self.coalescer.drain()
                self._swap_compacted(backend, hin_cur)
            comp._chain = 0
            return {
                "replayed_deltas": len(log),
                "pause_s": time.perf_counter() - t_pause,
                "capacity": {
                    t: idx.padded_size
                    for t, idx in hin_cur.indices.items()
                    if idx.capacity is not None
                },
                "token": list(self.consistency_token),
            }

    def _swap_compacted(self, backend: PathSimBackend, hin) -> None:
        """Install a compaction-built backend for the SAME logical
        graph. Caller (:meth:`_apply_compaction`) holds ``_swap_lock``
        with the pipeline drained. Unlike :meth:`_install_backend`
        this preserves the consistency token, the chained fingerprint,
        the per-row cache versions, and BOTH cache tiers — the graph
        content did not change, only its physical padding — so router
        fencing sees nothing and every warm entry stays servable. The
        bucket ladder is untouched (keyed on the unchanged logical n),
        and no rewarm runs here: the build thread warmed the new
        padded shapes before taking the lock."""
        self.backend = backend
        self.hin = hin
        # engines bind the old hin generation; they rebuild lazily
        with self._engines_lock:
            self._engines.clear()
        self.index = self.hin.indices[self.node_type]
        self.n = self.index.size
        old_ver = self._row_ver
        new_ver = np.zeros(self.index.padded_size, dtype=np.int64)
        m = min(old_ver.shape[0], new_ver.shape[0])
        new_ver[:m] = old_ver[:m]
        self._row_ver = new_ver
        self._d = np.asarray(
            backend._denominators(self.variant), dtype=np.float64
        )

    def compact(self, wait_s: float = 300.0) -> dict:
        """Force one compaction now (the ``compact`` protocol op): the
        same build-then-hot-swap the automatic triggers run, executed
        synchronously. Returns the compaction accounting (``swapped``,
        replayed deltas, build/pause ms, compile count, new per-type
        capacities). Serving keeps flowing during the build; only the
        swap itself (drain + replay + install) pauses admissions."""
        return self._compactor.compact_now(
            reason="requested", wait_s=wait_s
        )

    def _engine_summaries(self) -> dict:
        with self._engines_lock:
            engines = sorted(self._engines.items())
        return {
            name: {
                "backend": eng.backend.name,
                "n": eng.n,
                "fallback_from": eng.fallback_from,
                **eng.backend.plan.summary(),
            }
            for name, eng in engines
        }

    def stats(self) -> dict:
        c = self.coalescer
        batches = max(c.batch_count, 1)
        # live latency quantiles from the obs registry — the extended
        # snapshot: stats() answers "where is the p99 right now" without
        # anyone replaying JSONL
        lat = {}
        for outcome, cell in self._m_latency.items():
            if cell.count:
                lat[outcome] = {
                    "count": cell.count,
                    "p50_ms": round(cell.quantile(0.50) * 1e3, 4),
                    "p95_ms": round(cell.quantile(0.95) * 1e3, 4),
                    "p99_ms": round(cell.quantile(0.99) * 1e3, 4),
                }
        from .. import tuning

        table = tuning.active_table()
        return {
            "obs": {
                "latency": lat,
                "tracing": get_tracer().enabled,
                "metrics": get_registry().enabled,
                "tuning": {
                    "table": table.digest if table is not None else None,
                    "lookups": tuning.lookup_stats(),
                    "buckets": list(self._bucket_ladder),
                },
            },
            "n": self.n,
            "metapath": self.metapath.name,
            "variant": self.variant,
            "backend": self.backend.name,
            "fingerprint": self._fp,
            # Planner visibility (DESIGN.md §28): the primary plan's
            # chosen association order + cost estimates, every live
            # secondary engine's, and the sub-chain memo accounting —
            # stats() answers "what did the planner decide and is the
            # memo earning its bytes" without log replay.
            "plan": {
                "primary": self.backend.plan.summary(),
                "engines": self._engine_summaries(),
                "memo": (
                    self.memo.stats() if self.memo is not None else None
                ),
            },
            # Resident factor accounting (DESIGN.md §29): format,
            # measured bytes, and the COO-equivalent bytes — the
            # memory-headroom number the SLO/fleet-stats tier reads
            # (None for backends with no resident sparse factor).
            "factor": self.backend.factor_info(),
            "topk_mode": self.config.topk_mode,
            "ann": self._ann.snapshot() if self._ann is not None else None,
            "learned": (
                self._learned.snapshot()
                if self._learned is not None else None
            ),
            "delta": {
                "seq": self._delta_seq,
                "base_fingerprint": self._base_fp,
                "headroom": self.index.headroom,
                **self._update_stats,
            },
            # Background compaction accounting (DESIGN.md §30): trigger
            # state, swap/abandon counters, and the last swap's
            # build/pause/compile numbers — the firehose bench's gates
            # read these instead of replaying the event log.
            "compaction": self._compactor.snapshot(),
            "result_cache": {
                "hits": self.result_cache.hits,
                "misses": self.result_cache.misses,
                "entries": len(self.result_cache),
                "evictions": self.result_cache.evictions,
            },
            "tile_cache": {
                "hits": self.tile_cache.hits,
                "misses": self.tile_cache.misses,
                "bytes": self.tile_cache.bytes_used,
                "evictions": self.tile_cache.evictions,
            },
            "dispatch": {
                "batches": c.batch_count,
                "requests": c.dispatched_requests,
                "shed": c.shed_count,
                "mean_batch": round(c.dispatched_requests / batches, 3),
                "mean_wait_ms": round(self._wait_ms_sum / batches, 3),
                "buckets": dict(sorted(self._bucket_hist.items())),
            },
        }

    def close(self) -> None:
        self.coalescer.close()
        if self._ann is not None:
            self._ann.close()
        if self._learned is not None:
            self._learned.close()


def build_service(
    config,
    serve_config: ServeConfig | None = None,
    timer=None,
):
    """RunConfig → warm PathSimService (engine bootstrap + serving
    wrap): the one-call path the ``serve`` CLI and the load generator
    share."""
    from ..backends.base import create_backend
    from ..engine import backend_options, build_backend

    t0 = time.perf_counter()
    _, metapath, backend = build_backend(config, timer=timer)
    service = PathSimService(
        backend,
        variant=config.variant,
        config=serve_config,
        # delta-fallback rebuilds replay the full RunConfig knobs
        backend_factory=lambda hin: create_backend(
            config.backend, hin, metapath,
            # the service's sub-chain memo rides into rebuilds so a
            # refold hits the entries the delta did not invalidate
            # (installed below once the service — and its memo — exist)
            subchain_memo=service.memo,
            **backend_options(config),
        ),
    )
    runtime_event(
        "serve_ready",
        backend=backend.name,
        n=service.n,
        metapath=service.metapath.name,
        startup_s=round(time.perf_counter() - t0, 3),
    )
    return service
