"""Shape buckets for the serving layer's coalesced dispatch.

XLA specializes every jit program on its static shapes, so dispatching
request batches at their natural sizes (3 requests now, 7 next tick, 12
after that) would compile a fresh executable per distinct batch size —
tens of seconds each through a TPU tunnel, paid at serving time. Instead
every coalesced batch is padded UP to the nearest power of two from a
small fixed ladder: at most ``log2(max_batch)+1`` programs ever exist,
all of them pre-compiled at startup (``utils.xla_flags.
warm_compile_cache``), and steady-state traffic never triggers a
compile. Powers of two keep the ladder short (worst-case pad waste is
<2×, and the padded GEMM rows are nearly free next to the dispatch
overhead the batching amortizes) while covering every batch size the
coalescer can form.

Padding is semantically inert by construction: the pad slots repeat the
batch's first row, each row of the batched GEMM is an independent dot
product, and the completion path slices the pad off before anything
downstream sees it — verified by test (padded vs unbatched results are
bit-identical).
"""

from __future__ import annotations

import numpy as np

DEFAULT_BUCKETS: tuple[int, ...] = (1, 2, 4, 8, 16, 32)


def bucket_ladder(max_batch: int) -> tuple[int, ...]:
    """Powers of two 1, 2, 4, … covering ``max_batch`` (the last bucket
    is the smallest power of two ≥ max_batch). Delegates to the tuning
    registry's ``resolve_ladder`` — the one implementation of ladder
    geometry — so the untuned default can never drift from what a tuned
    'pow2' choice resolves to."""
    from ..tuning.registry import resolve_ladder

    return resolve_ladder("pow2", max_batch)


def bucket_for(n: int, buckets: tuple[int, ...]) -> int:
    """Smallest bucket ≥ ``n``. The coalescer caps batches at the
    largest bucket, so a miss is a caller bug — fail loudly."""
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"batch of {n} exceeds largest bucket {max(buckets)}")


def pad_rows(rows: np.ndarray, bucket: int) -> np.ndarray:
    """Pad a row-index batch to ``bucket`` by repeating the first row
    (deterministic, always a valid index; pad results are discarded)."""
    rows = np.asarray(rows, dtype=np.int64)
    if rows.shape[0] == bucket:
        return rows
    return np.concatenate(
        [rows, np.full(bucket - rows.shape[0], rows[0], dtype=np.int64)]
    )
