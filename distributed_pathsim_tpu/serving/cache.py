"""The serving layer's multi-tier result cache.

Atrapos (arXiv:2201.04058) measures real metapath query workloads as
dominated by repeated sub-queries; for PathSim serving the repetition
shows up at two granularities, hence two tiers in front of dispatch:

- **Tier 1 — result LRU**: finished top-k answers keyed by the full
  query identity ``(graph_fingerprint, metapath, variant, row, k)``.
  A hit is a dict lookup; nothing touches the backend.
- **Tier 2 — hot-tile score cache**: normalized f64 score ROWS, grouped
  into row tiles (the all-pairs matrix's natural reuse unit — a hot
  author's whole neighborhood tends to get queried together). A hit
  re-runs only the O(N) host top-k selection, e.g. for a different
  ``k`` than what tier 1 holds — no device dispatch. Eviction is
  tile-granular under a byte budget: hot tiles survive wholesale, cold
  tiles leave wholesale.

Both tiers key on the **graph fingerprint** (content hash of every
adjacency block), so a graph reload can never serve stale answers even
if explicit invalidation were forgotten; reload additionally clears both
tiers outright (``invalidate``) to return the memory.

Thread safety: every public method takes the tier's lock — client
threads, the coalescer's completion thread, and the reload path all
touch these concurrently.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np

from ..data.encode import EncodedHIN
from ..obs.metrics import get_registry


def _tier_counters(tier: str):
    """Bound obs counter cells for one cache tier — bound ONCE at cache
    construction so the per-hit cost is a single cell increment, not a
    registry lookup. Per-instance ``hits``/``misses`` attributes stay
    authoritative for ``stats()``; the registry cells are the
    process-wide aggregate Prometheus and the ``metrics`` op read."""
    reg = get_registry()
    return (
        reg.counter(
            "dpathsim_serve_cache_hits_total", "cache hits by tier"
        ).labels(tier=tier),
        reg.counter(
            "dpathsim_serve_cache_misses_total", "cache misses by tier"
        ).labels(tier=tier),
        reg.counter(
            "dpathsim_serve_cache_evictions_total", "cache evictions by tier"
        ).labels(tier=tier),
    )


def graph_fingerprint(hin: EncodedHIN) -> str:
    """Content hash of the encoded graph: every adjacency block's COO
    plus the per-type sizes. Two graphs with equal fingerprints produce
    equal scores, so the fingerprint is a sound cache key component.

    Memoized per EncodedHIN (``object.__setattr__`` on the frozen
    dataclass): re-hashing every COO block on each reload/build was an
    O(nnz) tax the serving path paid repeatedly, and delta-derived HINs
    carry a CHAINED fingerprint seeded by plan_delta
    (:func:`chain_fingerprint`) — their blocks are never hashed at all.
    """
    cached = hin.__dict__.get("_fingerprint_cache")
    if cached is not None:
        return cached
    h = hashlib.sha256()
    for t in sorted(hin.schema.node_types):
        h.update(f"{t}:{hin.type_size(t)};".encode())
    for name in sorted(hin.blocks):
        b = hin.blocks[name]
        h.update(f"{name}:{b.shape};".encode())
        h.update(np.ascontiguousarray(b.rows, dtype=np.int64).tobytes())
        h.update(np.ascontiguousarray(b.cols, dtype=np.int64).tobytes())
    fp = h.hexdigest()[:16]
    object.__setattr__(hin, "_fingerprint_cache", fp)
    return fp


def chain_fingerprint(base_fp: str, delta_digest: str) -> str:
    """Fingerprint of base graph ⊕ delta: ``sha256(base ∥ delta)``.

    Sound as cache identity because a delta batch is content-addressed
    (DeltaBatch.digest hashes its arrays) and apply_delta is a pure
    function of (graph, delta) — equal chains denote equal graphs. The
    ``~`` separator keeps the 17-char chained form disjoint from the
    16-hex-char base form."""
    return (
        "~" + hashlib.sha256(f"{base_fp}|{delta_digest}".encode()).hexdigest()[:16]
    )


class ResultCache:
    """Tier 1: LRU of finished (values, indices) top-k answers."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._d: OrderedDict[tuple, tuple[np.ndarray, np.ndarray]] = (
            OrderedDict()
        )
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._m_hits, self._m_misses, self._m_evict = _tier_counters("result")

    def get(self, key: tuple):
        with self._lock:
            hit = self._d.get(key)
            if hit is None:
                self.misses += 1
                self._m_misses.inc()
                return None
            self._d.move_to_end(key)
            self.hits += 1
            self._m_hits.inc()
            return hit

    def put(self, key: tuple, vals: np.ndarray, idxs: np.ndarray) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._d[key] = (vals, idxs)
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)
                self.evictions += 1
                self._m_evict.inc()

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def purge_rows(self, rows) -> int:
        """Drop every entry whose source row is in ``rows`` — the
        row-granular delta invalidation. Keys are
        ``(..., row, k)``; entries for other rows survive untouched.
        Returns how many entries were dropped. O(entries), bounded by
        the LRU capacity — far cheaper than the total flush it
        replaces (which also evicted every still-valid answer)."""
        rows = set(int(r) for r in rows)
        if not rows:
            return 0
        with self._lock:
            doomed = [key for key in self._d if int(key[-2]) in rows]
            for key in doomed:
                del self._d[key]
            return len(doomed)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)


class HotTileCache:
    """Tier 2: score rows grouped into row tiles, LRU by tile under a
    byte budget. Rows fill in lazily (a tile entry holds whichever of
    its rows have been computed); eviction drops whole tiles."""

    def __init__(self, budget_bytes: int, tile_rows: int = 64):
        self.budget_bytes = int(budget_bytes)
        self.tile_rows = max(1, int(tile_rows))
        self._lock = threading.Lock()
        # tile id → {row → f64 score row}
        self._tiles: OrderedDict[tuple, dict[int, np.ndarray]] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._m_hits, self._m_misses, self._m_evict = _tier_counters("tile")

    def _tile_key(self, epoch: tuple, row: int) -> tuple:
        return (*epoch, row // self.tile_rows)

    def get_row(self, epoch: tuple, row: int) -> np.ndarray | None:
        with self._lock:
            tile = self._tiles.get(self._tile_key(epoch, row))
            hit = None if tile is None else tile.get(row)
            if hit is None:
                self.misses += 1
                self._m_misses.inc()
                return None
            self._tiles.move_to_end(self._tile_key(epoch, row))
            self.hits += 1
            self._m_hits.inc()
            return hit

    def put_row(self, epoch: tuple, row: int, scores: np.ndarray) -> None:
        if self.budget_bytes <= 0:
            return
        with self._lock:
            key = self._tile_key(epoch, row)
            tile = self._tiles.get(key)
            if tile is None:
                tile = self._tiles[key] = {}
            if row not in tile:
                self._bytes += scores.nbytes
            tile[row] = scores
            self._tiles.move_to_end(key)
            while self._bytes > self.budget_bytes and len(self._tiles) > 1:
                _, dropped = self._tiles.popitem(last=False)
                self._bytes -= sum(v.nbytes for v in dropped.values())
                self.evictions += 1
                self._m_evict.inc()

    def clear(self) -> None:
        with self._lock:
            self._tiles.clear()
            self._bytes = 0

    def purge_rows(self, rows) -> int:
        """Drop the cached score rows in ``rows`` (delta invalidation).
        Tiles keep their surviving rows — eviction stays tile-granular,
        invalidation is row-granular. Returns rows dropped."""
        rows = set(int(r) for r in rows)
        if not rows:
            return 0
        dropped = 0
        with self._lock:
            for key in list(self._tiles):
                tile = self._tiles[key]
                doomed = [r for r in tile if r in rows]
                for r in doomed:
                    self._bytes -= tile[r].nbytes
                    del tile[r]
                dropped += len(doomed)
                if not tile:
                    del self._tiles[key]
        return dropped

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes
