"""``dpathsim serve`` — the online serving entry point.

Bootstraps the engine once (same flags as the batch CLI: dataset,
backend, metapath, variant, platform, loader), wraps the warm backend
in a :class:`PathSimService`, and speaks the JSONL protocol on
stdin/stdout until EOF or a ``shutdown`` op::

    echo '{"id": 1, "op": "topk", "source": "Didier Dubois", "k": 5}' \
        | dpathsim serve --dataset dblp/dblp_small.gexf --backend jax

Structured events (bucket warm times, batch accounting, sheds, reload)
ride the same --metrics JSONL channel the batch CLI uses.
"""

from __future__ import annotations

import argparse
import sys

from ..backends.base import available_backends
from ..config import RunConfig
from ..ops.pathsim import VARIANTS
from .service import ServeConfig, build_service


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dpathsim serve",
        description="online PathSim serving: JSONL queries on stdin, "
        "JSONL answers on stdout",
    )
    p.add_argument("--dataset", default=RunConfig.dataset)
    p.add_argument("--backend", default="jax", choices=available_backends())
    p.add_argument(
        "--metapath", default="APVPA",
        help="default served metapath; requests may override per query "
        "via the protocol's 'metapath' field (closed metapaths only)",
    )
    p.add_argument(
        "--memo-budget-mb", type=float, default=None,
        help="sub-chain memo budget shared by all metapath engines "
        "(default: the tuned plan_memo_budget_mb knob; 0 disables)",
    )
    p.add_argument(
        "--max-metapaths", type=int, default=8,
        help="bound on lazily-built per-request metapath engines",
    )
    p.add_argument("--variant", default="rowsum", choices=list(VARIANTS))
    p.add_argument(
        "--loader", default="auto", choices=("auto", "python", "native")
    )
    p.add_argument("--platform", default="auto", choices=("auto", "cpu", "tpu"))
    p.add_argument("--dtype", default="float32")
    p.add_argument("--n-devices", type=int, default=None)
    p.add_argument("--tile-rows", type=int, default=None)
    p.add_argument("--approx", action="store_true")
    p.add_argument(
        "--factor-format", default=None,
        choices=("coo", "blocked", "bitpacked"),
        help="resident sparse-factor layout (DESIGN.md §29): "
        "compressed layouts hold the half-chain factor in 1/3-1/6 "
        "of the COO bytes, bit-identically; default resolves "
        "through the tuning registry ('coo' when untuned)",
    )
    p.add_argument("--metrics", default=None, help="JSONL metrics/events file")
    p.add_argument("--k", type=int, default=10, help="default top-k")
    p.add_argument(
        "--max-batch", type=int, default=32,
        help="coalescing cap; buckets are powers of two up to this",
    )
    p.add_argument(
        "--max-wait-ms", type=float, default=2.0,
        help="how long a formed batch waits for stragglers",
    )
    p.add_argument(
        "--queue-depth", type=int, default=256,
        help="admission bound; requests beyond it are shed",
    )
    p.add_argument(
        "--cache-entries", type=int, default=4096,
        help="result LRU capacity (0 disables tier 1)",
    )
    p.add_argument(
        "--tile-cache-mb", type=float, default=64.0,
        help="hot-tile score cache budget (0 disables tier 2)",
    )
    p.add_argument(
        "--no-warm", action="store_true",
        help="skip pre-compiling the shape buckets at startup",
    )
    p.add_argument(
        "--batch-events", action="store_true",
        help="emit a JSONL event per dispatched batch",
    )
    p.add_argument(
        "--headroom", type=float, default=0.25,
        help="index-capacity reserve for recompile-free 'update' ops "
        "(fraction of each type's size; 0 disables — every node append "
        "then forces a full rebuild)",
    )
    p.add_argument(
        "--delta-threshold", type=float, default=0.05,
        help="'update' batches changing more than this fraction of "
        "edges rebuild instead of patching",
    )
    p.add_argument(
        "--no-compact", action="store_true",
        help="disable automatic background compaction (the 'compact' "
        "op still works on demand); without it, headroom exhaustion "
        "falls back to the synchronous inline rebuild",
    )
    p.add_argument(
        "--compact-chain-len", type=int, default=None,
        help="deltas absorbed since the last re-encode before a "
        "background compaction triggers (default: the tuned "
        "compact_chain_len knob)",
    )
    p.add_argument(
        "--compact-headroom-frac", type=float, default=0.10,
        help="compact when the capacity reserve falls below this "
        "fraction of the logical size (types that reserved headroom "
        "at build only)",
    )
    p.add_argument(
        "--compact-headroom", type=float, default=None,
        help="fresh capacity reserve of a compaction re-encode, as a "
        "fraction of size, padded to pow-2 (default: the tuned "
        "compact_headroom knob)",
    )
    p.add_argument(
        "--compact-cooldown", type=float, default=5.0,
        help="seconds between background compactions",
    )
    p.add_argument(
        "--metrics-file", default=None,
        help="Prometheus textfile: counters/gauges/latency histograms "
        "re-written atomically every --metrics-interval (node-exporter "
        "textfile-collector format)",
    )
    p.add_argument(
        "--metrics-interval", type=float, default=5.0,
        help="seconds between --metrics-file snapshots",
    )
    p.add_argument(
        "--trace-out", default=None,
        help="enable request tracing and write the span ring as "
        "Chrome/Perfetto trace-event JSON here on shutdown",
    )
    p.add_argument(
        "--trace-sample", type=int, default=1,
        help="trace every Nth request (head sampling; 1 = every "
        "request, the debugging default — sustained production "
        "traffic wants 16+ to keep span bookkeeping off the hot path)",
    )
    p.add_argument(
        "--no-metrics", action="store_true",
        help="disable the in-process metrics registry entirely "
        "(stats/metrics ops then report zeros)",
    )
    p.add_argument(
        "--tuning-table", default=None,
        help="measured dispatch table from `dpathsim tune` (drives "
        "kernel/tile/bucket choices incl. the warmup ladder); unusable "
        "tables degrade to heuristics with a tuning_fallback event",
    )
    p.add_argument(
        "--no-tuning", action="store_true",
        help="ignore any tuning table (env included)",
    )
    p.add_argument(
        "--topk-mode", default="exact",
        choices=("exact", "ann", "learned"),
        help="default topk answer path: 'exact' scores the full O(N) "
        "row; 'ann' probes the MIPS candidate index and exact-reranks "
        "C >> k candidates; 'learned' shortlists via the two-tower "
        "encoder and exact-reranks (per-request override via the "
        "protocol's 'mode' field; ineligible rows silently degrade "
        "learned -> ann -> exact, counted per reason)",
    )
    p.add_argument(
        "--index", default=None,
        help="prebuilt `dpathsim index build` artifact (.npz); must "
        "match the served graph's base fingerprint. Absent with "
        "--topk-mode ann, the struct-embedded index is built "
        "in-process at startup",
    )
    p.add_argument(
        "--ann-nprobe", type=int, default=None,
        help="clusters probed per ANN query (default: tuning registry)",
    )
    p.add_argument(
        "--ann-cand-mult", type=int, default=None,
        help="candidates per ANN query as a multiple of k (default: "
        "tuning registry)",
    )
    p.add_argument(
        "--ann-centroids", type=int, default=None,
        help="centroid count for the in-process index build "
        "(default: tuned multiplier on sqrt(N))",
    )
    p.add_argument(
        "--ann-cluster-cap", type=int, default=None,
        help="packed-cluster capacity for the in-process index build "
        "(default: tuning registry / auto)",
    )
    p.add_argument(
        "--ann-variant", default=None,
        choices=("rerank-all", "shortlist"),
        help="candidate-generation strategy (default: tuning "
        "registry; 'rerank-all' exact-reranks every probed member, "
        "'shortlist' cuts to cand_mult*k by embedding similarity "
        "first)",
    )
    p.add_argument(
        "--ann-shadow-every", type=int, default=64,
        help="every Nth ANN dispatch also runs the exact oracle and "
        "feeds the recall-confidence gate (0 disables shadowing)",
    )
    p.add_argument(
        "--no-ann-refresh", action="store_true",
        help="disable the background re-embed of delta-staled index "
        "rows (they then stay on the exact path until the "
        "'refresh_index' op)",
    )
    p.add_argument(
        "--learned-checkpoint", default=None,
        help="prebuilt `dpathsim learned train` tower artifact (.npz); "
        "must match the served graph's base fingerprint and token. "
        "Absent with --topk-mode learned, a tower is distilled "
        "in-process at startup",
    )
    p.add_argument(
        "--learned-dim", type=int, default=None,
        help="tower output width for the in-process distillation "
        "(default: tuning registry)",
    )
    p.add_argument(
        "--learned-steps", type=int, default=200,
        help="distillation steps for the in-process startup training",
    )
    p.add_argument(
        "--learned-neg-ratio", type=float, default=None,
        help="uniform-negative fraction of in-process training slates "
        "(default: tuning registry)",
    )
    p.add_argument(
        "--learned-cand-mult", type=int, default=None,
        help="candidates per learned query as a multiple of k "
        "(default: tuning registry)",
    )
    p.add_argument(
        "--learned-shadow-every", type=int, default=64,
        help="every Nth learned dispatch also runs the exact oracle "
        "and feeds the recall-confidence gate (0 disables shadowing)",
    )
    p.add_argument(
        "--learned-recall-floor", type=float, default=None,
        help="shadow score-recall floor below which the learned arm "
        "disables itself (default: tuning registry)",
    )
    p.add_argument(
        "--no-learned-refresh", action="store_true",
        help="disable the background tower re-embed after deltas "
        "(stale/appended rows then degrade until the "
        "'refresh_towers' op)",
    )
    return p


def serve_main(argv: list[str] | None = None) -> int:
    args = build_serve_parser().parse_args(argv)
    if "," in args.metapath:
        raise ValueError(
            "serve runs one metapath per service; multi-metapath "
            "ensembles are not served yet"
        )
    if args.factor_format is not None and args.backend != "jax-sparse":
        # same refusal as the batch CLI: other backends would swallow
        # the option via **options and serve uncompressed silently
        raise ValueError(
            "--factor-format selects the resident layout of the "
            "sparse half-chain factor and requires --backend jax-sparse"
        )
    from ..cli import _apply_platform, _require_tpu

    _apply_platform(args.platform)

    from ..utils.logging import RunLogger, set_event_sink
    from .protocol import serve_loop

    config = RunConfig(
        dataset=args.dataset,
        backend=args.backend,
        metapath=args.metapath,
        variant=args.variant,
        loader=args.loader,
        dtype=args.dtype,
        n_devices=args.n_devices,
        tile_rows=args.tile_rows,
        approx=args.approx,
        factor_format=args.factor_format,
        headroom=args.headroom,
        echo=False,
        tuning_table=args.tuning_table,
        tuning=not args.no_tuning,
    )
    serve_config = ServeConfig(
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        queue_depth=args.queue_depth,
        cache_entries=args.cache_entries,
        tile_cache_bytes=int(args.tile_cache_mb * (1 << 20)),
        k_default=args.k,
        warm=not args.no_warm,
        batch_events=args.batch_events,
        delta_threshold=args.delta_threshold,
        topk_mode=args.topk_mode,
        index_path=args.index,
        ann_nprobe=args.ann_nprobe,
        ann_cand_mult=args.ann_cand_mult,
        ann_centroids=args.ann_centroids,
        ann_cluster_cap=args.ann_cluster_cap,
        ann_variant=args.ann_variant,
        ann_shadow_every=args.ann_shadow_every,
        ann_auto_refresh=not args.no_ann_refresh,
        learned_checkpoint=args.learned_checkpoint,
        learned_dim=args.learned_dim,
        learned_steps=args.learned_steps,
        learned_neg_ratio=args.learned_neg_ratio,
        learned_cand_mult=args.learned_cand_mult,
        learned_shadow_every=args.learned_shadow_every,
        learned_recall_floor=args.learned_recall_floor,
        learned_auto_refresh=not args.no_learned_refresh,
        memo_budget_mb=args.memo_budget_mb,
        max_metapaths=args.max_metapaths,
        compact_auto=not args.no_compact,
        compact_chain_len=args.compact_chain_len,
        compact_headroom_frac=args.compact_headroom_frac,
        compact_headroom=args.compact_headroom,
        compact_cooldown_s=args.compact_cooldown,
    )
    from .. import obs

    obs.configure(
        metrics=not args.no_metrics,
        tracing=True if args.trace_out else None,
        trace_sample=args.trace_sample,
    )
    exporter = (
        obs.PrometheusTextfileExporter(
            args.metrics_file, interval_s=args.metrics_interval
        )
        if args.metrics_file
        else None
    )
    logger = RunLogger(output_path=None, echo=False,
                       metrics_path=args.metrics)
    set_event_sink(logger)
    # SIGTERM/SIGINT → graceful drain, NOT the batch CLI's
    # checkpoint-and-exit-75: serve_loop notices the latched request at
    # the next protocol event, completes in-flight work, flushes the
    # final metrics snapshot, and returns 0 (serving/protocol.py).
    from ..resilience import preemption_handler

    installed = preemption_handler.install()
    service = None
    try:
        service = build_service(config, serve_config)
        if args.platform == "tpu":
            _require_tpu()
        if exporter is not None:
            exporter.start()
        print(
            f"serving {service.metapath.name} over {service.n} "
            f"{service.node_type}s (backend={service.backend.name}); "
            "JSONL on stdin",
            file=sys.stderr,
        )
        return serve_loop(service, sys.stdin, sys.stdout)
    finally:
        if service is not None:
            service.close()
        if exporter is not None:
            exporter.stop()  # final write: shutdown state preserved
        if args.trace_out:
            print(obs.dump_trace(args.trace_out), file=sys.stderr)
        if installed:
            preemption_handler.uninstall()
            preemption_handler.reset()
        set_event_sink(None)
        logger.close()
