"""Background compaction: re-encode with fresh headroom, hot-swap.

A sustained delta firehose (ROADMAP item 3; the regime Atrapos,
arXiv:2201.04058, frames as concurrent metapath queries over a graph
that never stops changing) eventually exhausts what PR 3's O(Δ) patch
machinery can absorb: node appends eat the index-capacity reserve, and
when it runs out the NEXT update pays a full synchronous rebuild inline
— a multi-second stall in the middle of serving traffic. This module
moves that rebuild off the serving path:

- **Triggers** (checked per absorbed delta, under the swap lock):
  capacity headroom below ``compact_headroom_frac`` of the logical
  size, or more than ``compact_chain_len`` deltas absorbed since the
  last re-encode (both thresholds are tuning-registry knobs with real
  ``dpathsim tune`` arms).
- **Build** (background thread): the CURRENT logical graph is
  re-padded with fresh pow-2 headroom (:func:`compact_hin`) and a
  fresh backend is built through the service's sanctioned factory —
  the same call PR 14's packed layouts ride, so a compressed resident
  factor re-packs with headroom for free. Deltas that land during the
  build are recorded in a replay log; serving never stops.
- **Swap** (under the existing swap lock): the recorded deltas are
  replayed onto the new backend in O(Δ) each, the pipeline drains, and
  the backend is hot-swapped. The consistency token ``(base_fp,
  delta_seq)`` and the chained fingerprint are PRESERVED — the logical
  graph did not change, so PR-6 router fencing sees nothing, PR-7
  index tokens stay valid, and both cache tiers stay warm (zero
  entries purged: compaction is the one "update" that invalidates
  nothing). A rebuild or reload racing the build poisons the log and
  the attempt abandons, bounded by ``compact_attempts``.
- **Zero steady-state recompiles**: capacities are padded to pow-2
  buckets, so a re-encode at an unchanged bucket reuses every compiled
  program (the build thread counts its own compiles —
  ``dpathsim_compaction_compiles_total`` — and the firehose smoke
  gates that steady-state compactions add none).

The swap-lock hold (drain + replay + install) is the only pause
serving sees; it is measured into
``dpathsim_compaction_pause_seconds`` and gated in the firehose bench.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time

import numpy as np

from ..data.encode import EncodedHIN
from ..obs.metrics import get_registry
from ..obs.trace import get_tracer
from ..utils.logging import runtime_event


def _pow2_at_least(n: int) -> int:
    return 1 << max(int(math.ceil(math.log2(max(n, 1)))), 3)


def compact_hin(hin: EncodedHIN, headroom: float = 0.25) -> EncodedHIN:
    """The current logical graph, re-padded with a FRESH pow-2 capacity
    reserve per node type: ``capacity = pow2(size · (1 + headroom))``
    (min 8 slots of reserve). Types that never reserved headroom keep
    ``capacity=None`` — compaction refreshes the reserve, it does not
    change the headroom policy. Contents are untouched; padded slots
    carry no edges, so scores are bit-identical by the same argument
    ``with_headroom`` makes. Pow-2 buckets are the recompile contract:
    successive compactions at the same bucket produce identical array
    shapes, so every compiled XLA program survives the swap."""
    indices = {}
    for t, idx in hin.indices.items():
        if idx.capacity is None:
            indices[t] = idx
            continue
        cap = _pow2_at_least(
            max(int(math.ceil(idx.size * (1.0 + headroom))), idx.size + 8)
        )
        indices[t] = dataclasses.replace(idx, capacity=cap)
    blocks = {}
    for rel, b in hin.blocks.items():
        src, dst = hin.schema.relations[rel]
        blocks[rel] = dataclasses.replace(
            b,
            shape=(indices[src].padded_size, indices[dst].padded_size),
        )
    return EncodedHIN(
        schema=hin.schema, indices=indices, blocks=blocks, name=hin.name
    )


class Compactor:
    """Owns the compaction lifecycle for one :class:`PathSimService`.

    Thread discipline: every mutable field (``inflight``, the replay
    ``_log``, the chain counter) is read and written ONLY under the
    service's ``_swap_lock`` — ``note_update`` is called from
    ``service.update()`` which already holds it, and the build thread
    takes it for the snapshot and the swap. The build itself (the
    expensive part) runs outside the lock; serving continues."""

    def __init__(self, service):
        from .. import tuning

        self.service = service
        cfg = service.config
        self.chain_len = int(
            cfg.compact_chain_len
            if cfg.compact_chain_len is not None
            else tuning.choose(
                "compact_chain_len", n=service.n, default=256
            )
        )
        self.headroom = float(
            cfg.compact_headroom
            if cfg.compact_headroom is not None
            else tuning.choose(
                "compact_headroom", n=service.n, default=0.25
            )
        )
        self.headroom_frac = float(cfg.compact_headroom_frac)
        self.cooldown_s = float(cfg.compact_cooldown_s)
        self.max_attempts = max(int(cfg.compact_attempts), 1)
        # all guarded by service._swap_lock (see class docstring)
        self.inflight = False
        self._log: list | None = []
        self._chain = 0
        self._last_done = time.monotonic()
        self._done = threading.Event()
        self._done.set()
        self.compactions = 0
        self.abandoned = 0
        self.failures = 0
        self.last: dict = {}
        reg = get_registry()
        self._m_total = reg.counter(
            "dpathsim_compaction_total",
            "background compactions by outcome",
        )
        self._m_build = reg.histogram(
            "dpathsim_compaction_build_seconds",
            "off-path re-encode + backend build + rewarm time",
        ).labels()
        self._m_pause = reg.histogram(
            "dpathsim_compaction_pause_seconds",
            "swap-lock hold (drain + delta replay + install) per swap",
        ).labels()
        self._m_compiles = reg.counter(
            "dpathsim_compaction_compiles_total",
            "XLA compiles attributed to compaction builds",
        ).labels()
        self._m_headroom = reg.gauge(
            "dpathsim_compaction_headroom_frac",
            "min capacity headroom across types, as a fraction of size",
        ).labels()

    # -- trigger side (caller holds service._swap_lock) --------------------

    def _headroom_frac(self) -> float | None:
        """Min headroom/size over the types that reserved capacity;
        None when no type ever did (headroom triggering is then
        meaningless — every append already rebuilds)."""
        fracs = [
            idx.headroom / max(idx.size, 1)
            for idx in self.service.hin.indices.values()
            if idx.capacity is not None
        ]
        return min(fracs) if fracs else None

    def note_update(self, delta, mode: str) -> None:
        """One absorbed update: feed the replay log of an in-flight
        build, advance the chain counter, maybe trigger. Called under
        the swap lock from ``service.update()``."""
        if mode == "delta":
            self._chain += 1
            if self.inflight and self._log is not None:
                self._log.append(delta)
        else:
            # a rebuild re-encoded everything: the chain restarts and
            # any in-flight build is stale (its snapshot predates a
            # token re-base) — poison the log so the swap abandons
            self.note_rebuild()
        frac = self._headroom_frac()
        if frac is not None:
            self._m_headroom.set(frac)
        if not self.service.config.compact_auto or self.inflight:
            return
        if time.monotonic() - self._last_done < self.cooldown_s:
            return
        reason = None
        if self._chain >= self.chain_len:
            reason = f"delta chain at {self._chain} >= {self.chain_len}"
        elif frac is not None and frac < self.headroom_frac:
            reason = (
                f"headroom {frac:.3f} below {self.headroom_frac:.3f}"
            )
        if reason is None:
            return
        self._start(reason)

    def note_rebuild(self) -> None:
        """A reload/rebuild swapped the backend wholesale (token
        re-based): reset the chain, poison any in-flight build's log.
        Called under the swap lock."""
        self._chain = 0
        if self.inflight:
            self._log = None

    def _start(self, reason: str) -> None:
        """Spawn the build thread (caller holds the swap lock)."""
        self.inflight = True
        self._log = []
        self._done.clear()
        cur = get_tracer().current()
        link = (
            f"{cur.trace_id}:{cur.span_id}"
            if cur is not None and cur.span_id else None
        )
        runtime_event("serve_compact_trigger", reason=reason,
                      chain=self._chain)
        threading.Thread(
            target=self._run, args=(reason, link),
            name="pathsim-compact", daemon=True,
        ).start()

    # -- build side (background thread) ------------------------------------

    def compact_now(self, reason: str = "operator", wait_s: float = 300.0,
                    ) -> dict:
        """Force one compaction synchronously (the ``compact`` protocol
        op / benches). If a background build is already in flight, wait
        for it and return its accounting instead of stacking another."""
        with self.service._swap_lock:
            if not self.inflight:
                self.inflight = True
                self._log = []
                self._done.clear()
                started = True
            else:
                started = False
        if started:
            self._run(reason, None)
        elif not self._done.wait(wait_s):
            # the in-flight build outlived the wait: say so rather
            # than returning the PREVIOUS compaction's accounting as
            # if it answered this request
            return {
                "swapped": False,
                "error": f"in-flight compaction still running after "
                         f"{wait_s:g}s",
            }
        return dict(self.last)

    def _run(self, reason: str, link: str | None) -> None:
        tracer = get_tracer()
        try:
            with tracer.span("serve.compact", reason=reason, link=link):
                result = self._compact_once(reason)
        except Exception as exc:  # background thread: report, never die
            self.failures += 1
            self._m_total.inc(outcome="failed")
            result = {"swapped": False, "error": repr(exc)}
            runtime_event("serve_compact_failed", error=repr(exc))
        finally:
            with self.service._swap_lock:
                self.inflight = False
                self._log = []
                self._last_done = time.monotonic()
                self.last = result if isinstance(result, dict) else {}
            self._done.set()

    def _compact_once(self, reason: str) -> dict:
        from ..data.delta import half_chain_cached
        from ..utils.xla_flags import CompileCounter, warm_compile_cache

        svc = self.service
        tracer = get_tracer()
        t_all = time.perf_counter()
        for attempt in range(1, self.max_attempts + 1):
            with svc._swap_lock:
                token0 = svc.consistency_token
                fp0 = svc._fp
                hin0 = svc.hin
                self._log = []
            t_build = time.perf_counter()
            result = None
            abandon = None
            # ONE compile ledger over the whole attempt — build AND
            # swap: a capacity or nnz pow-2 step compiles here, once,
            # attributed to compaction; a steady-state re-encode at
            # unchanged buckets compiles NOTHING (the firehose smoke's
            # forced probe gates exactly that)
            with CompileCounter() as cc:
                with tracer.child_span("compact.build", attempt=attempt):
                    hin_c = compact_hin(hin0, headroom=self.headroom)
                    # the compacted encoding IS the same logical graph:
                    # its content fingerprint is the chain the live
                    # service already carries — seeding it keeps every
                    # replica's fingerprint chain identical no matter
                    # when each one compacts (and skips an O(nnz)
                    # re-hash)
                    object.__setattr__(hin_c, "_fingerprint_cache", fp0)
                    backend = svc._backend_factory(hin_c)
                    if svc.config.warm:
                        warm_compile_cache(
                            backend, svc._bucket_ladder,
                            k=svc.config.k_default, variant=svc.variant,
                        )
                    # pre-fold the half chain OUTSIDE the lock so
                    # replayed deltas under the lock are O(Δ), never
                    # O(nnz)
                    half_chain_cached(hin_c, svc.metapath)
                build_s = time.perf_counter() - t_build
                self._m_build.observe(build_s)
                # the swap itself goes through the SERVICE doorway
                # (the only sanctioned entry — analyzer rule CP001):
                # token check, mid-build delta replay, drain, hot-swap
                applied = svc._apply_compaction(backend, hin_c, token0)
                abandon = applied.get("abandoned")
                if abandon is None:
                    pause_s = applied["pause_s"]
                    self._m_pause.observe(pause_s)
                    self.compactions += 1
                    self._m_total.inc(outcome="swapped")
                    frac = self._headroom_frac()
                    if frac is not None:
                        self._m_headroom.set(frac)
                    result = {
                        "swapped": True,
                        "reason": reason,
                        "attempts": attempt,
                        "replayed_deltas": applied["replayed_deltas"],
                        "build_ms": round(build_s * 1e3, 3),
                        "pause_ms": round(pause_s * 1e3, 3),
                        "total_ms": round(
                            (time.perf_counter() - t_all) * 1e3, 3
                        ),
                        "capacity": applied["capacity"],
                        "headroom_frac": frac,
                        "token": applied["token"],
                    }
            if cc.count:
                self._m_compiles.inc(cc.count)
            if abandon is not None:
                self.abandoned += 1
                self._m_total.inc(outcome="abandoned")
                runtime_event(
                    "serve_compact_abandoned", attempt=attempt,
                    reason=abandon, echo=False,
                )
                continue
            result["compiles"] = cc.count
            runtime_event("serve_compact", **result)
            return result
        self._m_total.inc(outcome="failed")
        self.failures += 1
        result = {
            "swapped": False,
            "reason": reason,
            "attempts": self.max_attempts,
            "error": "every attempt was abandoned (token kept moving)",
        }
        runtime_event("serve_compact_failed", **result)
        return result

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        """The stats()/health() block — O(1), no locks beyond GIL-safe
        counter reads (values are monotone counters; an off-by-one read
        under a racing swap is harmless)."""
        return {
            "auto": bool(self.service.config.compact_auto),
            "inflight": self.inflight,
            "chain": self._chain,
            "chain_len": self.chain_len,
            "headroom_frac_trigger": self.headroom_frac,
            "fresh_headroom": self.headroom,
            "compactions": self.compactions,
            "abandoned": self.abandoned,
            "failures": self.failures,
            "last": dict(self.last),
        }
