"""The PathSim driver: single-source and all-pairs runs over any backend.

Reference parity (components C4 + C5, ``DPathSim_APVPA.py:9-68``): the
driver walks targets in node file order (the reference's dict insertion
order), emits the exact reference log grammar, and stores scores in an
id-keyed dict — but where the reference issues ``2N-1`` distributed joins,
all counts here come from at most two device computations (row sums +
source row), so "per-stage time" collapses to formatting.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from . import resilience
from .backends.base import PathSimBackend
from .obs.trace import get_tracer
from .utils.logging import RunLogger


def _format_count(x: float) -> int:
    """Counts are exact integers carried in floats; render like the
    reference's ``int(total_path)``."""
    return int(round(float(x)))


@dataclasses.dataclass
class SingleSourceResult:
    source_id: str
    source_label: str
    scores: dict[str, float]  # target node id → score, target order preserved
    global_walks: dict[str, int]
    pairwise_walks: dict[str, int]
    elapsed_s: float


class PathSimDriver:
    """Runs PathSim over a prepared backend.

    ``node_type`` is the metapath's endpoint type (author for APVPA).
    """

    def __init__(self, backend: PathSimBackend, variant: str = "rowsum"):
        self.backend = backend
        self.variant = variant
        self.hin = backend.hin
        self.node_type = backend.metapath.source_type
        self.index = self.hin.indices[self.node_type]

    def run_single_source(
        self,
        source: str,
        by_label: bool = True,
        logger: RunLogger | None = None,
    ) -> SingleSourceResult:
        """The reference's ``run()``: one source vs all other nodes of the
        endpoint type, with per-stage reference-grammar logging."""
        # Root span for the whole run: the StageTimer stages inside
        # nest under it, so a --trace-out dump shows one tree per query.
        with get_tracer().span(
            "driver.run_single_source", source=str(source)
        ):
            return self._run_single_source(source, by_label, logger)

    def _run_single_source(
        self,
        source: str,
        by_label: bool,
        logger: RunLogger | None,
    ) -> SingleSourceResult:
        logger = logger or RunLogger(output_path=None, echo=False)
        from .utils.profiling import StageTimer

        timer = StageTimer(logger)
        t0 = time.perf_counter()
        # Reference parity: the reference starts its overall clock when
        # the run begins (DPathSim_APVPA.py:26), not when the log file is
        # opened — a logger constructed before bootstrap must not fold
        # load/encode time into "***Overall done in:".
        logger.overall_start = t0

        source_index = self.hin.resolve_source(
            self.node_type,
            label=source if by_label else None,
            node_id=None if by_label else source,
        )

        # Where the time actually goes (the reference's per-stage clock
        # measures its joins; here the compute collapses to two device
        # dispatches + host formatting, so the split is the useful signal).
        # Both device computations sit behind the device_execute seam:
        # a transient dispatch failure (wedged tunnel, preempted device)
        # is retried rather than killing the run.
        with timer.stage("device_denominators"):
            d = resilience.resilient_call(
                "device_execute",
                lambda: self.backend._denominators(self.variant),
            )
        with timer.stage("device_pairwise_row"):
            row = resilience.resilient_call(
                "device_execute",
                lambda: self.backend.pairwise_row(source_index),
            )
        source_label = self.index.labels[source_index]
        source_id = self.index.ids[source_index]

        logger.source_global_walk(_format_count(d[source_index]))
        logger.metric(
            event="source_global_walk",
            source=source_id,
            count=_format_count(d[source_index]),
        )

        scores: dict[str, float] = {}
        global_walks: dict[str, int] = {}
        pairwise_walks: dict[str, int] = {}
        n = self.index.size
        d_src = float(d[source_index])
        with timer.stage("emit_log"):
            for t in range(n):
                if t == source_index:
                    continue
                stage_t0 = time.perf_counter()
                target_id = self.index.ids[t]
                pw = _format_count(row[t])
                gw = _format_count(d[t])
                denom = d_src + float(d[t])
                score = 2.0 * float(row[t]) / denom if denom > 0 else 0.0

                logger.pairwise_walk(target_id, pw)
                logger.target_global_walk(gw)
                logger.sim_score(source_label, self.index.labels[t], score)
                logger.stage_done(time.perf_counter() - stage_t0)

                scores[target_id] = score
                global_walks[target_id] = gw
                pairwise_walks[target_id] = pw

        logger.overall_done()
        return SingleSourceResult(
            source_id=source_id,
            source_label=source_label,
            scores=scores,
            global_walks=global_walks,
            pairwise_walks=pairwise_walks,
            elapsed_s=time.perf_counter() - t0,
        )

    def run_all_pairs(self) -> np.ndarray:
        """All-pairs score matrix — the capability the reference
        extrapolates to ~24 h of joins (SURVEY.md §6)."""
        with get_tracer().span("driver.run_all_pairs", n=self.index.size):
            return resilience.resilient_call(
                "device_execute",
                lambda: self.backend.all_pairs_scores(variant=self.variant),
            )

    def rank_all(self, k: int = 10, checkpoint_dir: str | None = None):
        """Per-source top-k ranking for EVERY node: (values [N, k] f64,
        indices [N, k] int64), self-pairs excluded.

        This is the batched generalization of the reference's whole
        program (one source against all targets, ``DPathSim_APVPA.py:
        28-68``) to all sources at once. Dispatch, best first:
        streaming tiled top-k (jax-sparse; supports checkpoint/resume,
        never materializes N×N), fused on-device top-k (jax dense,
        pallas on TPU), dense score matrix + argsort (any backend).
        """
        with get_tracer().span("driver.rank_all", k=k):
            return self._rank_all(k, checkpoint_dir)

    def _rank_all(self, k: int, checkpoint_dir: str | None):
        b = self.backend
        if hasattr(b, "topk_scores"):
            vals, idxs = b.topk_scores(
                k=k, variant=self.variant, checkpoint_dir=checkpoint_dir
            )
            return np.asarray(vals, dtype=np.float64), np.asarray(idxs)
        if checkpoint_dir is not None:
            raise ValueError(
                "checkpointed ranking requires a streaming backend "
                "(jax-sparse or jax-sharded)"
            )
        if hasattr(b, "topk") and b.metapath.is_symmetric:
            vals, idxs = resilience.resilient_call(
                "device_execute",
                lambda: b.topk(k=k, mask_self=True, variant=self.variant),
            )
            return (
                np.asarray(vals, dtype=np.float64),
                np.asarray(idxs, dtype=np.int64),
            )
        scores = np.array(
            resilience.resilient_call(
                "device_execute",
                lambda: b.all_pairs_scores(variant=self.variant),
            ),
            dtype=np.float64,
        )
        np.fill_diagonal(scores, -np.inf)
        idxs = np.argsort(-scores, axis=1, kind="stable")[:, :k]
        vals = np.take_along_axis(scores, idxs, axis=1)
        return vals, idxs.astype(np.int64)

    def write_ranking(self, path: str, vals: np.ndarray, idxs: np.ndarray):
        """TSV dump of a rank_all result: source_id, rank, target_id,
        score — the machine-readable analog of the reference's log."""
        with open(path, "w", encoding="utf-8") as f:
            f.write("source_id\trank\ttarget_id\tscore\n")
            for s in range(vals.shape[0]):
                for r in range(vals.shape[1]):
                    if not np.isfinite(vals[s, r]):
                        continue  # k exceeded the real candidate count
                    f.write(
                        f"{self.index.ids[s]}\t{r + 1}\t"
                        f"{self.index.ids[int(idxs[s, r])]}\t"
                        f"{vals[s, r]:.17g}\n"
                    )

    def top_k(self, source: str, k: int = 10, by_label: bool = True):
        """Ranked similar nodes — similarity *search*, the purpose PathSim
        serves in Sun et al. Routed through the backend's ``topk_row``
        primitive (the same code the serving layer's coalesced batches
        dispatch to), so a CLI query and a served query can never
        disagree on scores or tie order."""
        res_index = (
            self.hin.find_index_by_label(self.node_type, source)
            if by_label
            else self.index.index_of.get(source)
        )
        if res_index is None:
            raise KeyError(f"unknown {self.node_type} {source!r}")
        vals, idxs = self.backend.topk_row(res_index, k=k, variant=self.variant)
        return [
            (self.index.ids[int(i)], self.index.labels[int(i)], float(v))
            for v, i in zip(vals, idxs)
            if np.isfinite(v)
        ]
