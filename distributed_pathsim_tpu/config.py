"""Run configuration.

The reference hard-codes everything — dataset path, source author, output
path, engine package pin (``DPathSim_APVPA.py:141-176``). This is the real
config/flag system BASELINE.json asks for: dataset, backend, metapath,
variant, sharding, dtype, output — constructible from the CLI or
programmatically.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class RunConfig:
    dataset: str = "/root/reference/dblp/dblp_small.gexf"
    backend: str = "jax"  # see backends.available_backends()
    metapath: str = "APVPA"
    variant: str = "rowsum"  # reference semantics; "diagonal" = Sun et al.
    source: str | None = None  # node label (like the reference) …
    source_id: str | None = None  # … or node id
    output: str | None = None  # reference-grammar log path
    metrics: str | None = None  # JSONL metrics path
    all_pairs: bool = False
    top_k: int = 0
    n_devices: int | None = None  # sharded backends: devices to use
    dtype: str = "float32"
    loader: str = "auto"  # GEXF loader: auto | python | native
    tile_rows: int | None = None  # jax-sparse: rows per streaming tile
    approx: bool = False  # jax-sparse: waive the exact-count guard
    # Resident sparse-factor layout (ops/packed.py, DESIGN.md §29):
    # None resolves through the tuning registry (documented default:
    # "coo", the uncompressed layout); "blocked"/"bitpacked" hold the
    # half-chain factor compressed — bit-identical results, smaller
    # resident graph, higher max-N at a fixed memory budget.
    factor_format: str | None = None
    # Index-space capacity reserve (data/delta.py): 0.25 pads every type
    # by 25% so node appends up to the reserve never change array shapes
    # (the recompile-free delta-serving contract). 0 = no reserve.
    headroom: float = 0.0
    echo: bool = True
    # Resilience knobs (see resilience/): None = PATHSIM_MAX_RETRIES env
    # default (3 attempts total); degrade=False makes backend-init
    # failures fatal instead of stepping down the chain.
    max_retries: int | None = None
    degrade: bool = True
    # Measured-dispatch knobs (tuning/): path of a ``dpathsim tune``
    # table (None = honor PATHSIM_TUNING_TABLE, else built-in
    # heuristics); tuning=False pins every knob to its heuristic.
    tuning_table: str | None = None
    tuning: bool = True
