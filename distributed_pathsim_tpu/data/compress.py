"""Degree-sorted (hub-first) index permutations for compressed layouts.

The COO factor chain's resident footprint is the fleet's scale ceiling
(~14 GB host RSS at 4.19M authors, SCALE_4M_r03.json) — and both
compression papers this lands from (arXiv 2409.02208, arXiv 1708.07271)
make the same observation: a *reordered* sparse matrix compresses far
better than the raw one, because hub-first orderings concentrate the
used index range near zero (narrower integer dtypes, smaller
delta-encoded column gaps) and make adjacent rows structurally similar
(denser blocks).

This module computes those orderings and owns their algebra:

- :func:`degree_order` — the hub-first permutation of one index space
  (stable: equal degrees keep ascending original order, so the
  permutation is deterministic for a given degree vector).
- :class:`PermutationPair` — a permutation and its inverse as one
  value, with ``apply``/``invert`` for index arrays and an
  identity-``extend`` for capacity-padded/append-grown spaces: slots
  appended past the original size map to themselves, so a delta node
  append never re-permutes (and never re-encodes) existing data.
- :func:`hin_degree_permutations` — one pair per node type of an
  encoded HIN, from the summed degree of every adjacency block
  touching that type.
- :func:`factor_permutations` — row/col pairs for a single folded
  factor, from its own marginals (what ``ops/packed.py`` consumes).

**The permutation contract** (DESIGN.md §29): permutations are an
*encoding-internal* coordinate change. Every host-visible boundary —
labels, top-k tie order ``(desc score, asc global col)``, the JSONL
wire, checkpoint digests — speaks ORIGINAL ids; whoever applies a
permutation owns inverting it before anything escapes (ops/packed.py
inverts at every unpack/slice accessor, which is why every downstream
consumer is bit-identical by construction). Nothing in this module
mutates an :class:`~.encode.EncodedHIN`.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def degree_order(deg: np.ndarray) -> np.ndarray:
    """Hub-first permutation of an index space: ``perm[new] = old``,
    sorted by (descending degree, ascending original index). The
    secondary key makes the order total and deterministic — two
    packings of the same factor are byte-identical."""
    deg = np.asarray(deg)
    # lexsort's last key is primary; negate for hub-first, index
    # ascending breaks ties deterministically.
    return np.lexsort(
        (np.arange(deg.shape[0]), -deg.astype(np.int64))
    ).astype(np.int64)


@dataclasses.dataclass(frozen=True)
class PermutationPair:
    """A permutation and its inverse over one index space of size
    ``n``: ``perm[new] = old`` and ``inv[old] = new`` (so
    ``inv[perm] == arange(n)``). ``apply`` maps original ids to
    permuted ids; ``invert`` maps back — the two host-boundary
    directions, named so call sites read as what they do."""

    perm: np.ndarray
    inv: np.ndarray

    @property
    def n(self) -> int:
        return int(self.perm.shape[0])

    @property
    def is_identity(self) -> bool:
        return bool((self.perm == np.arange(self.n)).all())

    def apply(self, idx: np.ndarray) -> np.ndarray:
        """Original ids → permuted ids."""
        return self.inv[np.asarray(idx, dtype=np.int64)]

    def invert(self, idx: np.ndarray) -> np.ndarray:
        """Permuted ids → original ids (the host-boundary direction)."""
        return self.perm[np.asarray(idx, dtype=np.int64)]

    def extend(self, n_new: int) -> "PermutationPair":
        """Identity-extend to a grown index space: slots in
        ``[n, n_new)`` map to themselves. This is the append contract —
        a headroom-padded node append must never re-permute existing
        slots (existing packed chunks would all re-encode and the
        O(Δ) delta path would become O(nnz))."""
        if n_new < self.n:
            raise ValueError(
                f"cannot shrink a permutation ({self.n} -> {n_new})"
            )
        if n_new == self.n:
            return self
        tail = np.arange(self.n, n_new, dtype=np.int64)
        return PermutationPair(
            perm=np.concatenate([self.perm, tail]),
            inv=np.concatenate([self.inv, tail]),
        )

    @staticmethod
    def identity(n: int) -> "PermutationPair":
        ar = np.arange(int(n), dtype=np.int64)
        return PermutationPair(perm=ar, inv=ar)

    @staticmethod
    def from_perm(perm: np.ndarray) -> "PermutationPair":
        perm = np.asarray(perm, dtype=np.int64)
        inv = np.empty_like(perm)
        inv[perm] = np.arange(perm.shape[0], dtype=np.int64)
        return PermutationPair(perm=perm, inv=inv)


def hin_degree_permutations(hin) -> dict[str, PermutationPair]:
    """One hub-first :class:`PermutationPair` per node type, from the
    summed degree of every adjacency block incident to that type
    (rows of blocks where the type is source + cols where it is
    destination). Sized to each type's PADDED index space, so
    capacity-reserved slots (degree 0 by construction) sort last and
    an append inside the reserve only ever touches identity-mapped
    tail slots."""
    out: dict[str, PermutationPair] = {}
    for node_type, idx in hin.indices.items():
        deg = np.zeros(idx.padded_size, dtype=np.int64)
        for b in hin.blocks.values():
            if b.src_type == node_type and b.rows.shape[0]:
                np.add.at(deg, b.rows.astype(np.int64), 1)
            if b.dst_type == node_type and b.cols.shape[0]:
                np.add.at(deg, b.cols.astype(np.int64), 1)
        out[node_type] = PermutationPair.from_perm(degree_order(deg))
    return out


def factor_permutations(
    rows: np.ndarray, cols: np.ndarray, shape: tuple[int, int]
) -> tuple[PermutationPair, PermutationPair]:
    """(row pair, col pair) for one factor from its own marginals.
    The column permutation is the load-bearing one for the bit-packed
    layout: hub columns land at small permuted ids, so within-row
    delta gaps (and the max used column id, which picks the narrow
    dtype) shrink together. ``ops/packed.py``'s hot path computes
    exactly that column half inline (skipping the row sort it does
    not need — its row layout is chunk-local, derived from the count
    tables); this full pair is the audit/experimentation surface for
    layouts that DO reorder rows globally."""
    row_deg = np.bincount(
        np.asarray(rows, dtype=np.int64), minlength=int(shape[0])
    )
    col_deg = np.bincount(
        np.asarray(cols, dtype=np.int64), minlength=int(shape[1])
    )
    return (
        PermutationPair.from_perm(degree_order(row_deg)),
        PermutationPair.from_perm(degree_order(col_deg)),
    )
