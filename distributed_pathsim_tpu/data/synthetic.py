"""Synthetic DBLP-like HIN generator (BASELINE.json config 5 feedstock).

Generates author/paper/venue(/topic) graphs at arbitrary scale with
power-law-ish venue popularity and small per-paper author lists, directly
as an :class:`EncodedHIN` (no string round-trip — at 1M authors / 5M
papers the id strings would dominate memory). A small-scale GEXF writer is
also provided so loader tests have realistic files.
"""

from __future__ import annotations

import numpy as np

from .encode import AdjacencyBlock, EncodedHIN, TypeIndex
from .schema import HINSchema

DBLP_SCHEMA = HINSchema(
    node_types=("author", "paper", "venue", "topic"),
    relations={
        "author_of": ("author", "paper"),
        "submit_at": ("paper", "venue"),
        "has_topic": ("paper", "topic"),
    },
)


def synthetic_hin(
    n_authors: int,
    n_papers: int,
    n_venues: int,
    n_topics: int = 0,
    authors_per_paper: float = 1.3,
    topics_per_paper: float = 1.0,
    seed: int = 0,
    materialize_ids: bool = False,
) -> EncodedHIN:
    """Build a synthetic DBLP-shaped HIN.

    Structure mirrors the real data's invariants: every paper has exactly
    one venue (``submit_at`` nnz == n_papers, as in dblp_small), papers have
    ~``authors_per_paper`` authors, venues have Zipf-like popularity so the
    commuting-matrix row sums spread over orders of magnitude like the
    reference log's global walks (876 … 11631).
    """
    rng = np.random.default_rng(seed)

    # author_of: each paper gets 1 + Poisson(extra) distinct authors, biased
    # to a Zipf head so a few authors are prolific (Jiawei-Han-like rows).
    extra = rng.poisson(max(authors_per_paper - 1.0, 0.0), size=n_papers)
    counts = 1 + extra
    total = int(counts.sum())
    zipf_w = 1.0 / np.arange(1, n_authors + 1, dtype=np.float64)
    zipf_w /= zipf_w.sum()
    authors = rng.choice(n_authors, size=total, p=zipf_w)
    papers = np.repeat(np.arange(n_papers, dtype=np.int64), counts)
    ap = np.unique(np.stack([authors, papers], axis=1), axis=0)

    # submit_at: exactly one venue per paper, Zipf venue popularity.
    venue_w = 1.0 / np.arange(1, n_venues + 1, dtype=np.float64)
    venue_w /= venue_w.sum()
    venues = rng.choice(n_venues, size=n_papers, p=venue_w)
    pv_rows = np.arange(n_papers, dtype=np.int64)

    relations = {
        "author_of": ("author", "paper"),
        "submit_at": ("paper", "venue"),
    }
    blocks = {
        "author_of": AdjacencyBlock(
            relationship="author_of",
            src_type="author",
            dst_type="paper",
            rows=ap[:, 0].astype(np.int32),
            cols=ap[:, 1].astype(np.int32),
            shape=(n_authors, n_papers),
        ),
        "submit_at": AdjacencyBlock(
            relationship="submit_at",
            src_type="paper",
            dst_type="venue",
            rows=pv_rows.astype(np.int32),
            cols=venues.astype(np.int32),
            shape=(n_papers, n_venues),
        ),
    }

    sizes = {"author": n_authors, "paper": n_papers, "venue": n_venues}
    node_types = ["author", "paper", "venue"]
    if n_topics > 0:
        n_pt = int(round(topics_per_paper * n_papers))
        pt_papers = rng.integers(0, n_papers, size=n_pt)
        pt_topics = rng.integers(0, n_topics, size=n_pt)
        pt = np.unique(np.stack([pt_papers, pt_topics], axis=1), axis=0)
        relations["has_topic"] = ("paper", "topic")
        blocks["has_topic"] = AdjacencyBlock(
            relationship="has_topic",
            src_type="paper",
            dst_type="topic",
            rows=pt[:, 0].astype(np.int32),
            cols=pt[:, 1].astype(np.int32),
            shape=(n_papers, n_topics),
        )
        sizes["topic"] = n_topics
        node_types.append("topic")

    indices = {t: _range_index(t, sizes[t], materialize_ids) for t in node_types}
    schema = HINSchema(node_types=tuple(node_types), relations=relations)
    return EncodedHIN(
        schema=schema,
        indices=indices,
        blocks=blocks,
        name=f"synthetic_a{n_authors}_p{n_papers}_v{n_venues}",
    )


def _range_index(node_type: str, size: int, materialize: bool) -> TypeIndex:
    if materialize:
        ids = tuple(f"{node_type}_{i}" for i in range(size))
        return TypeIndex(
            node_type=node_type,
            ids=ids,
            labels=ids,
            index_of={s: i for i, s in enumerate(ids)},
        )
    # At 1M+ nodes, per-node Python strings cost more than the graph itself;
    # the index spaces are pure ranges — keep them implicit but sized.
    return TypeIndex(
        node_type=node_type, ids=(), labels=(), index_of={}, size_override=size
    )


def write_gexf(hin: EncodedHIN, path: str) -> None:
    """Write an EncodedHIN as GEXF 1.2 in the reference's dialect
    (NetworkX-2.0-style: node_type as node attvalue 0, relationship as
    edge attvalue titled 'label'). Streams to the file — dblp_large-scale
    graphs (millions of nodes, ~1 GB of XML) must not be built as one
    in-memory string."""
    from xml.sax.saxutils import quoteattr

    with open(path, "w", encoding="utf-8") as f:
        w = f.write
        w("<?xml version='1.0' encoding='utf-8'?>\n")
        w('<gexf version="1.2" xmlns="http://www.gexf.net/1.2draft">\n')
        w(
            f'  <graph defaultedgetype="directed" mode="static" '
            f"name={quoteattr(hin.name)}>\n"
        )
        w('    <attributes class="edge" mode="static">\n')
        w('      <attribute id="1" title="label" type="string" />\n')
        w("    </attributes>\n")
        w('    <attributes class="node" mode="static">\n')
        w('      <attribute id="0" title="node_type" type="string" />\n')
        w("    </attributes>\n")
        w("    <nodes>\n")
        for t in hin.schema.node_types:
            idx = hin.indices[t]
            n = idx.size
            if n and not idx.ids:
                raise ValueError(
                    "write_gexf needs materialized ids; build the HIN with "
                    "materialize_ids=True"
                )
            tq = quoteattr(t)
            for i in range(n):
                w(
                    f"      <node id={quoteattr(idx.ids[i])} "
                    f"label={quoteattr(idx.labels[i])}>"
                    f'<attvalues><attvalue for="0" value={tq} />'
                    f"</attvalues></node>\n"
                )
        w("    </nodes>\n")
        w("    <edges>\n")
        k = 0
        for rel, b in hin.blocks.items():
            src_ids = hin.indices[b.src_type].ids
            dst_ids = hin.indices[b.dst_type].ids
            relq = quoteattr(rel)
            for r, c in zip(b.rows.tolist(), b.cols.tolist()):
                w(
                    f'      <edge id="{k}" source={quoteattr(src_ids[r])} '
                    f"target={quoteattr(dst_ids[c])}>"
                    f'<attvalues><attvalue for="1" value={relq} />'
                    f"</attvalues></edge>\n"
                )
                k += 1
        w("    </edges>\n")
        w("  </graph>\n")
        w("</gexf>\n")
