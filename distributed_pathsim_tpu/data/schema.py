"""Typed heterogeneous-information-network (HIN) data model.

This is the framework's plugin boundary, kept content-compatible with the
reference's ingestion layer (``read_dblp_nx_file``, reference
``DPathSim_APVPA.py:114-129``): a graph is a list of
``(id, label, node_type)`` vertices and ``(src, dst, relationship)`` edges.
Unlike the reference — which ships these as Python tuple lists into Spark
DataFrames — we keep string ids strictly on the host and hand only dense
integer indices to the device (SURVEY.md §7 "String ids").
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Mapping, Sequence


@dataclasses.dataclass(frozen=True)
class Vertex:
    id: str
    label: str
    node_type: str


@dataclasses.dataclass(frozen=True)
class Edge:
    src: str
    dst: str
    relationship: str


@dataclasses.dataclass
class HINGraph:
    """Host-side typed graph: the content of a parsed GEXF file.

    ``vertices`` and ``edges`` preserve file order — the reference's target
    iteration order (and hence its log line order) is node insertion order,
    so order is semantically meaningful (SURVEY.md §4).
    """

    vertices: list[Vertex]
    edges: list[Edge]
    name: str = ""

    # ---- reference-compatible views -------------------------------------

    def vertex_tuples(self) -> list[tuple[str, str, str]]:
        """``(id, label, node_type)`` tuples, exactly what the reference's
        ``read_dblp_nx_file`` returns for vertices."""
        return [(v.id, v.label, v.node_type) for v in self.vertices]

    def edge_tuples(self) -> list[tuple[str, str, str]]:
        """``(src, dst, relationship)`` tuples, the reference's edge list."""
        return [(e.src, e.dst, e.relationship) for e in self.edges]

    # ---- lookups ---------------------------------------------------------

    def find_node_id_by_label(self, label: str) -> str | None:
        """Name→id resolution; linear scan like the reference
        (``DPathSim_APVPA.py:132-137``), returning ``None`` on a miss."""
        for v in self.vertices:
            if v.label == label:
                return v.id
        return None

    def node_types(self) -> list[str]:
        """Distinct node types in first-appearance order."""
        seen: dict[str, None] = {}
        for v in self.vertices:
            seen.setdefault(v.node_type, None)
        return list(seen)

    def relationships(self) -> list[str]:
        """Distinct edge relationships in first-appearance order."""
        seen: dict[str, None] = {}
        for e in self.edges:
            seen.setdefault(e.relationship, None)
        return list(seen)

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for v in self.vertices:
            out[v.node_type] = out.get(v.node_type, 0) + 1
        return out

    @staticmethod
    def from_tuples(
        vertices: Iterable[tuple[str, str, str]],
        edges: Iterable[tuple[str, str, str]],
        name: str = "",
    ) -> "HINGraph":
        return HINGraph(
            vertices=[Vertex(*t) for t in vertices],
            edges=[Edge(*t) for t in edges],
            name=name,
        )


@dataclasses.dataclass(frozen=True)
class HINSchema:
    """The type-level view of a HIN: node types and typed edge relations.

    ``relations`` maps a relationship name to its ``(src_type, dst_type)``
    signature — e.g. DBLP has ``author_of: (author, paper)`` and
    ``submit_at: (paper, venue)``.
    """

    node_types: tuple[str, ...]
    relations: Mapping[str, tuple[str, str]]

    def validate_metapath(self, node_seq: Sequence[str]) -> None:
        for t in node_seq:
            if t not in self.node_types:
                raise ValueError(
                    f"metapath node type {t!r} not in schema {self.node_types}"
                )


def infer_schema(graph: HINGraph) -> HINSchema:
    """Infer the typed schema from data.

    Every relationship must have a unique ``(src_type, dst_type)`` signature;
    mixed-signature relationships are rejected (the DBLP data is clean in
    this sense, and typed adjacency blocks require it).
    """
    type_of = {v.id: v.node_type for v in graph.vertices}
    relations: dict[str, tuple[str, str]] = {}
    for e in graph.edges:
        try:
            sig = (type_of[e.src], type_of[e.dst])
        except KeyError as exc:
            raise ValueError(f"edge endpoint {exc} has no vertex entry") from exc
        prev = relations.get(e.relationship)
        if prev is None:
            relations[e.relationship] = sig
        elif prev != sig:
            raise ValueError(
                f"relationship {e.relationship!r} has mixed signatures "
                f"{prev} vs {sig}"
            )
    return HINSchema(node_types=tuple(graph.node_types()), relations=relations)
