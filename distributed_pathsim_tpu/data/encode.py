"""Typed-adjacency encoding: host strings → device-ready dense indices.

The reference ships raw string tuple-lists into Spark DataFrames and lets
Catalyst join on strings (``DPathSim_APVPA.py:160-163``). TPU-first design
inverts this: every node type gets its own contiguous dense index space on
the host, and each relationship becomes a COO block of ``(row, col)`` int32
index pairs between two type spaces. Everything downstream (dense, sharded,
sparse, pallas) consumes these blocks; strings never reach the device.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .schema import HINGraph, HINSchema, infer_schema


@dataclasses.dataclass(frozen=True)
class TypeIndex:
    """Bidirectional id↔dense-index map for one node type.

    Index order is vertex file order, which is the reference's iteration
    (and log) order. ``size_override`` supports huge synthetic graphs
    whose ids are implicit ranges (no per-node strings); such indices
    still report the correct size but cannot resolve string ids.

    ``capacity`` (when set) reserves index slots beyond ``size`` — the
    delta-ingestion headroom (data/delta.py): adjacency blocks are built
    at capacity shape so node appends up to the reserve never change any
    array shape (and therefore never invalidate a compiled program).
    Slots in ``[size, capacity)`` carry no edges and are invisible to
    every logical-size consumer.
    """

    node_type: str
    ids: tuple[str, ...]
    labels: tuple[str, ...]
    index_of: dict[str, int]
    size_override: int | None = None
    capacity: int | None = None

    @property
    def size(self) -> int:
        """Logical node count (never the padded capacity)."""
        return self.size_override if self.size_override is not None else len(self.ids)

    @property
    def padded_size(self) -> int:
        """Array-shape size: capacity when headroom is reserved, else
        the logical size."""
        return self.capacity if self.capacity is not None else self.size

    @property
    def headroom(self) -> int:
        return self.padded_size - self.size

    def label_of_index(self, i: int) -> str:
        return self.labels[i]

    def index_of_label(self, label: str) -> int | None:
        """Label → first dense index, O(1) via a lazily built map.

        Labels are not unique (author names collide); ``labels.index``
        semantics — first occurrence wins — are preserved by the
        setdefault construction. The map is built once per TypeIndex
        (frozen dataclass: cached via ``object.__setattr__``) instead of
        paying an O(N) list scan on every serving-path resolve."""
        cache = self.__dict__.get("_label_index")
        if cache is None:
            cache = {}
            for i, lab in enumerate(self.labels):
                cache.setdefault(lab, i)
            object.__setattr__(self, "_label_index", cache)
        return cache.get(label)


@dataclasses.dataclass(frozen=True)
class AdjacencyBlock:
    """COO adjacency block for one relationship: rows in ``src_type``'s
    index space, cols in ``dst_type``'s. Entries are unique (simple graph —
    see gexf.py dedup) and unweighted (weight 1, like the reference data).
    """

    relationship: str
    src_type: str
    dst_type: str
    rows: np.ndarray  # int32 [nnz]
    cols: np.ndarray  # int32 [nnz]
    shape: tuple[int, int]

    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    def to_dense(self, dtype=np.float64) -> np.ndarray:
        out = np.zeros(self.shape, dtype=dtype)
        out[self.rows, self.cols] = 1
        return out

    def transpose(self) -> "AdjacencyBlock":
        return AdjacencyBlock(
            relationship=self.relationship + "^T",
            src_type=self.dst_type,
            dst_type=self.src_type,
            rows=self.cols,
            cols=self.rows,
            shape=(self.shape[1], self.shape[0]),
        )


@dataclasses.dataclass(frozen=True)
class EncodedHIN:
    """A fully encoded HIN: schema + per-type index spaces + COO blocks."""

    schema: HINSchema
    indices: dict[str, TypeIndex]
    blocks: dict[str, AdjacencyBlock]  # keyed by relationship name
    name: str = ""

    def type_size(self, node_type: str) -> int:
        return self.indices[node_type].size

    def block(self, relationship: str) -> AdjacencyBlock:
        return self.blocks[relationship]

    def find_index_by_label(self, node_type: str, label: str) -> int | None:
        """Label→dense index within a type (the reference's name→id lookup,
        ``DPathSim_APVPA.py:132-137``, composed with index encoding).
        O(1): this sits on the per-request serving path (resolve_source)."""
        return self.indices[node_type].index_of_label(label)

    def resolve_source(
        self,
        node_type: str,
        label: str | None = None,
        node_id: str | None = None,
    ) -> int:
        """Label-or-id → dense index, with the canonical not-found
        messages (shared by the driver and both CLIs — the reference
        crashes opaquely on an unknown source, SURVEY.md §3.1)."""
        if label is not None:
            idx = self.find_index_by_label(node_type, label)
            if idx is None:
                raise KeyError(f"no {node_type} labeled {label!r}")
            return idx
        idx = self.indices[node_type].index_of.get(node_id)
        if idx is None:
            raise KeyError(f"no {node_type} with id {node_id!r}")
        return idx


def encode_hin(graph: HINGraph, schema: HINSchema | None = None) -> EncodedHIN:
    """Encode a host graph into typed index spaces and COO blocks.

    Edges whose endpoints are missing from the vertex table are rejected;
    edges whose relationship has no schema entry are rejected. Isolated
    nodes (e.g. dblp_small's 10 ``topic`` nodes) still get index entries —
    they simply appear in no block.
    """
    if schema is None:
        schema = infer_schema(graph)

    per_type: dict[str, list[tuple[str, str]]] = {t: [] for t in schema.node_types}
    for v in graph.vertices:
        per_type.setdefault(v.node_type, []).append((v.id, v.label))

    indices: dict[str, TypeIndex] = {}
    for node_type, pairs in per_type.items():
        ids = tuple(p[0] for p in pairs)
        labels = tuple(p[1] for p in pairs)
        indices[node_type] = TypeIndex(
            node_type=node_type,
            ids=ids,
            labels=labels,
            index_of={i: k for k, i in enumerate(ids)},
        )

    per_rel: dict[str, tuple[list[int], list[int]]] = {
        r: ([], []) for r in schema.relations
    }
    for e in graph.edges:
        sig = schema.relations.get(e.relationship)
        if sig is None:
            raise ValueError(f"edge relationship {e.relationship!r} not in schema")
        src_type, dst_type = sig
        rows, cols = per_rel[e.relationship]
        rows.append(indices[src_type].index_of[e.src])
        cols.append(indices[dst_type].index_of[e.dst])

    blocks: dict[str, AdjacencyBlock] = {}
    for rel, (rows, cols) in per_rel.items():
        src_type, dst_type = schema.relations[rel]
        blocks[rel] = AdjacencyBlock(
            relationship=rel,
            src_type=src_type,
            dst_type=dst_type,
            rows=np.asarray(rows, dtype=np.int32),
            cols=np.asarray(cols, dtype=np.int32),
            shape=(indices[src_type].size, indices[dst_type].size),
        )

    return EncodedHIN(schema=schema, indices=indices, blocks=blocks, name=graph.name)
