"""GEXF ingestion (reference component C1, ``DPathSim_APVPA.py:114-129``).

The reference reads GEXF through ``networkx.read_gexf`` and flattens to
tuple lists. We parse the XML directly with a streaming ``iterparse`` —
no networkx dependency, no intermediate graph object, O(E) memory — and
optionally through the C++ fast parser in ``native/`` for large files.

Semantics matched to the reference pipeline:
- node attvalue titled ``node_type`` → vertex node_type
- edge attvalue titled ``label`` → edge *relationship* (the reference
  stores the relationship under the GEXF attribute titled "label",
  SURVEY.md §3.4)
- multi-edges are deduplicated (networkx yields a simple DiGraph, so
  ``distinct()`` in the reference is a no-op — we reproduce that by
  dedup at ingestion, SURVEY.md §3.3)
- file order of nodes/edges is preserved (drives target iteration order)
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

from .schema import Edge, HINGraph, Vertex


def _local(tag: str) -> str:
    """Strip any XML namespace from a tag."""
    return tag.rsplit("}", 1)[-1]


def read_gexf(path: str, use_native: bool | None = None) -> HINGraph:
    """Parse a GEXF file into a typed :class:`HINGraph`.

    ``use_native``: force (True) or forbid (False) the C++ parser;
    ``None`` auto-selects it when the shared library is available.
    """
    if use_native is not False:
        try:
            from ..native import gexf_native

            if gexf_native.available():
                return gexf_native.read_gexf(path)
            if use_native is True:
                raise RuntimeError("native GEXF parser requested but unavailable")
        except ImportError:
            if use_native is True:
                raise
    return _read_gexf_python(path)


def _read_gexf_python(path: str) -> HINGraph:
    # Two-level state machine over iterparse events: attribute declarations
    # give us attr-id → title maps per class; then nodes/edges stream out.
    node_attr_titles: dict[str, str] = {}
    edge_attr_titles: dict[str, str] = {}

    vertices: list[Vertex] = []
    # (src, dst) → position in `edges`; duplicate (src, dst) pairs keep their
    # first position but take the last relationship — exactly what
    # nx.read_gexf's DiGraph edge-attribute overwrite does in the reference.
    edge_pos: dict[tuple[str, str], int] = {}
    edges: list[Edge] = []
    graph_name = ""

    cur_attr_class: str | None = None

    for event, elem in ET.iterparse(path, events=("start", "end")):
        tag = _local(elem.tag)
        if event == "start":
            if tag == "attributes":
                cur_attr_class = elem.get("class")
            elif tag == "attribute" and cur_attr_class is not None:
                titles = (
                    node_attr_titles if cur_attr_class == "node" else edge_attr_titles
                )
                titles[elem.get("id", "")] = elem.get("title", "")
            elif tag == "graph":
                graph_name = elem.get("name", "") or ""
            continue

        # end events
        if tag == "attributes":
            cur_attr_class = None
        elif tag == "node":
            attrs = _attvalues(elem, node_attr_titles)
            vertices.append(
                Vertex(
                    id=elem.get("id", ""),
                    label=elem.get("label", elem.get("id", "")),
                    node_type=attrs.get("node_type", ""),
                )
            )
            elem.clear()
        elif tag == "edge":
            attrs = _attvalues(elem, edge_attr_titles)
            # GEXF edges may carry an explicit label attribute; the DBLP
            # data stores the relationship in the attvalue titled "label".
            rel = attrs.get("label", elem.get("label", ""))
            key = (elem.get("source", ""), elem.get("target", ""))
            pos = edge_pos.get(key)
            if pos is None:
                edge_pos[key] = len(edges)
                edges.append(Edge(src=key[0], dst=key[1], relationship=rel))
            else:
                edges[pos] = Edge(src=key[0], dst=key[1], relationship=rel)
            elem.clear()

    return HINGraph(vertices=vertices, edges=edges, name=graph_name)


def _attvalues(elem, titles: dict[str, str]) -> dict[str, str]:
    out: dict[str, str] = {}
    for child in elem.iter():
        if _local(child.tag) == "attvalue":
            attr_id = child.get("for", "")
            out[titles.get(attr_id, attr_id)] = child.get("value", "")
    return out
