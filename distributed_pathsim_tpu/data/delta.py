"""Incremental graph-delta ingestion: O(Δ) updates to an encoded HIN.

The reference recomputes its entire join chain per query; PR 2's serving
layer inherited the batch-world assumption one level up — any change to
the graph (one new paper, one new author) forced a full reparse,
re-encode, backend rebuild, per-bucket recompile, and a total cache
flush. This module is the other half of the serving story, the part
Atrapos (arXiv:2201.04058) identifies as decisive for real-time metapath
workloads: amortizing the commuting-matrix work across updates.

Three pieces:

- **Capacity headroom** (:func:`with_headroom`): every type's index
  space is padded to a reserved capacity and adjacency blocks are built
  at capacity shape. Node appends up to the reserve change *contents*,
  never *shapes* — so every compiled XLA program (shape-specialized by
  construction) survives growth. Padded slots carry no edges; backends
  trim every host-visible result to the logical size, so padding is
  semantically invisible (verified bit-for-bit by test).

- **Deltas** (:class:`DeltaBatch` / :func:`apply_delta`): a batch of
  edge adds/removes and node appends applied to an :class:`EncodedHIN`
  in O(Δ + touched-block nnz) array surgery — no string round-trip, no
  reparse. Exactness is preserved structurally: duplicate adds and
  phantom removes are rejected (the encoded graph stays simple, so
  integer path counts stay exact).

- **Plans** (:func:`plan_delta`): the serving-facing product — the new
  HIN plus the signed half-chain delta ΔC (product rule, ops/sparse),
  the patched factor, a sound superset of the score rows the delta
  affects (row-granular cache invalidation), a chained content
  fingerprint, and a fallback verdict (headroom exhausted / Δ over
  threshold / asymmetric metapath → the caller rebuilds instead of
  patching).
"""

from __future__ import annotations

import dataclasses
import hashlib
import math

import numpy as np

from .encode import AdjacencyBlock, EncodedHIN, TypeIndex

# Edge-pair keys: (row, col) packed into one int64. Index spaces are
# int32, so a 2^32 multiplier can never collide.
_KEY_SHIFT = np.int64(1) << np.int64(32)


def _edge_keys(rows: np.ndarray, cols: np.ndarray) -> np.ndarray:
    return rows.astype(np.int64) * _KEY_SHIFT + cols.astype(np.int64)


@dataclasses.dataclass(frozen=True)
class NodeAppend:
    """Append nodes to one type's index space (appends only — dense
    index spaces are append-only by design; node removal is edge
    removal plus an orphaned index slot, exactly like the reference's
    isolated topic nodes)."""

    node_type: str
    ids: tuple[str, ...] = ()
    labels: tuple[str, ...] = ()
    count: int = 0  # id-less appends for implicit-range index spaces

    @property
    def n(self) -> int:
        return len(self.ids) if self.ids else self.count


@dataclasses.dataclass(frozen=True)
class EdgeDelta:
    """Edge adds/removes for one relationship, in dense index space.
    ``add``/``remove`` are int64 [m, 2] arrays of (src, dst) pairs."""

    relationship: str
    add: np.ndarray
    remove: np.ndarray

    @property
    def n_changes(self) -> int:
        return int(self.add.shape[0] + self.remove.shape[0])


@dataclasses.dataclass(frozen=True)
class DeltaBatch:
    """One atomic batch of graph changes. Node appends are applied
    before edge changes, so added edges may reference appended nodes."""

    edges: tuple[EdgeDelta, ...] = ()
    nodes: tuple[NodeAppend, ...] = ()

    @property
    def n_edge_changes(self) -> int:
        return sum(e.n_changes for e in self.edges)

    @property
    def n_node_appends(self) -> int:
        return sum(a.n for a in self.nodes)

    def digest(self) -> str:
        """Content hash of the batch — the fingerprint-chaining token
        (a delta's identity, so two services applying equal deltas to
        equal graphs agree on the chained fingerprint)."""
        h = hashlib.sha256()
        for a in self.nodes:
            h.update(f"n:{a.node_type}:{a.count};".encode())
            # labels default to ids (mirroring apply_delta) — zipping
            # against an empty labels tuple would silently drop id
            # appends from the digest and collide distinct deltas
            for i, lab in zip(a.ids, a.labels or a.ids):
                h.update(f"{i}\0{lab}\0".encode())
        for e in self.edges:
            h.update(f"e:{e.relationship};".encode())
            h.update(np.ascontiguousarray(e.add, dtype=np.int64).tobytes())
            h.update(b";")
            h.update(np.ascontiguousarray(e.remove, dtype=np.int64).tobytes())
        return h.hexdigest()[:16]


def _as_pairs(pairs) -> np.ndarray:
    a = np.asarray(pairs, dtype=np.int64)
    if a.size == 0:
        return np.empty((0, 2), dtype=np.int64)
    if a.ndim != 2 or a.shape[1] != 2:
        raise ValueError(f"edge pairs must be [m, 2], got {a.shape}")
    return a


def edge_delta(relationship: str, add=(), remove=()) -> EdgeDelta:
    """Convenience constructor normalizing list-of-pairs input."""
    return EdgeDelta(
        relationship=relationship, add=_as_pairs(add), remove=_as_pairs(remove)
    )


# ---------------------------------------------------------------------------
# Headroom
# ---------------------------------------------------------------------------


def _padded_capacity(size: int, headroom: float, min_slots: int = 8) -> int:
    return size + max(min_slots, int(math.ceil(size * headroom)))


def with_headroom(
    hin: EncodedHIN, headroom: float = 0.25, min_slots: int = 8
) -> EncodedHIN:
    """Reserve append capacity: every type's padded size becomes
    ``size + max(min_slots, ceil(size·headroom))`` and every adjacency
    block is re-shaped to capacity. Contents are untouched — the padded
    slots have no edges, and every backend trims results to the logical
    size, so scores are bit-identical to the unpadded encoding."""
    indices = {
        t: dataclasses.replace(
            idx, capacity=_padded_capacity(idx.size, headroom, min_slots)
        )
        for t, idx in hin.indices.items()
    }
    return EncodedHIN(
        schema=hin.schema,
        indices=indices,
        blocks=_reshape_blocks(hin.blocks, hin.schema, indices),
        name=hin.name,
    )


def strip_headroom(hin: EncodedHIN) -> EncodedHIN:
    """Drop the capacity reserve — the result is exactly what a full
    re-encode of the same logical graph produces (the parity tests'
    comparator)."""
    indices = {
        t: dataclasses.replace(idx, capacity=None)
        for t, idx in hin.indices.items()
    }
    return EncodedHIN(
        schema=hin.schema,
        indices=indices,
        blocks=_reshape_blocks(hin.blocks, hin.schema, indices),
        name=hin.name,
    )


def _reshape_blocks(blocks, schema, indices) -> dict[str, AdjacencyBlock]:
    out = {}
    for rel, b in blocks.items():
        src, dst = schema.relations[rel]
        out[rel] = dataclasses.replace(
            b, shape=(indices[src].padded_size, indices[dst].padded_size)
        )
    return out


# ---------------------------------------------------------------------------
# Applying a delta
# ---------------------------------------------------------------------------


def _append_to_index(
    idx: TypeIndex, app: NodeAppend, grow_headroom: float
) -> tuple[TypeIndex, bool]:
    """New TypeIndex with ``app`` appended. Returns (index, grew):
    ``grew`` means the append exhausted the capacity reserve and the
    padded size had to change — the caller must treat the delta as a
    full rebuild (array shapes changed)."""
    if app.ids:
        if idx.size_override is not None:
            raise ValueError(
                f"type {idx.node_type!r} has an implicit range index; "
                "append with count=, not ids"
            )
        if len(app.labels) not in (0, len(app.ids)):
            raise ValueError("labels must be empty or match ids 1:1")
        labels = app.labels or app.ids
        dup = [i for i in app.ids if i in idx.index_of]
        if dup:
            raise ValueError(f"node id(s) already present: {dup[:3]}")
        if len(set(app.ids)) != len(app.ids):
            raise ValueError("duplicate ids within one append")
        new_ids = idx.ids + tuple(app.ids)
        new_labels = idx.labels + tuple(labels)
        new_index_of = dict(idx.index_of)
        for k, i in enumerate(app.ids):
            new_index_of[i] = idx.size + k
        new = dataclasses.replace(
            idx, ids=new_ids, labels=new_labels, index_of=new_index_of
        )
    else:
        if idx.size_override is None:
            raise ValueError(
                f"type {idx.node_type!r} has materialized ids; "
                "append with ids, not count="
            )
        new = dataclasses.replace(idx, size_override=idx.size + app.count)
    cap = idx.padded_size
    if new.size > cap:
        return (
            dataclasses.replace(
                new, capacity=_padded_capacity(new.size, grow_headroom)
            ),
            True,
        )
    return dataclasses.replace(new, capacity=idx.capacity), False


def apply_delta(
    hin: EncodedHIN, delta: DeltaBatch, grow_headroom: float = 0.25
) -> tuple[EncodedHIN, bool]:
    """Apply one delta batch → (new EncodedHIN, capacity_grew).

    Node appends land first (added edges may reference them). Edge adds
    must be new and edge removes must exist — the encoding is a simple
    graph (gexf.py dedup) and exact integer path counts depend on it, so
    a malformed delta is rejected loudly rather than silently coalesced.

    ``capacity_grew=True`` means some index space outgrew its reserve:
    the new HIN is still correct (re-padded with ``grow_headroom``), but
    its array shapes changed, so warm backends cannot patch in place —
    callers fall back to a full rebuild.
    """
    indices = dict(hin.indices)
    grew = False
    for app in delta.nodes:
        if app.node_type not in indices:
            raise ValueError(f"unknown node type {app.node_type!r}")
        if app.n == 0:
            continue
        indices[app.node_type], g = _append_to_index(
            indices[app.node_type], app, grow_headroom
        )
        grew = grew or g

    deltas_by_rel: dict[str, EdgeDelta] = {}
    for e in delta.edges:
        if e.relationship not in hin.blocks:
            raise ValueError(f"unknown relationship {e.relationship!r}")
        if e.relationship in deltas_by_rel:
            raise ValueError(
                f"relationship {e.relationship!r} appears twice in one batch"
            )
        deltas_by_rel[e.relationship] = e

    blocks: dict[str, AdjacencyBlock] = {}
    for rel, b in hin.blocks.items():
        src_t, dst_t = hin.schema.relations[rel]
        shape = (indices[src_t].padded_size, indices[dst_t].padded_size)
        e = deltas_by_rel.get(rel)
        if e is None or e.n_changes == 0:
            blocks[rel] = dataclasses.replace(b, shape=shape)
            continue
        n_src, n_dst = indices[src_t].size, indices[dst_t].size
        for name, pairs in (("add", e.add), ("remove", e.remove)):
            if pairs.size and (
                pairs.min() < 0
                or pairs[:, 0].max() >= n_src
                or pairs[:, 1].max() >= n_dst
            ):
                raise ValueError(
                    f"{rel} {name} endpoints out of range "
                    f"[{n_src}, {n_dst}) — append the nodes first"
                )
        existing = _edge_keys(b.rows, b.cols)
        add_keys = _edge_keys(e.add[:, 0], e.add[:, 1])
        rem_keys = _edge_keys(e.remove[:, 0], e.remove[:, 1])
        if np.unique(add_keys).shape[0] != add_keys.shape[0]:
            raise ValueError(f"{rel}: duplicate edges within the add set")
        if np.isin(add_keys, existing).any():
            raise ValueError(f"{rel}: add of an edge that already exists")
        if np.intersect1d(add_keys, rem_keys).size:
            raise ValueError(f"{rel}: edge both added and removed")
        if np.unique(rem_keys).shape[0] != rem_keys.shape[0]:
            raise ValueError(f"{rel}: duplicate edges within the remove set")
        rem_hit = np.isin(existing, rem_keys)
        if int(rem_hit.sum()) != rem_keys.shape[0]:
            raise ValueError(f"{rel}: remove of a nonexistent edge")
        blocks[rel] = AdjacencyBlock(
            relationship=rel,
            src_type=src_t,
            dst_type=dst_t,
            rows=np.concatenate(
                [b.rows[~rem_hit], e.add[:, 0].astype(np.int32)]
            ),
            cols=np.concatenate(
                [b.cols[~rem_hit], e.add[:, 1].astype(np.int32)]
            ),
            shape=shape,
        )

    return (
        EncodedHIN(
            schema=hin.schema, indices=indices, blocks=blocks, name=hin.name
        ),
        grew,
    )


# ---------------------------------------------------------------------------
# Planning (the serving-facing API)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DeltaPlan:
    """Everything a warm service/backend needs to absorb one delta:
    the new HIN, the signed half-chain delta and patched factor (shared
    by every backend so nobody refolds), the affected score rows (a
    sound superset — the row-granular invalidation set), the chained
    fingerprint, and the fallback verdict."""

    delta: DeltaBatch
    hin_old: EncodedHIN
    hin_new: EncodedHIN
    fingerprint: str
    n_edge_changes: int
    fallback: bool
    reason: str | None = None
    delta_c: object | None = None  # ops.sparse.COOMatrix (signed ΔC)
    half_old: object | None = None  # pre-delta factor C
    half_new: object | None = None  # patched factor C
    affected_rows: np.ndarray | None = None  # sorted logical source rows


def half_chain_cached(hin: EncodedHIN, metapath):
    """The metapath's folded half-chain COO factor, memoized per HIN
    (``object.__setattr__`` on the frozen dataclass — same idiom as the
    fingerprint memo). plan_delta seeds the child HIN's entry with the
    patched factor, so a chain of deltas never refolds."""
    from ..ops import planner

    cache = hin.__dict__.get("_half_coo_cache")
    if cache is None:
        cache = {}
        object.__setattr__(hin, "_half_coo_cache", cache)
    c = cache.get(metapath.name)
    if c is None:
        c = cache[metapath.name] = planner.fold_half(hin, metapath).summed()
    return c


def _oriented_delta_blocks(hin: EncodedHIN, metapath, delta: DeltaBatch):
    """(old oriented COO blocks, signed oriented delta blocks) for the
    metapath's half chain — the product-rule inputs."""
    from ..ops import sparse as sp

    by_rel = {e.relationship: e for e in delta.edges}
    old_blocks, delta_blocks = [], []
    for st in metapath.half():
        b = hin.block(st.relationship)
        c = sp.coo_from_block(b)
        e = by_rel.get(st.relationship)
        if e is None:
            rows = np.empty(0, dtype=np.int64)
            cols = np.empty(0, dtype=np.int64)
            w = np.empty(0, dtype=np.float64)
        else:
            rows = np.concatenate([e.add[:, 0], e.remove[:, 0]])
            cols = np.concatenate([e.add[:, 1], e.remove[:, 1]])
            w = np.concatenate(
                [
                    np.ones(e.add.shape[0], dtype=np.float64),
                    -np.ones(e.remove.shape[0], dtype=np.float64),
                ]
            )
        d = sp.COOMatrix(rows=rows, cols=cols, weights=w, shape=c.shape)
        if st.reverse:
            c = sp.COOMatrix(
                rows=c.cols, cols=c.rows, weights=c.weights,
                shape=(c.shape[1], c.shape[0]),
            )
            d = sp.COOMatrix(
                rows=d.cols, cols=d.rows, weights=d.weights,
                shape=(d.shape[1], d.shape[0]),
            )
        old_blocks.append(c)
        delta_blocks.append(d)
    return old_blocks, delta_blocks


def plan_delta(
    hin: EncodedHIN,
    delta: DeltaBatch,
    metapath,
    max_delta_fraction: float = 0.05,
    grow_headroom: float = 0.25,
) -> DeltaPlan:
    """Apply ``delta`` and decide patch-vs-rebuild.

    The patch path requires: a symmetric metapath (the half-chain
    factorization is what makes O(Δ) possible), capacity headroom that
    absorbed any node appends (shapes unchanged), and a delta small
    enough that patching beats rebuilding (``max_delta_fraction`` of
    total edge nnz — past that the O(Δ·deg) products and the O(affected)
    invalidation converge on rebuild cost anyway)."""
    from ..ops import sparse as sp
    from ..serving.cache import chain_fingerprint, graph_fingerprint

    hin_new, grew = apply_delta(hin, delta, grow_headroom=grow_headroom)
    fp = chain_fingerprint(graph_fingerprint(hin), delta.digest())
    # Memoize the child fingerprint: nobody ever re-hashes the blocks.
    object.__setattr__(hin_new, "_fingerprint_cache", fp)

    n_changes = delta.n_edge_changes
    total_nnz = sum(b.nnz for b in hin.blocks.values())

    def _fallback(reason: str) -> DeltaPlan:
        return DeltaPlan(
            delta=delta, hin_old=hin, hin_new=hin_new, fingerprint=fp,
            n_edge_changes=n_changes, fallback=True, reason=reason,
        )

    if grew:
        return _fallback("headroom exhausted: index capacity grew")
    if not metapath.is_symmetric:
        return _fallback(f"metapath {metapath.name} is not symmetric")
    if n_changes > max_delta_fraction * max(total_nnz, 1):
        return _fallback(
            f"delta of {n_changes} edge changes exceeds "
            f"{max_delta_fraction:.0%} of {total_nnz} edges"
        )

    c_old = half_chain_cached(hin, metapath)
    old_blocks, delta_blocks = _oriented_delta_blocks(hin, metapath, delta)
    delta_c = sp.coo_delta_fold(old_blocks, delta_blocks)
    c_new = sp.coo_apply_delta(c_old, delta_c)
    # Seed the child's factor cache: the next delta folds nothing.
    object.__setattr__(hin_new, "_half_coo_cache", {metapath.name: c_new})
    affected = sp.affected_source_rows(
        c_old, c_new, delta_c,
        n_logical=hin_new.type_size(metapath.source_type),
    )
    return DeltaPlan(
        delta=delta, hin_old=hin, hin_new=hin_new, fingerprint=fp,
        n_edge_changes=n_changes, fallback=False,
        delta_c=delta_c, half_old=c_old, half_new=c_new,
        affected_rows=affected,
    )


# ---------------------------------------------------------------------------
# Coalescing (the firehose batching primitive)
# ---------------------------------------------------------------------------


class NotCoalescable(ValueError):
    """The batches cannot fold into one (a within-window conflict —
    e.g. the same edge added twice — that only sequential application
    can express). Callers fall back to applying them one by one."""


def coalesce_deltas(batches) -> DeltaBatch:
    """Fold K *sequentially valid* delta batches into ONE batch whose
    application produces the identical graph (the router's firehose
    batching: the product-rule ΔC composes, so K broadcasts become
    one). Edge changes cancel pairwise — ``add e`` then ``remove e``
    (or remove then re-add) nets to nothing, which is exactly what the
    sequential chain produces — and node appends concatenate in order
    (later batches' edges may reference earlier batches' appends).

    Raises :class:`NotCoalescable` on transitions a single batch
    cannot express (add-after-add, remove-after-remove of one edge, or
    colliding appended ids): such sequences were invalid sequentially
    anyway, or need the window split. Bit-exactness of the coalesced
    result vs the sequential chain is property-tested across all four
    backends (tests/test_firehose.py)."""
    batches = list(batches)
    if not batches:
        return DeltaBatch()
    if len(batches) == 1:
        return batches[0]
    appends: dict[str, dict] = {}  # type → {"ids": [...], "labels": [...], "count": n}
    seen_ids: dict[str, set] = {}
    net: dict[str, dict[tuple[int, int], int]] = {}
    for batch in batches:
        for a in batch.nodes:
            slot = appends.setdefault(
                a.node_type, {"ids": [], "labels": [], "count": 0}
            )
            if a.ids:
                ids_seen = seen_ids.setdefault(a.node_type, set())
                for i in a.ids:
                    if i in ids_seen:
                        raise NotCoalescable(
                            f"node id {i!r} appended twice in window"
                        )
                    ids_seen.add(i)
                slot["ids"].extend(a.ids)
                slot["labels"].extend(a.labels or a.ids)
            else:
                slot["count"] += a.count
        for e in batch.edges:
            m = net.setdefault(e.relationship, {})
            for pairs, sign in ((e.add, 1), (e.remove, -1)):
                for row in pairs:
                    key = (int(row[0]), int(row[1]))
                    cur = m.get(key, 0)
                    if cur == sign:
                        raise NotCoalescable(
                            f"{e.relationship}: edge {key} "
                            f"{'added' if sign > 0 else 'removed'} "
                            "twice in window"
                        )
                    if cur == 0:
                        m[key] = sign
                    else:
                        del m[key]  # add+remove (either order) cancels
    for t, slot in appends.items():
        if slot["ids"] and slot["count"]:
            # a type is either materialized (id appends) or implicit
            # (count appends); a window mixing them was invalid
            # sequentially too — refuse rather than drop either half
            raise NotCoalescable(f"type {t!r} mixes id and count appends")
    nodes = tuple(
        NodeAppend(
            node_type=t,
            ids=tuple(slot["ids"]),
            labels=tuple(slot["labels"]),
            count=slot["count"] if not slot["ids"] else 0,
        )
        for t, slot in appends.items()
        if slot["ids"] or slot["count"]
    )
    edges = tuple(
        edge_delta(
            rel,
            add=[k for k, s in m.items() if s > 0],
            remove=[k for k, s in m.items() if s < 0],
        )
        for rel, m in sorted(net.items())
        if m
    )
    return DeltaBatch(edges=edges, nodes=nodes)


# ---------------------------------------------------------------------------
# Wire-format construction (the JSONL ``update`` op)
# ---------------------------------------------------------------------------


def delta_from_records(
    hin: EncodedHIN,
    add_nodes=(),
    add_edges=(),
    remove_edges=(),
) -> DeltaBatch:
    """Build a DeltaBatch from id-level records (the protocol layer's
    shape)::

        add_nodes:    [{"type": "author", "id": "a9", "label": "Ada"}]
        add_edges:    [{"rel": "author_of", "src": "a9", "dst": "p3"}]
        remove_edges: [{"rel": "author_of", "src_row": 4, "dst_row": 17}]

    Endpoints resolve by id (``src``/``dst``) or raw dense index
    (``src_row``/``dst_row``); ids of nodes appended in the same batch
    resolve to their future indices."""
    appends: dict[str, list[tuple[str, str]]] = {}
    for rec in add_nodes:
        t = rec["type"]
        appends.setdefault(t, []).append(
            (rec["id"], rec.get("label", rec["id"]))
        )
    pending: dict[str, dict[str, int]] = {}
    nodes = []
    for t, pairs in appends.items():
        idx = hin.indices[t]
        pending[t] = {
            i: idx.size + k for k, (i, _) in enumerate(pairs)
        }
        nodes.append(
            NodeAppend(
                node_type=t,
                ids=tuple(p[0] for p in pairs),
                labels=tuple(p[1] for p in pairs),
            )
        )

    def resolve(node_type: str, rec: dict, side: str) -> int:
        row = rec.get(f"{side}_row")
        if row is not None:
            return int(row)
        node_id = rec.get(side)
        if node_id is None:
            raise KeyError(f"edge record needs {side} or {side}_row")
        idx = hin.indices[node_type].index_of.get(node_id)
        if idx is None:
            idx = pending.get(node_type, {}).get(node_id)
        if idx is None:
            raise KeyError(f"no {node_type} with id {node_id!r}")
        return idx

    adds: dict[str, list[tuple[int, int]]] = {}
    rems: dict[str, list[tuple[int, int]]] = {}
    for out, records in ((adds, add_edges), (rems, remove_edges)):
        for rec in records:
            rel = rec["rel"]
            sig = hin.schema.relations.get(rel)
            if sig is None:
                raise KeyError(f"unknown relationship {rel!r}")
            src_t, dst_t = sig
            out.setdefault(rel, []).append(
                (resolve(src_t, rec, "src"), resolve(dst_t, rec, "dst"))
            )
    edges = tuple(
        edge_delta(rel, add=adds.get(rel, ()), remove=rems.get(rel, ()))
        for rel in sorted(set(adds) | set(rems))
    )
    return DeltaBatch(edges=edges, nodes=tuple(nodes))
