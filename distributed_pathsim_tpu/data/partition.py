"""Row-range partitioning of an encoded HIN: ownership, slicing, routing.

The half-chain factorization M = C·Cᵀ is *row-separable*: row ``i`` of
the factor ``C = A₁·A₂·…`` depends only on node ``i``'s own edges in
the first (axis-type) block of the chain — every later block is shared.
That is what makes a bigger-than-one-worker graph servable: partition
the SOURCE-type rows into contiguous ranges, give each worker only its
ranges' slice of the axis blocks (plus the whole of every non-axis
block, which is small for DBLP-shaped HINs), and the worker can compute
its slice of any pairwise row ``M[s, :]`` from the source's factor row
``C[s, :]`` alone — a V-length tile that travels on the wire
(DESIGN.md §26).

Three pieces:

- :class:`PartitionMap` — the ownership geometry: ``n`` logical rows
  split into ``p`` contiguous ceil-division ranges, the SAME geometry
  :class:`~..router.hashring.RangeRouter` routes by (one shared
  definition, so routing and ownership can never disagree). Replication
  is chained: the worker at partition index ``i`` holds ranges
  ``i, i+1, …, i+r−1 (mod p)``, so every range survives ``r−1`` worker
  deaths.
- :func:`slice_hin` — an :class:`EncodedHIN` whose axis-type adjacency
  entries are filtered to the held ranges. Index spaces stay FULL
  (global row numbering, label resolution, block shapes all unchanged)
  — only edge storage shrinks, which is where the memory goes.
- :func:`filter_axis_edges` — the delta-routing filter: restrict a
  wire-level edge-delta record set to the rows a partition holds, so a
  routed update is applied exactly by the holders of its rows and
  nobody else (O(Δ) per owning partition).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .encode import EncodedHIN


@dataclasses.dataclass(frozen=True)
class PartitionMap:
    """Contiguous ceil-division row ranges over ``n`` logical rows.

    Range ``g`` is ``[g·span, min((g+1)·span, n))`` with
    ``span = ceil(n / p)`` — identical to RangeRouter's split, and
    ``owner_of`` clamps to the last partition exactly as its routing
    does, so a row is owned by the partition its queries route to.
    Ranges can be empty when ``n < p``; holders of an empty range
    simply have no rows there.
    """

    n: int
    p: int

    def __post_init__(self):
        if self.p < 1:
            raise ValueError(f"need at least one partition, got {self.p}")
        if self.n < 1:
            raise ValueError(f"need at least one row, got {self.n}")

    @property
    def span(self) -> int:
        return -(-self.n // self.p)  # ceil division

    def range_of(self, g: int) -> tuple[int, int]:
        """Half-open row range ``[lo, hi)`` of partition ``g``."""
        if not 0 <= g < self.p:
            raise ValueError(f"partition {g} out of range [0, {self.p})")
        lo = min(g * self.span, self.n)
        hi = min((g + 1) * self.span, self.n)
        if g == self.p - 1:
            hi = self.n  # the tail partition absorbs any remainder
        return lo, hi

    def owner_of(self, row: int) -> int:
        """Partition index owning ``row``."""
        if not 0 <= row < self.n:
            raise ValueError(f"row {row} out of range [0, {self.n})")
        return min(row // self.span, self.p - 1)

    def ranges(self) -> tuple[tuple[int, int], ...]:
        return tuple(self.range_of(g) for g in range(self.p))

    def held_by(self, part_index: int, replication: int) -> tuple[int, ...]:
        """Range indices the worker at ``part_index`` holds under
        chained replication: its own range plus the next
        ``replication−1`` (mod p), deduplicated in hold order."""
        r = max(1, min(int(replication), self.p))
        out = []
        for j in range(r):
            g = (part_index + j) % self.p
            if g not in out:
                out.append(g)
        return tuple(out)

    def holders_of(self, g: int, replication: int) -> tuple[int, ...]:
        """Partition (= worker) indices holding range ``g``, owner
        first, then the mirrors in chained order — the preference order
        failover walks."""
        r = max(1, min(int(replication), self.p))
        out = []
        for j in range(r):
            w = (g - j) % self.p
            if w not in out:
                out.append(w)
        return tuple(out)

    def rows_held(self, part_index: int, replication: int) -> int:
        return sum(
            hi - lo
            for lo, hi in (
                self.range_of(g)
                for g in self.held_by(part_index, replication)
            )
        )


def _row_mask(values: np.ndarray, ranges) -> np.ndarray:
    mask = np.zeros(values.shape[0], dtype=bool)
    for lo, hi in ranges:
        mask |= (values >= lo) & (values < hi)
    return mask


def slice_hin(hin: EncodedHIN, axis_type: str, ranges) -> EncodedHIN:
    """The partition's resident graph: every adjacency block whose
    source (or destination) type is ``axis_type`` keeps only the edges
    whose axis endpoint falls in ``ranges``; every other block is kept
    whole. Index spaces, shapes, and schema are untouched — global row
    numbering survives, so factor rows, wire payloads, and label
    resolution need no translation layer."""
    ranges = tuple((int(lo), int(hi)) for lo, hi in ranges)
    blocks = {}
    for rel, b in hin.blocks.items():
        src_t, dst_t = hin.schema.relations[rel]
        keep = None
        if src_t == axis_type:
            keep = _row_mask(b.rows, ranges)
        if dst_t == axis_type:
            dmask = _row_mask(b.cols, ranges)
            keep = dmask if keep is None else (keep & dmask)
        if keep is None or bool(keep.all()):
            blocks[rel] = b
            continue
        blocks[rel] = dataclasses.replace(
            b, rows=b.rows[keep], cols=b.cols[keep],
        )
    return EncodedHIN(
        schema=hin.schema, indices=hin.indices, blocks=blocks,
        name=hin.name,
    )


def filter_axis_edges(
    hin: EncodedHIN, axis_type: str, ranges,
    add_edges=(), remove_edges=(),
) -> tuple[list, list]:
    """Restrict wire-level edge records to the rows this partition
    holds. Records on axis-type relationships keep only endpoints in
    ``ranges``; records on shared (non-axis) relationships pass through
    untouched — every partition applies those. Endpoints given by id
    are resolved through the (full) index spaces first."""
    ranges = tuple((int(lo), int(hi)) for lo, hi in ranges)

    def _held(row: int) -> bool:
        return any(lo <= row < hi for lo, hi in ranges)

    def _resolve(node_type: str, rec: dict, end: str) -> int:
        row = rec.get(f"{end}_row")
        if row is not None:
            return int(row)
        return hin.resolve_source(node_type, node_id=rec.get(end))

    def _filter(records) -> list:
        out = []
        for rec in records:
            rel = rec.get("rel")
            if rel not in hin.schema.relations:
                out.append(rec)  # let the delta machinery reject it loudly
                continue
            src_t, dst_t = hin.schema.relations[rel]
            if src_t == axis_type and not _held(_resolve(src_t, rec, "src")):
                continue
            if dst_t == axis_type and not _held(_resolve(dst_t, rec, "dst")):
                continue
            out.append(rec)
        return out

    return _filter(add_edges), _filter(remove_edges)
