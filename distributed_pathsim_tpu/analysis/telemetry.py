"""Telemetry-discipline pass: obs/ stays the only reporting door.

Migrated from scripts/lint_telemetry.py (R2, R3); the wall-clock rule
became determinism.DT003 and the print/stream rules became
wire.WC003/WC004, so the old script's whole rule set lives on across
the unified passes (see MIGRATED_RULES in registry.py).

- **TL001 raw-stderr-print**: ``print(..., file=sys.stderr)`` outside
  the CLI surface and utils/logging.py. Library code reporting through
  raw stderr is invisible to the JSONL sink and the obs counters, and
  interleaves mid-line across threads — that's what ``runtime_event``
  exists for.
- **TL002 event-sink-bypass**: ``_EVENT_SINK`` referenced outside
  utils/logging.py — writing the sink directly skips the lock, the obs
  event counter, and the stderr echo policy.
"""

from __future__ import annotations

import ast

from .astutil import is_print_call, print_stream
from .core import Finding, Module, qualname_index, symbol_at

RULE_DOCS = {
    "TL001": (
        "raw stderr print in library code",
        "library code reports through runtime_event() (JSONL sink + "
        "obs counter + locked stderr), not raw stderr prints",
    ),
    "TL002": (
        "_EVENT_SINK accessed outside utils/logging.py",
        "the event sink is private to utils/logging.py — emitting "
        "through it directly skips the lock and the obs counters; "
        "call runtime_event()",
    ),
}

_STDERR_ALLOWED = frozenset({
    "utils/logging.py", "cli.py", "serving/cli.py", "neural_cli.py",
    "router/cli.py", "index/cli.py", "analysis/cli.py", "batch/cli.py",
})
_SINK_ALLOWED = frozenset({"utils/logging.py"})


class TelemetryPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        for m in modules:
            if m.root_kind != "package":
                continue
            index = None
            if m.rel not in _STDERR_ALLOWED:
                for node in m.nodes:
                    if is_print_call(node) and print_stream(node) == "stderr":
                        if index is None:
                            index = qualname_index(m.tree)
                        findings.append(Finding(
                            path=m.repo_rel, line=node.lineno,
                            rule="TL001",
                            symbol=symbol_at(index, node.lineno),
                            message=(
                                "print(..., file=sys.stderr) in library "
                                "code — use runtime_event()"
                            ),
                        ))
            if m.rel not in _SINK_ALLOWED:
                for node in m.nodes:
                    if (
                        isinstance(node, (ast.Name, ast.Attribute))
                        and getattr(node, "id", getattr(node, "attr", None))
                        == "_EVENT_SINK"
                    ):
                        if index is None:
                            index = qualname_index(m.tree)
                        findings.append(Finding(
                            path=m.repo_rel, line=node.lineno,
                            rule="TL002",
                            symbol=symbol_at(index, node.lineno),
                            message=(
                                "_EVENT_SINK is private to "
                                "utils/logging.py — call runtime_event()"
                            ),
                        ))
        return findings
