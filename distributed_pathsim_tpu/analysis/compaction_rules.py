"""Compaction-doorway pass: the hot-swap stays behind the service.

- **CP001 compaction-swap-reached-outside-the-service-doorway**: a
  compaction swap (serving/compact.py, DESIGN.md §30) preserves the
  consistency token, the chained fingerprint, the per-row cache
  versions, and both cache tiers — invariants that hold ONLY because
  :meth:`PathSimService._apply_compaction` performs the whole sequence
  (token re-check, mid-build delta replay, pipeline drain, install)
  atomically under the swap lock. A module that reaches
  ``_apply_compaction``/``_swap_compacted`` from anywhere else can
  install a backend whose graph lags the live delta chain, or swap
  without draining — serving stale rows with a CURRENT token, which no
  fencing layer can catch. The surface registry is a frozenset literal
  parsed out of serving/service.py (the PT001/CF001 pattern), so the
  rule and the code cannot drift; serving/compact.py is the one
  sanctioned caller.
"""

from __future__ import annotations

import ast

from .core import Finding, Module, qualname_index, symbol_at
from .wire import _frozenset_literal

RULE_DOCS = {
    "CP001": (
        "compaction swap reached outside the service doorway",
        "the compaction hot-swap's invariants (token/fingerprint/cache "
        "preservation, mid-build delta replay, drain-before-install) "
        "hold only inside PathSimService._apply_compaction under the "
        "swap lock; reaching the swap internals from anywhere but "
        "serving/compact.py can install a stale backend behind a "
        "current consistency token — serve compaction through "
        "service.compact() / the 'compact' protocol op instead",
    ),
}

_SERVICE = "serving/service.py"
# the sanctioned caller: the background builder itself
_ALLOWED = frozenset({
    "serving/service.py",
    "serving/compact.py",
})


class CompactionDoorwayPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        pkg = [m for m in modules if m.root_kind == "package"]
        surface = None
        for m in pkg:
            if m.rel == _SERVICE:
                surface = _frozenset_literal(m.tree, "COMPACTION_SURFACE")
                break
        if not surface:
            return []  # no compaction layer in this tree (fixture corpora)
        findings: list[Finding] = []
        for m in pkg:
            if m.rel in _ALLOWED:
                continue
            index = None
            for node in m.nodes:
                if (
                    isinstance(node, ast.Attribute)
                    and node.attr in surface
                ):
                    if index is None:
                        index = qualname_index(m.tree)
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="CP001",
                        symbol=symbol_at(index, node.lineno),
                        message=(
                            f".{node.attr} reached outside the service "
                            "doorway — the compaction swap is only "
                            "sound inside _apply_compaction under the "
                            "swap lock; use service.compact() (or the "
                            "'compact' protocol op)"
                        ),
                    ))
        return findings
