"""Metapath-IR pass: no chain evaluation outside the planner.

- **MP001 chain-evaluation-outside-the-planner**: the metapath-IR
  refactor (DESIGN.md §28) made the adjacency chain *data*: the only
  sanctioned way to evaluate it is through ``ops/planner.py``, whose
  plans carry the DP association order, the cost estimates, and the
  sub-chain memoization hooks. A module that calls a chain-fold
  primitive directly gets none of that — it silently reverts to the
  hardcoded left-to-right fold the refactor retired, bypasses the
  workload memo, and its results stop being auditable through the
  plan dump. This is exactly the reachability query the
  interprocedural engine was built for (PR 12, DESIGN.md §27): seed
  every chain-evaluation primitive (``chain_product`` /
  ``half_product`` / ``rowsums_general`` in ops/chain.py, the COO
  ``fold_half_chain`` in ops/sparse.py), cut the call graph at the
  planner doorway (edges INTO ops/planner.py functions are removed —
  going through the doorway is the sanctioned path), run
  ``callgraph.propagate_reachability``, and flag every package
  function outside the primitive-owning modules from which a seed is
  still reachable. The finding message carries the witness chain, so
  the report says *how* the module reaches the primitive.

Deliberately NOT seeded: the half-factor *scoring* primitives
(``commuting_matrix_from_half``, ``rowsums_from_half``,
``pairwise_row_from_half``, the tile/ring GEMM kernels) — those
consume an already-folded factor C, they do not evaluate the chain;
and ``coo_matmul`` — the delta algebra's product rule uses it for
O(Δ) patches, which is incremental maintenance, not evaluation.
"""

from __future__ import annotations

from .callgraph import propagate_reachability, shared_package_graph
from .core import Finding, Module

RULE_DOCS = {
    "MP001": (
        "chain evaluation outside the planner",
        "the metapath chain is data: every evaluation must go through "
        "ops/planner.py (plan_metapath + fold_half / fold_general / "
        "fold_blocks / execute_dense / rowsums_fold), which owns the "
        "DP association order, the cost audit, and the sub-chain "
        "memo. Direct calls to the chain-fold primitives silently "
        "revert to the hardcoded left-to-right fold the metapath-IR "
        "refactor retired",
    ),
}

# (package-relative module, function qualname) -> human witness. These
# are the chain-evaluation primitives; reaching one without passing
# through the planner doorway is the violation.
_SEEDS: dict[tuple[str, str], str] = {
    ("ops/chain.py", "chain_product"): "chain.chain_product()",
    ("ops/chain.py", "half_product"): "chain.half_product()",
    ("ops/chain.py", "rowsums_general"): "chain.rowsums_general()",
    ("ops/sparse.py", "fold_half_chain"): "sparse.fold_half_chain()",
}

# The planner itself plus the primitive-owning modules (their
# internals may compose each other freely; the boundary is the module
# surface, same shape as PT001's exchange-layer allowance).
_PLANNER = "ops/planner.py"
_ALLOWED = frozenset({_PLANNER, "ops/chain.py", "ops/sparse.py"})


class MetapathIRPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        graph = shared_package_graph(modules)
        seeds: dict[str, str] = {}
        for fid in sorted(graph.by_fid):
            fn = graph.by_fid[fid]
            key = (fn.module.rel, fn.qual)
            if key in _SEEDS:
                seeds[fid] = _SEEDS[key]
        if not seeds:
            return []  # no chain layer in this tree (fixture corpora)
        # The doorway cut: edges into planner-defined functions are
        # removed BEFORE propagation, so "reaches a seed" means
        # "reaches it without going through the planner" — the exact
        # sanctioned/unsanctioned distinction the rule states.
        edges: dict[str, set[str]] = {}
        for site in graph.call_sites():
            if site.callee is None:
                continue
            callee = graph.by_fid[site.callee]
            if callee.module.rel == _PLANNER:
                continue
            edges.setdefault(site.caller, set()).add(site.callee)
        chains = propagate_reachability(graph, seeds, edges=edges)
        findings: list[Finding] = []
        for fid in sorted(chains):
            fn = graph.by_fid.get(fid)
            if fn is None or fn.module.rel in _ALLOWED:
                continue
            witness = " -> ".join(chains[fid])
            findings.append(Finding(
                path=fn.module.repo_rel,
                line=fn.node.lineno,
                rule="MP001",
                symbol=fn.qual,
                message=(
                    f"reaches a chain-evaluation primitive without "
                    f"going through the planner ({witness}); use "
                    "ops/planner.py (fold_half / fold_general / "
                    "execute_dense / rowsums_fold) instead"
                ),
            ))
        return findings
