"""Lock-discipline pass: guarded attributes stay guarded.

For every class that owns a ``threading.Lock``/``RLock`` field, the
pass computes the set of instance attributes *mutated* while that lock
is held (direct assignment, augmented assignment, subscript store/del,
or a mutating container method like ``append``/``pop``/``clear`` —
lexically inside a ``with self._lock:`` block, Conditions constructed
on the lock counting as the lock). Any read or write of a guarded
attribute on a path that provably does not hold the lock is a finding:

- **LD001** unlocked WRITE of a lock-guarded attribute (a real race:
  two writers, or a writer racing the locked readers), and
- **LD002** unlocked READ (torn/stale view of state the class itself
  says needs the lock).

"Provably does not hold it" is made precise by a small intra-class
dataflow: a private method whose every internal call site runs with the
lock held is itself treated as lock-held (fixpoint over the class's
call graph), so the common ``_helper_called_under_lock`` pattern is not
noise. ``__init__``/``__del__`` are exempt (construction is
single-threaded), and code inside nested functions/lambdas is treated
as NOT holding the enclosing lock — a closure runs later, on whatever
thread calls it, which is exactly how completion callbacks race.

Intended targets: the coalescer, the two-tier caches, the router's
pending table, the flight ring, the ANN confidence gate — everything
the serving tier touches from more than one thread.
"""

from __future__ import annotations

import ast
import dataclasses

from .astutil import call_name, self_attr
from .core import Finding, Module

_LOCK_CTORS = ("threading.Lock", "threading.RLock")
_MUTATORS = frozenset({
    "append", "appendleft", "add", "discard", "remove", "clear", "pop",
    "popitem", "popleft", "update", "setdefault", "move_to_end",
    "extend", "insert", "__setitem__",
})
_EXEMPT_METHODS = frozenset({"__init__", "__new__", "__del__"})

RULE_DOCS = {
    "LD001": (
        "unlocked write to a lock-guarded attribute",
        "the class writes this attribute under its lock elsewhere — an "
        "unlocked write races both the locked writers and every locked "
        "reader; take the lock (or baseline with a justification)",
    ),
    "LD002": (
        "unlocked read of a lock-guarded attribute",
        "the class mutates this attribute under its lock — an unlocked "
        "read can observe torn/stale state; take the lock (or baseline "
        "a deliberately racy read with a justification)",
    ),
}


@dataclasses.dataclass
class _ClassInfo:
    node: ast.ClassDef
    qual: str
    locks: set[str] = dataclasses.field(default_factory=set)
    # condition/alias attr -> underlying lock attr
    aliases: dict[str, str] = dataclasses.field(default_factory=dict)
    # lock attr -> guarded instance attrs
    guarded: dict[str, set[str]] = dataclasses.field(default_factory=dict)
    methods: dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict
    )
    # method name -> set of locks held at EVERY internal call site
    held_for: dict[str, set[str]] = dataclasses.field(default_factory=dict)


def _classes(module: Module) -> list[_ClassInfo]:
    out = []

    def visit(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                info = _ClassInfo(node=child, qual=qual)
                for stmt in child.body:
                    if isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        info.methods[stmt.name] = stmt
                out.append(info)
                visit(child, qual)
            else:
                visit(child, prefix)

    visit(module.tree, "")
    return out


def _find_locks(info: _ClassInfo) -> None:
    for fn in info.methods.values():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if len(node.targets) != 1:
                continue
            attr = self_attr(node.targets[0])
            if attr is None or not isinstance(node.value, ast.Call):
                continue
            cn = call_name(node.value)
            if cn in _LOCK_CTORS:
                info.locks.add(attr)
            elif cn == "threading.Condition" and node.value.args:
                base = self_attr(node.value.args[0])
                if base is not None:
                    info.aliases[attr] = base


def _with_locks(node: ast.With, info: _ClassInfo) -> set[str]:
    """Lock attrs this ``with`` acquires (conditions resolve to their
    lock)."""
    held: set[str] = set()
    for item in node.items:
        attr = self_attr(item.context_expr)
        if attr is None:
            continue
        if attr in info.locks:
            held.add(attr)
        elif attr in info.aliases:
            held.add(info.aliases[attr])
    return held


def _written_attrs(node: ast.AST) -> list[str]:
    """EVERY self-attribute a statement mutates (tuple targets included)."""
    out: list[str] = []
    if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                attr = self_attr(e)
                if attr is not None:
                    out.append(attr)
                elif isinstance(e, ast.Subscript):
                    attr = self_attr(e.value)
                    if attr is not None:
                        out.append(attr)
    if isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                attr = self_attr(t.value)
                if attr is not None:
                    out.append(attr)
            attr = self_attr(t)
            if attr is not None:
                out.append(attr)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in _MUTATORS:
            attr = self_attr(node.func.value)
            if attr is not None:
                out.append(attr)
    return out


def _collect_guarded(info: _ClassInfo) -> None:
    """Attrs mutated lexically under ``with self.<lock>``, per lock."""
    for lock in info.locks:
        info.guarded.setdefault(lock, set())

    def scan(node: ast.AST, held: frozenset[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                scan(child, frozenset())  # closures run unlocked
                continue
            child_held = held
            if isinstance(child, ast.With):
                child_held = held | _with_locks(child, info)
            if held:
                for attr in _written_attrs(child):
                    if attr in info.locks or attr in info.aliases:
                        continue
                    for lock in held:
                        info.guarded[lock].add(attr)
            scan(child, child_held)

    for fn in info.methods.values():
        scan(fn, frozenset())


def _held_fixpoint(info: _ClassInfo) -> None:
    """Private methods whose every internal call site holds lock L are
    themselves held-for-L."""
    # method -> list of lock-sets held at each internal call site
    callsites: dict[str, list[set[str]]] = {m: [] for m in info.methods}

    def scan(node: ast.AST, held: set[str], extra: set[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                # closures run later on whatever thread calls them: a
                # call site inside one holds NEITHER the lexical locks
                # NOR the enclosing method's held-for set
                scan(child, set(), set())
                continue
            child_held = held
            if isinstance(child, ast.With):
                child_held = held | _with_locks(child, info)
            if isinstance(child, ast.Call):
                m = None
                if (
                    isinstance(child.func, ast.Attribute)
                    and isinstance(child.func.value, ast.Name)
                    and child.func.value.id == "self"
                    and child.func.attr in info.methods
                ):
                    m = child.func.attr
                if m is not None:
                    callsites[m].append(set(child_held) | set(extra))
            scan(child, child_held, extra)

    info.held_for = {m: set() for m in info.methods}
    for _ in range(len(info.methods) + 1):
        for sites in callsites.values():
            sites.clear()
        for name, fn in info.methods.items():
            if name in _EXEMPT_METHODS:
                # construction is single-threaded: a call from __init__
                # needs no lock and must not veto a helper's heldness
                continue
            scan(fn, set(), info.held_for.get(name, set()))
        changed = False
        for name in info.methods:
            if not name.startswith("_") or name.startswith("__"):
                continue  # public methods are callable from anywhere
            sites = callsites[name]
            if not sites:
                continue
            new = set.intersection(*sites) if sites else set()
            if new != info.held_for[name]:
                info.held_for[name] = new
                changed = True
        if not changed:
            break


def _scan_method(fn, base_held, qual, info, all_guarded, module, findings):
    def scan(node: ast.AST, held: set[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_unlocked(child, f"{qual}.<{child.name}>")
                continue
            if isinstance(child, ast.Lambda):
                scan_unlocked(child, qual)
                continue
            child_held = held
            if isinstance(child, ast.With):
                child_held = held | _with_locks(child, info)
            _check(child, held, qual)
            scan(child, child_held)

    def scan_unlocked(node: ast.AST, q: str) -> None:
        for child in ast.iter_child_nodes(node):
            _check(child, set(), q)
            scan_unlocked(child, q)

    reported: set[int] = set()

    def _check(node: ast.AST, held: set[str], q: str) -> None:
        for written in _written_attrs(node):
            if written not in all_guarded:
                continue
            locks = all_guarded[written]
            if not (locks & held):
                key = (id(node), written)
                if key not in reported:
                    reported.add(key)
                    findings.append(_mk(node, written, locks, q, True))
            # mark the attribute node of this statement as handled
            for sub in ast.walk(node):
                if self_attr(sub) == written:
                    reported.add(id(sub))
        if isinstance(node, ast.Attribute):
            attr = self_attr(node)
            if (
                attr in all_guarded
                and isinstance(node.ctx, ast.Load)
                and id(node) not in reported
            ):
                locks = all_guarded[attr]
                if not (locks & held):
                    reported.add(id(node))
                    findings.append(_mk(node, attr, locks, q, False))

    def _mk(node, attr, locks, q, write) -> Finding:
        lock_names = "/".join(sorted(locks))
        return Finding(
            path=module.repo_rel, line=node.lineno,
            rule="LD001" if write else "LD002", symbol=q,
            message=(
                f"{'write to' if write else 'read of'} self.{attr} "
                f"without holding self.{lock_names} (attribute is "
                f"mutated under that lock elsewhere in {info.qual})"
            ),
        )

    scan(fn, set(base_held))


class LockDisciplinePass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        for module in modules:
            if module.root_kind == "tests":
                continue  # test helpers race on purpose
            for info in _classes(module):
                _find_locks(info)
                if not info.locks:
                    continue
                _collect_guarded(info)
                _held_fixpoint(info)
                _report_safe(info, module, findings)
        return findings


def _report_safe(info, module, findings):
    all_guarded: dict[str, set[str]] = {}
    for lock, attrs in info.guarded.items():
        for a in attrs:
            all_guarded.setdefault(a, set()).add(lock)
    if not all_guarded:
        return
    for name, fn in info.methods.items():
        if name in _EXEMPT_METHODS:
            continue
        _scan_method(
            fn, info.held_for.get(name, set()),
            f"{info.qual}.{name}", info, all_guarded, module, findings,
        )
