"""Parse/mtime cache: the whole-repo run stays inside the tier-1 gate.

The analyzer's cost is dominated by reading + ``ast.parse``-ing every
file; the interprocedural passes re-walk the same trees. This cache
pickles parsed trees keyed by ``(mtime_ns, size)`` so a warm run skips
parsing for every unchanged file — the common CI/pre-commit case where
one file changed and 200 didn't.

Correctness over cleverness:

- the key is per-file ``(mtime_ns, size)``; any mismatch re-parses
  (there is no content hash: stat is the budget here);
- the cache format carries a version stamp (bump ``_VERSION`` when the
  :class:`~.core.Module` shape changes) and the Python version (pickled
  AST objects are not stable across interpreter versions);
- every failure mode — unreadable cache, unpicklable entry, version
  skew — silently degrades to a full parse. The cache can make lint
  faster, never wrong.

The cache file lives in ``<repo>/.lint_cache/`` (gitignored): the
analyzer must not write inside the package tree it is analyzing.
"""

from __future__ import annotations

import ast
import pathlib
import pickle
import sys

from .core import Module, default_roots, repo_root

_VERSION = 1
CACHE_REL = ".lint_cache/parse.pkl"


def _cache_key() -> tuple:
    return (_VERSION, sys.version_info[:2])


def load_modules_cached(
    roots: dict | None = None,
    repo: pathlib.Path | None = None,
    cache_path: pathlib.Path | str | None = None,
) -> list[Module]:
    """Drop-in for :func:`~.core.load_modules` with the pickle cache.
    Walk order and Module contents are identical to the uncached
    loader — byte-stable output is part of the contract."""
    repo = repo or repo_root()
    roots = roots or default_roots(repo)
    cache_file = (
        pathlib.Path(cache_path) if cache_path is not None
        else repo / CACHE_REL
    )
    entries: dict[str, tuple] = {}
    try:
        with open(cache_file, "rb") as f:
            stored = pickle.load(f)
        if stored.get("key") == _cache_key():
            entries = stored.get("files", {})
    except Exception:
        entries = {}

    modules: list[Module] = []
    fresh: dict[str, tuple] = {}
    dirty = False
    for kind in sorted(roots):
        root = pathlib.Path(roots[kind])
        if not root.is_dir():
            continue
        for path in sorted(root.rglob("*.py")):
            if "fixtures" in path.relative_to(root).parts:
                continue
            try:
                st = path.stat()
                stat_key = (st.st_mtime_ns, st.st_size)
            except OSError:
                continue
            try:
                repo_rel = (
                    path.resolve().relative_to(repo.resolve()).as_posix()
                )
            except ValueError:
                repo_rel = path.as_posix()
            cached = entries.get(repo_rel)
            if cached is not None and cached[0] == stat_key:
                text, tree = cached[1], cached[2]
            else:
                dirty = True
                try:
                    text = path.read_text(encoding="utf-8")
                    tree = ast.parse(text, filename=str(path))
                except (OSError, SyntaxError):
                    continue
            fresh[repo_rel] = (stat_key, text, tree)
            modules.append(Module(
                path=path,
                rel=path.relative_to(root).as_posix(),
                repo_rel=repo_rel,
                root_kind=kind,
                text=text,
                tree=tree,
            ))
    if dirty or set(fresh) != set(entries):
        try:
            cache_file.parent.mkdir(parents=True, exist_ok=True)
            tmp = cache_file.with_suffix(".tmp")
            with open(tmp, "wb") as f:
                pickle.dump(
                    {"key": _cache_key(), "files": fresh}, f,
                    protocol=pickle.HIGHEST_PROTOCOL,
                )
            tmp.replace(cache_file)
        except Exception:
            pass  # a cache that can't be written is just a cold cache
    return modules
