"""Learned-doorway pass: raw tower scores stay behind the rerank.

- **LN001 tower-scores-reached-outside-the-learned-doorway**: the
  learned tier's raw tower similarities (``tower_sims`` /
  ``ProbeHandle.raw_sims``, learned/serving.py, DESIGN.md §32) are
  approximations in a score-LIKE scale — an operator (or any host
  boundary: protocol result, cache, metric, log) reading them as
  PathSim scores would be silently wrong in score units, which is
  exactly the failure the learned arm's safety story exists to
  exclude. Every served answer must leave through
  ``LearnedState.answer_from_handle``, which exact-f64 reranks inside
  ``learned/``. The surface registry is a frozenset literal parsed out
  of learned/serving.py (the CF001/BT001 pattern), so the rule and the
  code cannot drift; only modules inside ``learned/`` may unwrap the
  handle.
"""

from __future__ import annotations

import ast

from .core import Finding, Module, qualname_index, symbol_at
from .wire import _frozenset_literal

RULE_DOCS = {
    "LN001": (
        "raw tower scores reached outside the learned doorway",
        "tower similarities are approximate shortlist scores, not "
        "PathSim scores; every answer must be exact-f64 reranked "
        "inside learned/ (LearnedState.answer_from_handle) before it "
        "reaches a host boundary — unwrap the probe handle only in "
        "learned/ modules",
    ),
}

_ENGINE = "learned/serving.py"
# the sanctioned callers: the learned package itself (the rerank
# doorway lives there, and the trainer/bench read raw predictions to
# MEASURE the towers, never to serve them)
_ALLOWED_PREFIX = "learned/"


class LearnedDoorwayPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        pkg = [m for m in modules if m.root_kind == "package"]
        surface = None
        for m in pkg:
            if m.rel == _ENGINE:
                surface = _frozenset_literal(m.tree, "LEARNED_SURFACE")
                break
        if not surface:
            return []  # no learned tier in this tree (fixture corpora)
        findings: list[Finding] = []
        for m in pkg:
            if m.rel.startswith(_ALLOWED_PREFIX):
                continue
            index = None
            for node in m.nodes:
                if (
                    isinstance(node, ast.Attribute)
                    and node.attr in surface
                ):
                    if index is None:
                        index = qualname_index(m.tree)
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="LN001",
                        symbol=symbol_at(index, node.lineno),
                        message=(
                            f".{node.attr} reached outside the learned "
                            "doorway — raw tower similarities are "
                            "approximate shortlist scores; serve "
                            "answers only through LearnedState."
                            "answer_from_handle (exact f64 rerank "
                            "inside learned/)"
                        ),
                    ))
        return findings
