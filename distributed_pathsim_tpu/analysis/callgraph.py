"""Whole-repo call graph + the fact-propagation fixpoint engine.

This is the interprocedural backbone the semantic passes (interlocks.py,
wireschema.py) run on — and the hook ROADMAP items 1 and 4 name: the
metapath-IR planner pass ("no chain evaluation outside the planner")
and the packed-layout boundary pass ("packed layouts must not leak past
the factor boundary") are both "facts propagated over this graph".

Design constraints, same as the rest of ``analysis/``:

- **One parse**: built from the already-loaded :class:`~.core.Module`
  list; no file is re-read.
- **Deterministic**: functions are indexed in source order of the
  sorted module walk; every iteration below runs over sorted keys, so
  witness chains and fixpoint results are byte-stable run to run.
- **Name-resolution honesty**: an edge exists only when the callee is
  *resolved* — ``self.m()`` to a method of the lexically enclosing
  class, bare/module-attribute calls through the module's import map,
  and ``x = ClassName(...); x.m()`` through a single-assignment local
  type map. Everything else (duck-typed attribute calls, dynamic
  dispatch) stays unresolved: the passes treat unresolved calls
  conservatively *per rule* (e.g. a blocking-primitive name match fires
  without resolution; lock facts never flow through an unresolved
  edge, so an unknown callee can hide a fact but never fabricate one).
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib

from .astutil import call_name, dotted
from .core import Module


@dataclasses.dataclass
class FuncInfo:
    """One function/method in the repo-wide index. ``fid`` is the
    stable identity findings and witness chains use:
    ``"<repo_rel>:<qualname>"``."""

    fid: str
    module: Module
    qual: str
    cls: str | None          # enclosing class qualname (None: free func)
    name: str                # bare name
    node: ast.FunctionDef | ast.AsyncFunctionDef

    @property
    def private(self) -> bool:
        """Callable only from inside the repo by convention: a leading
        underscore, not a dunder. Only private functions may inherit
        caller facts (anything public has unknown external callers)."""
        return self.name.startswith("_") and not self.name.startswith("__")

    @property
    def params(self) -> list[str]:
        a = self.node.args
        return [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]


@dataclasses.dataclass
class CallSite:
    """One resolved or unresolved call inside ``caller``."""

    caller: str              # fid
    callee: str | None       # fid when resolved, else None
    node: ast.Call


class CallGraph:
    """The repo-wide function index + resolver. Construction walks
    every module once; :meth:`resolve` answers per-call-site questions
    for the passes (which also need the raw AST around the site, so
    they re-walk function bodies themselves with :meth:`resolve` in
    hand rather than consuming a pre-flattened edge list)."""

    def __init__(self, modules: list[Module]):
        self.modules = modules
        self.by_fid: dict[str, FuncInfo] = {}
        # (repo_rel, class_qual, method) -> fid
        self._methods: dict[tuple[str, str, str], str] = {}
        # (repo_rel, name) -> fid for module-level functions
        self._module_funcs: dict[tuple[str, str], str] = {}
        # (repo_rel, name) -> class qualname, for local classes
        self._classes: dict[tuple[str, str], str] = {}
        # per module repo_rel: imported name -> ("mod", target_repo_rel)
        #                                     | ("sym", target_rel, name)
        self._imports: dict[str, dict[str, tuple]] = {}
        self._by_repo_rel = {m.repo_rel: m for m in modules}
        self._lt_cache: dict[str, dict] = {}
        self._rel_index: dict[str, str] = {}  # package rel -> repo_rel
        for m in modules:
            self._rel_index.setdefault(m.rel, m.repo_rel)
        for m in modules:
            self._index_module(m)
        for m in modules:
            self._imports[m.repo_rel] = self._import_map(m)

    # -- indexing ----------------------------------------------------------

    def _index_module(self, m: Module) -> None:
        def visit(node: ast.AST, prefix: str, cls: str | None) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    qual = (
                        f"{prefix}.{child.name}" if prefix else child.name
                    )
                    self._classes[(m.repo_rel, child.name)] = qual
                    visit(child, qual, qual)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = (
                        f"{prefix}.{child.name}" if prefix else child.name
                    )
                    fid = f"{m.repo_rel}:{qual}"
                    info = FuncInfo(
                        fid=fid, module=m, qual=qual, cls=cls,
                        name=child.name, node=child,
                    )
                    self.by_fid[fid] = info
                    if cls is not None:
                        self._methods[(m.repo_rel, cls, child.name)] = fid
                    elif prefix == "":
                        self._module_funcs[(m.repo_rel, child.name)] = fid
                    # nested defs are not methods of the class
                    visit(child, qual, None)
                else:
                    visit(child, prefix, cls)

        visit(m.tree, "", None)

    def _module_dir_parts(self, m: Module) -> list[str]:
        return list(pathlib.PurePosixPath(m.rel).parts[:-1])

    def _candidate_rel(self, parts: list[str]) -> str | None:
        """A module path (as root-relative parts) -> repo_rel of the
        analyzed file implementing it, if any."""
        if not parts:
            return None
        for rel in ("/".join(parts) + ".py",
                    "/".join(parts) + "/__init__.py"):
            if rel in self._rel_index:
                return self._rel_index[rel]
        return None

    def _import_map(self, m: Module) -> dict[str, tuple]:
        """name -> resolution for this module's imports that land on an
        analyzed file. Absolute imports of the package are mapped by
        stripping the package name (the package root is a walk root)."""
        out: dict[str, tuple] = {}
        pkg_prefix = "distributed_pathsim_tpu"
        for node in m.nodes:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    parts = alias.name.split(".")
                    if parts[0] == pkg_prefix:
                        parts = parts[1:]
                    rel = self._candidate_rel(parts)
                    if rel is not None:
                        out[alias.asname or parts[-1]] = ("mod", rel)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = self._module_dir_parts(m)
                    if node.level > 1:
                        base = base[: -(node.level - 1)] or []
                else:
                    base = []
                mod_parts = (node.module or "").split(".") if node.module \
                    else []
                if mod_parts and mod_parts[0] == pkg_prefix:
                    mod_parts = mod_parts[1:]
                target_parts = base + [p for p in mod_parts if p]
                for alias in node.names:
                    name = alias.asname or alias.name
                    # `from pkg import mod` (the name IS a module)
                    sub = self._candidate_rel(
                        target_parts + [alias.name]
                    )
                    if sub is not None:
                        out[name] = ("mod", sub)
                        continue
                    # `from pkg.mod import symbol`
                    rel = self._candidate_rel(target_parts)
                    if rel is not None:
                        out[name] = ("sym", rel, alias.name)
        return out

    # -- per-function local type map ---------------------------------------

    def local_types(self, fn: FuncInfo) -> dict[str, tuple[str, str]]:
        """Single-assignment ``x = ClassName(...)`` locals:
        name -> (repo_rel, class_qual). A name assigned twice (or to
        anything else) is dropped — no merging, no flow sensitivity.
        Cached per function (several passes ask repeatedly)."""
        hit = self._lt_cache.get(fn.fid)
        if hit is not None:
            return hit
        assigned: dict[str, tuple[str, str] | None] = {}
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not isinstance(t, ast.Name):
                continue
            hit = None
            if isinstance(node.value, ast.Call):
                cls = self.resolve_class(fn.module, node.value.func)
                if cls is not None:
                    hit = cls
            if t.id in assigned:
                assigned[t.id] = None  # reassigned: unknown
            else:
                assigned[t.id] = hit
        out = {k: v for k, v in assigned.items() if v is not None}
        self._lt_cache[fn.fid] = out
        return out

    def resolve_class(
        self, m: Module, node: ast.AST
    ) -> tuple[str, str] | None:
        """A Name/Attribute that names a class we indexed."""
        if isinstance(node, ast.Name):
            key = (m.repo_rel, node.id)
            if key in self._classes:
                return (m.repo_rel, self._classes[key])
            imp = self._imports.get(m.repo_rel, {}).get(node.id)
            if imp is not None and imp[0] == "sym":
                key = (imp[1], imp[2])
                if key in self._classes:
                    return (imp[1], self._classes[key])
        elif isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            imp = self._imports.get(m.repo_rel, {}).get(node.value.id)
            if imp is not None and imp[0] == "mod":
                key = (imp[1], node.attr)
                if key in self._classes:
                    return (imp[1], self._classes[key])
        return None

    # -- call resolution ---------------------------------------------------

    def resolve(
        self, fn: FuncInfo, call: ast.Call,
        local_types: dict[str, tuple[str, str]] | None = None,
    ) -> str | None:
        """fid of the callee, or None. ``local_types`` is the caller's
        :meth:`local_types` map (passed in so a body walk computes it
        once)."""
        m = fn.module
        func = call.func
        # self.method()
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
        ):
            base, attr = func.value.id, func.attr
            if base == "self" and fn.cls is not None:
                fid = self._methods.get((m.repo_rel, fn.cls, attr))
                if fid is not None:
                    return fid
            if local_types and base in local_types:
                rel, cls = local_types[base]
                fid = self._methods.get((rel, cls, attr))
                if fid is not None:
                    return fid
            imp = self._imports.get(m.repo_rel, {}).get(base)
            if imp is not None and imp[0] == "mod":
                return self._module_funcs.get((imp[1], attr))
            return None
        if isinstance(func, ast.Name):
            fid = self._module_funcs.get((m.repo_rel, func.id))
            if fid is not None:
                return fid
            imp = self._imports.get(m.repo_rel, {}).get(func.id)
            if imp is not None and imp[0] == "sym":
                return self._module_funcs.get((imp[1], imp[2]))
        return None

    def call_sites(self) -> list[CallSite]:
        """Every call in every function, in deterministic order.
        Memoized on the instance: the walk+resolve is a real fraction
        of the tier-1 lint budget and the graph is immutable after
        construction, so every interprocedural pass sharing this graph
        shares one walk."""
        cached = getattr(self, "_call_sites_cache", None)
        if cached is not None:
            return cached
        out: list[CallSite] = []
        for fid in sorted(self.by_fid):
            fn = self.by_fid[fid]
            lt = self.local_types(fn)
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    out.append(CallSite(
                        caller=fid,
                        callee=self.resolve(fn, node, lt),
                        node=node,
                    ))
        self._call_sites_cache = out
        return out

    def functions_named(
        self, name: str, rel_prefix: str = "",
        with_param: str | None = None,
    ) -> list[FuncInfo]:
        """Fallback resolution for dynamic dispatch (``getattr(service,
        op)``-style trampolines): every indexed function with this bare
        name, optionally restricted to a tree and to functions taking a
        parameter of a given name. Sorted by fid."""
        out = []
        for fid in sorted(self.by_fid):
            fn = self.by_fid[fid]
            if fn.name != name:
                continue
            if rel_prefix and not fn.module.rel.startswith(rel_prefix):
                continue
            if with_param is not None and with_param not in fn.params:
                continue
            out.append(fn)
        return out


# -- the generic fixpoint engine ---------------------------------------------


def propagate_reachability(
    graph: CallGraph,
    seeds: dict[str, str],
    edges: dict[str, set[str]] | None = None,
) -> dict[str, list[str]]:
    """The "facts over the call graph to fixpoint" primitive: given
    seed functions (fid -> human-readable witness for WHY the fact
    holds there, e.g. "queue.get()"), compute every function from which
    a seed is reachable through resolved call edges. Returns fid ->
    witness chain ``[fid, fid, ..., seed_witness]`` (shortest-first by
    construction: BFS over the reverse graph; ties broken by sorted
    order, so chains are deterministic).

    ``edges`` overrides the graph's own resolved edges when a pass has
    already computed them (caller fid -> set of callee fids)."""
    if edges is None:
        edges = {}
        for site in graph.call_sites():
            if site.callee is not None:
                edges.setdefault(site.caller, set()).add(site.callee)
    reverse: dict[str, set[str]] = {}
    for caller in sorted(edges):
        for callee in sorted(edges[caller]):
            reverse.setdefault(callee, set()).add(caller)
    chains: dict[str, list[str]] = {
        fid: [witness] for fid, witness in sorted(seeds.items())
    }
    frontier = sorted(seeds)
    while frontier:
        next_frontier: list[str] = []
        for fid in frontier:
            for caller in sorted(reverse.get(fid, ())):
                if caller in chains:
                    continue
                chains[caller] = [fid] + chains[fid]
                next_frontier.append(caller)
        frontier = next_frontier
    return chains


def strongly_connected(edges: dict[str, set[str]]) -> list[list[str]]:
    """Tarjan SCCs over a token graph (used by the lock-order pass).
    Deterministic: nodes visited in sorted order, components returned
    sorted by their smallest member. Only components that can actually
    cycle (size > 1, or a self-edge) are returned."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]
    nodes = sorted(set(edges) | {v for vs in edges.values() for v in vs})

    def strong(v: str) -> None:
        # iterative Tarjan: recursion depth is unbounded on long chains
        work = [(v, iter(sorted(edges.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(edges.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or node in edges.get(node, ()):
                    out.append(sorted(comp))

    for v in nodes:
        if v not in index:
            strong(v)
    return sorted(out, key=lambda c: c[0])


def dotted_tail(node: ast.AST) -> str | None:
    """Like :func:`~.astutil.dotted` but tolerant of non-Name chain
    heads: returns the trailing attribute path (``"transport.send"``
    for ``self.workers[w].transport.send``), which is what suffix-based
    primitive matching wants."""
    full = dotted(node)
    if full is not None:
        return full
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    return ".".join(reversed(parts)) if parts else None


# One package-view CallGraph per analysis run: the interprocedural
# passes over `root_kind == "package"` (wire schema WC101+, MP001,
# CF001) all consume the IDENTICAL graph, and building it (plus the
# call-site walk) once per pass was a real fraction of the tier-1
# lint time budget. Keyed by the module objects' identities — safe
# because the cached graph holds the modules strongly, so their ids
# cannot be reused while the entry is alive; a new run parses new
# Module objects and misses.
_PKG_GRAPH_CACHE: tuple[tuple[int, ...], CallGraph] | None = None


def shared_package_graph(modules: list[Module]) -> CallGraph:
    global _PKG_GRAPH_CACHE
    pkg = [m for m in modules if m.root_kind == "package"]
    key = tuple(id(m) for m in pkg)
    if _PKG_GRAPH_CACHE is not None and _PKG_GRAPH_CACHE[0] == key:
        return _PKG_GRAPH_CACHE[1]
    graph = CallGraph(pkg)
    _PKG_GRAPH_CACHE = (key, graph)
    return graph


__all__ = [
    "CallGraph",
    "CallSite",
    "FuncInfo",
    "call_name",
    "dotted_tail",
    "propagate_reachability",
    "strongly_connected",
]
