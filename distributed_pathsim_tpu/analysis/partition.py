"""Partition-ownership pass: factor rows stay behind the exchange layer.

- **PT001 factor-slice-read-outside-exchange-layer**: the partitioned
  fleet's correctness rests on one invariant — a worker computes ONLY
  with factor rows it owns, and everything else arrives over the wire
  ops (``tile_pull`` / ``partial_*`` / ``set_colsum``). The raw
  held-row surface (``FACTOR_SURFACE`` in
  backends/partition_factors.py: ``c_held`` / ``slot_of`` /
  ``range_slots``) may therefore only be touched inside the exchange
  layer itself; any other package module reading those attributes is
  reaching into rows it does not own, bypassing the ownership map, the
  fencing epochs, and the wire contract at once. Mirror of WC001's
  registry style: the guarded surface is a frozenset literal the pass
  parses out of the owning module, so rule and code cannot drift.
"""

from __future__ import annotations

import ast

from .core import Finding, Module, qualname_index, symbol_at
from .wire import _frozenset_literal

RULE_DOCS = {
    "PT001": (
        "partition factor slice read outside the exchange layer",
        "only backends/partition_factors.py and serving/partition.py "
        "may touch the held-row factor surface (FACTOR_SURFACE) — "
        "anything else is reading factor rows it does not own, "
        "bypassing ownership, fencing, and the tile-exchange wire "
        "contract; go through the partition wire ops instead",
    ),
}

_SURFACE_FILE = "backends/partition_factors.py"
# the exchange layer: the slice builder and the partition worker that
# serves the wire ops over it
_ALLOWED = frozenset({
    "backends/partition_factors.py",
    "serving/partition.py",
})


class PartitionOwnershipPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        surface = None
        for m in modules:
            if m.root_kind == "package" and m.rel == _SURFACE_FILE:
                surface = _frozenset_literal(m.tree, "FACTOR_SURFACE")
                break
        if not surface:
            return findings  # no partition layer in this tree
        for m in modules:
            if m.root_kind != "package" or m.rel in _ALLOWED:
                continue
            index = None
            for node in m.nodes:
                if (
                    isinstance(node, ast.Attribute)
                    and node.attr in surface
                ):
                    if index is None:
                        index = qualname_index(m.tree)
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="PT001",
                        symbol=symbol_at(index, node.lineno),
                        message=(
                            f".{node.attr} read outside the partition "
                            "exchange layer — this is factor-row state "
                            "the module does not own; use the wire ops "
                            "(tile_pull / partial_* / set_colsum)"
                        ),
                    ))
        return findings
