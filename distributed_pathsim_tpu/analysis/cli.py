"""``dpathsim lint``: run the unified analyzer, exit nonzero on findings.

Usage::

    dpathsim lint                     # all rules, baseline applied
    dpathsim lint --rules LD001,LD002 # one pass's rules only
    dpathsim lint --json              # stable sorted JSON (diffable)
    dpathsim lint --no-baseline       # raw findings, suppressions off
    dpathsim lint --list-rules        # the rule catalog

Exit codes: 0 clean (baseline-suppressed findings don't fail), 1 any
non-baselined finding (including expired/stale baseline entries), 2
usage errors.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dpathsim lint",
        description="unified invariant-checking static analysis "
        "(recompile-safety, lock-discipline, determinism, "
        "wire-contract; DESIGN.md §25)",
    )
    p.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="machine-readable output: sorted findings, sorted keys — "
        "byte-stable across runs for diffing",
    )
    p.add_argument(
        "--baseline", default=None,
        help="baseline/suppression file "
        "(default: distributed_pathsim_tpu/analysis/baseline.json)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    return p


def lint_main(argv: list[str] | None = None) -> int:
    from .core import (
        load_baseline,
        render_human,
        render_json,
        run_analysis,
    )
    from .registry import RULES

    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rid in sorted(RULES):
            doc = RULES[rid]
            print(f"{rid}  [{doc.pass_name}] {doc.title}")
        return 0
    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES) - {"BASELINE"}
        if unknown:
            print(
                f"error: unknown rule(s) {sorted(unknown)}; see "
                "--list-rules", file=sys.stderr,
            )
            return 2
    baseline = None if args.no_baseline else load_baseline(args.baseline)
    if baseline is not None and rules is not None:
        # a rule filter must not turn the other rules' suppressions
        # into "stale entry" findings
        baseline = [e for e in baseline if e.get("rule") in rules]
    result = run_analysis(rules=rules, baseline=baseline)
    if args.json:
        print(render_json(result))
    else:
        print(render_human(result))
    return 1 if result["findings"] else 0
