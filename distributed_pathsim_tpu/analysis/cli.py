"""``dpathsim lint``: run the unified analyzer, exit nonzero on findings.

Usage::

    dpathsim lint                     # all rules, baseline applied
    dpathsim lint --rules LD101,LD102 # one family's rules only
    dpathsim lint --json              # stable sorted JSON (diffable)
    dpathsim lint --sarif PATH        # SARIF 2.1.0 for CI annotations
    dpathsim lint --write-wire-schema # regenerate artifacts/wire_schema.json
    dpathsim lint --no-baseline       # raw findings, suppressions off
    dpathsim lint --no-cache          # skip the parse/mtime cache
    dpathsim lint --list-rules        # the rule catalog, by family

Exit codes: 0 clean (baseline-suppressed findings don't fail), 1 any
non-baselined finding (including expired/stale baseline entries), 2
usage errors.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dpathsim lint",
        description="unified invariant-checking static analysis "
        "(recompile-safety, lock-discipline + interprocedural "
        "lock-order, determinism, wire-contract + wire-schema gate, "
        "exception-safety; DESIGN.md §25/§27)",
    )
    p.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="machine-readable output: sorted findings, sorted keys — "
        "byte-stable across runs for diffing",
    )
    p.add_argument(
        "--sarif", default=None, metavar="PATH",
        help="also write a SARIF 2.1.0 report (byte-stable; baselined "
        "findings ride along as suppressed results)",
    )
    p.add_argument(
        "--baseline", default=None,
        help="baseline/suppression file "
        "(default: distributed_pathsim_tpu/analysis/baseline.json)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding",
    )
    p.add_argument(
        "--no-cache", action="store_true",
        help="bypass the parse/mtime cache (.lint_cache/)",
    )
    p.add_argument(
        "--write-wire-schema", action="store_true",
        help="regenerate artifacts/wire_schema.json from the inferred "
        "wire contract and exit (commit the diff)",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog grouped by family and exit",
    )
    return p


def _list_rules() -> None:
    from .registry import ALL_PASSES, PASS_FAMILIES, RULES

    for p in ALL_PASSES:
        name = type(p).__name__
        family = PASS_FAMILIES.get(name, name)
        rids = sorted(p.rules)
        print(f"{family}:")
        for rid in rids:
            print(f"  {rid}  {RULES[rid].title}")
    print(
        "\nrun `dpathsim lint --rules <ids>` for one subset; every "
        "rule's rationale is in the human report's `->` lines"
    )


def lint_main(argv: list[str] | None = None) -> int:
    from .cache import load_modules_cached
    from .core import (
        load_baseline,
        load_modules,
        render_human,
        render_json,
        run_analysis,
    )
    from .registry import RULES

    args = build_parser().parse_args(argv)
    if args.list_rules:
        _list_rules()
        return 0
    if args.write_wire_schema:
        return _write_wire_schema(args)
    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES) - {"BASELINE"}
        if unknown:
            print(
                f"error: unknown rule(s) {sorted(unknown)}; see "
                "--list-rules", file=sys.stderr,
            )
            return 2
    baseline = None if args.no_baseline else load_baseline(args.baseline)
    if baseline is not None and rules is not None:
        # a rule filter must not turn the other rules' suppressions
        # into "stale entry" findings
        baseline = [e for e in baseline if e.get("rule") in rules]
    if args.no_cache:
        from .core import default_roots

        modules = load_modules(default_roots())
    else:
        modules = load_modules_cached()
    result = run_analysis(rules=rules, baseline=baseline, modules=modules)
    if args.sarif:
        from .sarif import render_sarif

        with open(args.sarif, "w", encoding="utf-8") as f:
            f.write(render_sarif(result))
    if args.json:
        print(render_json(result))
    else:
        print(render_human(result))
    return 1 if result["findings"] else 0


def _write_wire_schema(args) -> int:
    from .cache import load_modules_cached
    from .core import default_roots, load_modules
    from .wireschema import infer_schema, render_schema, schema_path_for

    modules = (
        load_modules(default_roots()) if args.no_cache
        else load_modules_cached()
    )
    schema = infer_schema(modules)
    if schema is None:
        print(
            "error: no serving/protocol.py with PROTOCOL_OPS in the "
            "analyzed tree", file=sys.stderr,
        )
        return 2
    path = schema_path_for(modules)
    if path is None:
        print("error: cannot locate artifacts/", file=sys.stderr)
        return 2
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(render_schema(schema), encoding="utf-8")
    ops = len(schema["ops"])
    print(f"wrote {path} ({ops} ops)")
    return 0
