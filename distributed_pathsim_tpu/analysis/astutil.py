"""Small shared AST helpers for the analysis passes."""

from __future__ import annotations

import ast


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str | None:
    """Dotted name of a call's callee, else None."""
    return dotted(node.func)


def self_attr(node: ast.AST) -> str | None:
    """``X`` when ``node`` is exactly ``self.X``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def is_print_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "print"
    )


def print_stream(node: ast.Call) -> str:
    """'stdout' | 'stderr' | 'other' for a print() call's file= target."""
    for kw in node.keywords:
        if kw.arg == "file":
            name = dotted(kw.value)
            if name == "sys.stderr":
                return "stderr"
            if name == "sys.stdout":
                return "stdout"
            return "other"
    return "stdout"


def walk_functions(tree: ast.Module):
    """Yield every (qualname, FunctionDef) in the module, nested defs
    and methods included."""

    def visit(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = f"{prefix}.{child.name}" if prefix else child.name
                yield name, child
                yield from visit(child, name)
            elif isinstance(child, ast.ClassDef):
                name = f"{prefix}.{child.name}" if prefix else child.name
                yield from visit(child, name)
            else:
                yield from visit(child, prefix)

    yield from visit(tree, "")


def own_nodes(fn: ast.AST):
    """Walk a function's OWN body: descendants excluding nested
    function/lambda bodies (each nested def is analyzed in its own
    right by walk_functions, with its own context)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def jit_decorated(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    """True for ``@jax.jit``, ``@jit``, and
    ``@functools.partial(jax.jit, ...)`` / ``@partial(jax.jit, ...)``."""
    for dec in fn.decorator_list:
        name = dotted(dec)
        if name in ("jax.jit", "jit"):
            return True
        if isinstance(dec, ast.Call):
            cn = call_name(dec)
            if cn in ("functools.partial", "partial") and dec.args:
                if dotted(dec.args[0]) in ("jax.jit", "jit"):
                    return True
            if cn in ("jax.jit", "jit"):
                return True
    return False


def static_argnames(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[str]:
    """The static_argnames string list of a jit decorator, if spelled
    as literals."""
    out: list[str] = []
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        cn = call_name(dec)
        args = dec.keywords
        if cn in ("functools.partial", "partial") and dec.args:
            if dotted(dec.args[0]) not in ("jax.jit", "jit"):
                continue
        elif cn not in ("jax.jit", "jit"):
            continue
        for kw in args:
            if kw.arg != "static_argnames":
                continue
            value = kw.value
            elts = (
                value.elts
                if isinstance(value, (ast.Tuple, ast.List, ast.Set))
                else [value]
            )
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.append(e.value)
    return out
