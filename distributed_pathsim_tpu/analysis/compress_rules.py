"""Compressed-layout pass: packed factors stay behind the factory.

- **CF001 packed-layout-reached-outside-the-factory**: the compressed
  factor layouts (ops/packed.py, DESIGN.md §29) store column ids in a
  hub-first PERMUTED coordinate system, weights in narrow chunk-local
  dtypes, and rows in a derived hub-first layout order. Those internals
  are only meaningful through the sanctioned factory surface
  (``SANCTIONED_FACTORY``), whose accessors invert the permutations and
  widen the dtypes at every return — a module that reaches the
  constructors/accessors around the factory, or reads the raw layout
  attributes (``PACKED_SURFACE``), is consuming permuted-space ids as
  if they were global columns: exactly the silent bit-parity corruption
  the boundary exists to prevent. This is ROADMAP item 4's
  interprocedural hook (PR 12, DESIGN.md §27): seed every function the
  packed module defines OUTSIDE the factory set, cut the call graph at
  the factory doorway (edges into ``SANCTIONED_FACTORY`` functions of
  ops/packed.py are removed — going through the doorway IS the
  sanctioned path), run ``callgraph.propagate_reachability``, and flag
  every package function outside the factor modules from which a seed
  is still reachable; the PT001-style attribute scan covers the
  data-read half of the surface. Both registries are frozenset literals
  parsed out of ops/packed.py (the WC001 pattern), so the rule and the
  code cannot drift.
"""

from __future__ import annotations

import ast

from .callgraph import propagate_reachability, shared_package_graph
from .core import Finding, Module, qualname_index, symbol_at
from .wire import _frozenset_literal

RULE_DOCS = {
    "CF001": (
        "packed factor layout reached outside the sanctioned factory",
        "compressed factor internals (ops/packed.py) speak a permuted, "
        "narrow-dtype coordinate system; only the SANCTIONED_FACTORY "
        "surface inverts it. Reaching the constructors/accessors "
        "around the factory — or reading PACKED_SURFACE attributes — "
        "consumes permuted ids as global ones and silently breaks the "
        "bit-parity contract; go through ops/packed.py "
        "(make_factor / as_coo / row_slice / patch_factor / "
        "factor_bytes …) instead",
    ),
}

_PACKED = "ops/packed.py"
# The factor modules: packed itself, the tiled half-chain host that
# feeds device scatters (ops/sparse.py), and the partition slice
# builder — their internals may compose the layouts freely; the
# boundary is the module surface (same shape as PT001/MP001).
_ALLOWED = frozenset({
    "ops/packed.py",
    "ops/sparse.py",
    "backends/partition_factors.py",
})


class CompressedLayoutPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        pkg = [m for m in modules if m.root_kind == "package"]
        surface = factory = None
        for m in pkg:
            if m.rel == _PACKED:
                surface = _frozenset_literal(m.tree, "PACKED_SURFACE")
                factory = _frozenset_literal(m.tree, "SANCTIONED_FACTORY")
                break
        if not surface or not factory:
            return []  # no packed layer in this tree (fixture corpora)
        findings: list[Finding] = []
        # (a) PT001-style attribute guard: raw layout state read
        # outside the factor modules.
        for m in pkg:
            if m.rel in _ALLOWED:
                continue
            index = None
            for node in m.nodes:
                if (
                    isinstance(node, ast.Attribute)
                    and node.attr in surface
                ):
                    if index is None:
                        index = qualname_index(m.tree)
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="CF001",
                        symbol=symbol_at(index, node.lineno),
                        message=(
                            f".{node.attr} read outside the factor "
                            "modules — raw packed-layout state in a "
                            "permuted coordinate system; go through "
                            "the ops/packed.py factory surface"
                        ),
                    ))
        # (b) MP001-style reachability: seeds are every function the
        # packed module defines outside the factory set (private
        # encoders/decoders, PackedFactor methods); the doorway cut
        # removes edges into factory functions BEFORE propagation, so
        # "reaches a seed" means "reaches it around the factory".
        graph = shared_package_graph(modules)
        seeds: dict[str, str] = {}
        for fid in sorted(graph.by_fid):
            fn = graph.by_fid[fid]
            if fn.module.rel != _PACKED:
                continue
            if fn.qual.split(".", 1)[0] in factory:
                continue
            seeds[fid] = f"packed.{fn.qual}()"
        if not seeds:
            return findings
        edges: dict[str, set[str]] = {}
        for site in graph.call_sites():
            if site.callee is None:
                continue
            callee = graph.by_fid[site.callee]
            if (
                callee.module.rel == _PACKED
                and callee.qual.split(".", 1)[0] in factory
            ):
                continue
            edges.setdefault(site.caller, set()).add(site.callee)
        chains = propagate_reachability(graph, seeds, edges=edges)
        for fid in sorted(chains):
            fn = graph.by_fid.get(fid)
            if fn is None or fn.module.rel in _ALLOWED:
                continue
            witness = " -> ".join(chains[fid])
            findings.append(Finding(
                path=fn.module.repo_rel,
                line=fn.node.lineno,
                rule="CF001",
                symbol=fn.qual,
                message=(
                    f"reaches a packed-layout constructor/accessor "
                    f"without going through the sanctioned factory "
                    f"({witness}); use ops/packed.py (make_factor / "
                    "as_coo / row_slice / patch_factor) instead"
                ),
            ))
        return findings
