"""Batch-doorway pass: block-sweep scoring stays behind the campaign.

- **BT001 block-sweep-reached-outside-the-batch-doorway**: the batch
  engine's block primitives (``sweep_topk_block`` / ``sweep_scores_block``
  / ``sweep_pair_block``, batch/campaign.py, DESIGN.md §31) compute
  correct bytes anywhere — but only the campaign runners wrap them in
  the checkpoint manifest (content-addressed on the graph identity),
  the stale-graph fence, the preemption checks, and the batch metrics.
  A module that calls a sweep primitive directly produces results no
  manifest owns: un-resumable after SIGTERM, un-fenced against a delta
  landing mid-sweep, and invisible to the campaign progress gauges.
  The surface registry is a frozenset literal parsed out of
  batch/campaign.py (the CF001/CP001 pattern), so the rule and the
  code cannot drift; batch/simjoin.py is the one sanctioned caller
  outside the engine module itself.
"""

from __future__ import annotations

import ast

from .core import Finding, Module, qualname_index, symbol_at
from .wire import _frozenset_literal

RULE_DOCS = {
    "BT001": (
        "block-sweep scoring reached outside the batch doorway",
        "the sweep primitives are only resumable/fenced/metered inside "
        "a campaign runner (run_topk_campaign / run_simjoin_campaign); "
        "calling them elsewhere yields results no checkpoint manifest "
        "owns and no stale-graph fence protects — run a campaign, or "
        "dispatch the 'batch_blocks' protocol op",
    ),
}

_ENGINE = "batch/campaign.py"
# the sanctioned callers: the engine module and the simjoin runner
_ALLOWED = frozenset({
    "batch/campaign.py",
    "batch/simjoin.py",
})


class BatchDoorwayPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        pkg = [m for m in modules if m.root_kind == "package"]
        surface = None
        for m in pkg:
            if m.rel == _ENGINE:
                surface = _frozenset_literal(m.tree, "BATCH_SURFACE")
                break
        if not surface:
            return []  # no batch tier in this tree (fixture corpora)
        findings: list[Finding] = []
        for m in pkg:
            if m.rel in _ALLOWED:
                continue
            index = None
            for node in m.nodes:
                if (
                    isinstance(node, ast.Attribute)
                    and node.attr in surface
                ):
                    if index is None:
                        index = qualname_index(m.tree)
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="BT001",
                        symbol=symbol_at(index, node.lineno),
                        message=(
                            f".{node.attr} reached outside the batch "
                            "doorway — sweep results are only "
                            "checkpointed, fenced, and metered inside "
                            "a campaign runner; use run_topk_campaign/"
                            "run_simjoin_campaign (or the "
                            "'batch_blocks' protocol op)"
                        ),
                    ))
        return findings
