"""Determinism pass: bit-identical answers need order-identical inputs.

- **DT001 unordered-iteration**: iterating a provably set-typed
  expression (set literal / comprehension, ``set(...)``/
  ``frozenset(...)`` call, or a local assigned from one) without
  ``sorted(...)`` inside a function that feeds a fingerprint, a digest,
  or the wire (calls ``hashlib``/``json.dumps``, or is named like
  ``*fingerprint*``/``*digest*``/``*to_wire*``/``*serialize*``). Python
  set order varies with PYTHONHASHSEED and insertion history, so the
  same graph could hash or serialize differently across processes.
- **DT002 selection-outside-primitives**: score selection/tie-break
  (``np.argsort``/``lexsort``/``argpartition``/``partition``) in
  ``serving/``/``router/`` code instead of the shared
  ``ops/pathsim`` primitives — the one place the (descending score,
  ascending column) oracle order is implemented; a local reimplementation
  is how tie order silently forks. Also flags float32 casts inside
  functions that call the f64 ``pathsim.score_*`` primitives.
- **DT003 wall-clock**: ``time.time()`` outside the two sanctioned
  sites (migrated from scripts/lint_telemetry.py R1) — wall time steps
  under NTP, so durations/orderings must use perf_counter/monotonic.
- **DT004 unseeded-rng**: module-global RNG state (``random.<fn>()``,
  legacy ``np.random.<fn>()``) or ``np.random.default_rng()`` with no
  seed in package code — deterministic paths take an explicit seed.
"""

from __future__ import annotations

import ast

from .astutil import call_name, own_nodes, walk_functions
from .core import Finding, Module, qualname_index, symbol_at

RULE_DOCS = {
    "DT001": (
        "unordered set iteration into a fingerprint/wire payload",
        "set iteration order varies per process (hash seed, insertion "
        "history); wrap the iterable in sorted(...) so fingerprints and "
        "wire payloads are order-identical fleet-wide",
    ),
    "DT002": (
        "score selection outside the ops/pathsim primitives",
        "top-k/tie order must come from the shared f64 primitives "
        "(pathsim.topk_from_score_rows / topk_from_candidate_scores); "
        "a local argsort/partition (or an f32 cast in an f64 scoring "
        "path) forks the bit-exact contract",
    ),
    "DT003": (
        "wall-clock time.time() in library code",
        "time.time() is wall clock — durations/ordering must use "
        "perf_counter/monotonic; stamp events via "
        "utils.logging.timestamps() (sanctioned: utils/logging.py, "
        "obs/trace.py's wall anchor)",
    ),
    "DT004": (
        "unseeded / global-state RNG in package code",
        "deterministic paths take an explicit seed: use "
        "np.random.default_rng(seed) or random.Random(seed), never the "
        "module-global RNG",
    ),
}

_WALLCLOCK_ALLOWED = frozenset({"utils/logging.py", "obs/trace.py"})
_CONTEXT_NAME_TOKENS = ("fingerprint", "digest", "to_wire", "serialize")
_HASH_SINKS = ("hashlib.", "json.dumps")
_SELECTION_CALLS = frozenset({
    "np.argsort", "np.lexsort", "np.argpartition", "np.partition",
    "numpy.argsort", "numpy.lexsort", "numpy.argpartition",
    "numpy.partition", "jnp.argsort", "jnp.lexsort",
})
_LEGACY_NP_RANDOM = frozenset({
    "seed", "rand", "randn", "randint", "random", "choice", "shuffle",
    "permutation", "standard_normal", "uniform", "normal",
})
_GLOBAL_RANDOM_FNS = frozenset({
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "getrandbits", "seed", "betavariate",
})


def _is_set_expr(node: ast.AST, set_locals: set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and call_name(node) in ("set", "frozenset"):
        return True
    if isinstance(node, ast.Name) and node.id in set_locals:
        return True
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.BitAnd, ast.Sub)
    ):
        return _is_set_expr(node.left, set_locals) or _is_set_expr(
            node.right, set_locals
        )
    return False


def _set_locals(fn: ast.AST) -> set[str]:
    """Names assigned from a provably-set expression in this function."""
    out: set[str] = set()
    for _ in range(2):  # one extra sweep: set-from-set assignments
        for node in own_nodes(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and _is_set_expr(node.value, out):
                    out.add(t.id)
    return out


def _is_context_fn(name: str, fn: ast.AST) -> bool:
    short = name.rsplit(".", 1)[-1].lower()
    if any(tok in short for tok in _CONTEXT_NAME_TOKENS):
        return True
    for node in own_nodes(fn):
        if isinstance(node, ast.Call):
            cn = call_name(node) or ""
            if cn == "json.dumps" or cn.startswith("hashlib."):
                return True
    return False


def _iterated_exprs(fn: ast.AST):
    """(node, iterable) pairs whose iteration order becomes output
    order: for loops, comprehension generators, and list/tuple/join
    materializations."""
    for node in own_nodes(fn):
        if isinstance(node, ast.For):
            yield node, node.iter
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                yield node, gen.iter
        elif isinstance(node, ast.Call):
            cn = call_name(node)
            if cn in ("list", "tuple") and node.args:
                yield node, node.args[0]
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and node.args
            ):
                yield node, node.args[0]


class DeterminismPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        for m in modules:
            if m.root_kind != "package":
                continue
            self._dt001(m, findings)
            self._dt002(m, findings)
            self._dt003(m, findings)
            self._dt004(m, findings)
        return findings

    def _dt001(self, m: Module, findings: list[Finding]) -> None:
        for qual, fn in walk_functions(m.tree):
            if not _is_context_fn(qual, fn):
                continue
            set_locals = _set_locals(fn)
            for node, it in _iterated_exprs(fn):
                if _is_set_expr(it, set_locals):
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="DT001",
                        symbol=qual,
                        message=(
                            "iteration over a set feeds a fingerprint/"
                            "wire payload — wrap it in sorted(...)"
                        ),
                    ))

    def _dt002(self, m: Module, findings: list[Finding]) -> None:
        in_scope = m.rel.startswith(("serving/", "router/"))
        for qual, fn in walk_functions(m.tree):
            calls_pathsim = any(
                isinstance(n, ast.Call)
                and (call_name(n) or "").startswith("pathsim.score")
                for n in own_nodes(fn)
            )
            for node in own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node) or ""
                if in_scope and cn in _SELECTION_CALLS:
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="DT002",
                        symbol=qual,
                        message=(
                            f"{cn}() reimplements score selection — use "
                            "the shared ops/pathsim top-k primitives "
                            "(oracle tie order lives there)"
                        ),
                    ))
                elif calls_pathsim and cn in (
                    "np.float32", "jnp.float32", "numpy.float32"
                ):
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="DT002",
                        symbol=qual,
                        message=(
                            "float32 cast inside an f64 scoring path — "
                            "the pathsim primitives are f64 end to end"
                        ),
                    ))

    def _dt003(self, m: Module, findings: list[Finding]) -> None:
        if m.rel in _WALLCLOCK_ALLOWED:
            return
        index = None
        for node in m.nodes:
            if isinstance(node, ast.Call) and call_name(node) == "time.time":
                if index is None:
                    index = qualname_index(m.tree)
                findings.append(Finding(
                    path=m.repo_rel, line=node.lineno, rule="DT003",
                    symbol=symbol_at(index, node.lineno),
                    message=(
                        "time.time() — durations/ordering use "
                        "perf_counter/monotonic; events go through "
                        "utils.logging.timestamps()"
                    ),
                ))

    def _dt004(self, m: Module, findings: list[Finding]) -> None:
        index = None
        for node in m.nodes:
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node) or ""
            bad = None
            if cn in ("np.random.default_rng", "numpy.random.default_rng"):
                if not node.args and not node.keywords:
                    bad = f"{cn}() without a seed"
            elif cn.startswith(("np.random.", "numpy.random.")):
                if cn.rsplit(".", 1)[-1] in _LEGACY_NP_RANDOM:
                    bad = f"{cn}() uses numpy's global RNG state"
            elif cn.startswith("random."):
                if cn.rsplit(".", 1)[-1] in _GLOBAL_RANDOM_FNS:
                    bad = f"{cn}() uses the module-global RNG"
            if bad is not None:
                if index is None:
                    index = qualname_index(m.tree)
                findings.append(Finding(
                    path=m.repo_rel, line=node.lineno, rule="DT004",
                    symbol=symbol_at(index, node.lineno),
                    message=f"{bad} — pass an explicit seed",
                ))
