"""Exception-safety / exactly-once resource passes (EX001s).

The partition tier's pending-table/fencing state machine (DESIGN.md
§26) must resolve every acquisition exactly once — including on
exception edges, where "we'll clean it up two statements later" is a
leak. Three rules, all CFG-lite (lexical regions + try/finally
awareness, no path enumeration):

- **EX001 bare lock acquire**: ``lock.acquire()`` whose matching
  ``release()`` is not guaranteed on exception exits — not inside a
  ``try`` whose ``finally`` releases it. ``with lock:`` is always the
  answer; an explicit acquire is only tolerated release-in-finally.
- **EX002 leaked resource handle**: a locally-bound ``open(...)`` /
  ``subprocess.Popen(...)`` / ``os.fdopen(...)`` that is neither
  ``with``-managed nor closed in a ``finally`` — on the exception
  path the fd/child outlives the function. Handles that *escape*
  (stored on ``self``, returned, passed to another call) transfer
  ownership and are exempt: their lifetime is someone else's contract.
- **EX003 registration not exception-safe**: a function that both
  inserts into and removes from the same ``self.<table>`` (the
  pending-table / collector pattern) where a statement that can raise
  sits between the insert and a removal that is not in a covering
  ``finally`` — the exception skips the removal and the entry leaks
  forever (a pending entry that never resolves IS a hung client).
  Long-lived registrations resolved by a *different* function
  (callback-resolved pending tables) are out of scope by construction:
  the rule only fires when the same function owns both ends.
"""

from __future__ import annotations

import ast

from .astutil import call_name, own_nodes as _own_nodes, walk_functions
from .core import Finding, Module, qualname_index, symbol_at

RULE_DOCS = {
    "EX001": (
        "lock.acquire() without a guaranteed release",
        "an exception between acquire and release leaves the lock held "
        "forever — every later taker deadlocks; use `with lock:` (or "
        "release in a `finally`)",
    ),
    "EX002": (
        "resource handle leaked on exception paths",
        "a locally-opened file/process that isn't with-managed or "
        "closed in a finally outlives the function when an exception "
        "fires — fds and zombie children accumulate; use `with` (or "
        "close/kill in a `finally`)",
    ),
    "EX003": (
        "registration not removed on exception paths",
        "this function inserts into and removes from the same table, "
        "but an exception between the two skips the removal — the "
        "entry (a pending request, a collector) leaks and its waiter "
        "hangs forever; move the removal into a `finally`",
    ),
}

_OPENERS = frozenset({"open", "os.fdopen", "subprocess.Popen"})
_CLOSERS = frozenset({
    "close", "wait", "kill", "terminate", "release", "__exit__",
})
_REMOVERS = frozenset({"pop", "discard", "remove", "popitem", "clear"})


def _key_print(node: ast.AST) -> str | None:
    """A stable fingerprint for a table key expression: Name identity
    or constant value. Computed keys (slices, calls) return None —
    pairing them would be guesswork."""
    if isinstance(node, ast.Name):
        return f"n:{node.id}"
    if isinstance(node, ast.Constant):
        return f"c:{node.value!r}"
    return None


def _self_table(node: ast.AST) -> str | None:
    """``X`` when node is ``self.X`` (the table attribute)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _receiver_name(node: ast.AST) -> str | None:
    """Identity of a lock/handle receiver: bare name or self-attr."""
    if isinstance(node, ast.Name):
        return node.id
    t = _self_table(node)
    return f"self.{t}" if t is not None else None


def _stmts_between(fn: ast.AST, lo: int, hi: int,
                   kinds=ast.Call) -> list[ast.AST]:
    """Nodes of the given kinds strictly between two line bounds,
    nested defs excluded (they don't run here)."""
    out = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        ln = getattr(node, "lineno", None)
        if ln is not None and lo < ln < hi and isinstance(node, kinds):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _finally_blocks(fn: ast.AST) -> list[tuple[ast.Try, int, int]]:
    """(try-node, body-start-line, body-end-line) for every try with a
    finalbody, nested defs excluded."""
    out = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Try) and node.finalbody:
            end = max(
                getattr(s, "end_lineno", s.lineno) for s in node.body
            )
            out.append((node, node.body[0].lineno, end))
        stack.extend(ast.iter_child_nodes(node))
    return out


def _calls_in(nodes: list[ast.AST]) -> list[ast.Call]:
    out = []
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Call):
                out.append(sub)
    return out


class ExceptionSafetyPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        for module in modules:
            if module.root_kind == "tests":
                continue
            index = qualname_index(module.tree)
            for qual, fn in walk_functions(module.tree):
                finals = _finally_blocks(fn)
                self._ex001(module, index, fn, finals, findings)
                self._ex002(module, index, fn, finals, findings)
                self._ex003(module, index, fn, finals, findings)
        return findings

    # -- EX001: bare acquire -----------------------------------------------

    def _ex001(self, module, index, fn, finals, findings) -> None:
        for node in _own_nodes(fn):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
            ):
                continue
            recv = _receiver_name(node.func.value)
            if recv is None:
                continue
            if self._released_in_finally(fn, node, recv, finals):
                continue
            findings.append(Finding(
                path=module.repo_rel, line=node.lineno, rule="EX001",
                symbol=symbol_at(index, node.lineno),
                message=(
                    f"{recv}.acquire() without release guaranteed in a "
                    "finally — an exception leaves the lock held; use "
                    f"`with {recv}:`"
                ),
            ))

    def _released_in_finally(self, fn, node, recv, finals) -> bool:
        """Is there a try/finally whose finalbody calls
        ``recv.release()`` and whose body covers the acquisition — or
        that starts right after it with nothing raising in between?"""
        for t, lo, hi in finals:
            if not any(
                isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr == "release"
                and _receiver_name(c.func.value) == recv
                for fb in t.finalbody
                for c in ast.walk(fb)
            ):
                continue
            if lo <= node.lineno <= hi:
                return True  # acquired inside the protected body
            if node.lineno < lo:
                # acquire-then-try: safe when no call between the
                # acquire and the protected region can raise
                end = getattr(node, "end_lineno", node.lineno)
                if not _calls_in(_stmts_between(fn, end, lo)):
                    return True
        return False

    # -- EX002: leaked handles ---------------------------------------------

    def _ex002(self, module, index, fn, finals, findings) -> None:
        for node in _own_nodes(fn):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            cn = call_name(node.value)
            if cn not in _OPENERS:
                continue
            name = node.targets[0].id
            if self._escapes_or_closed(fn, node, name, finals):
                continue
            findings.append(Finding(
                path=module.repo_rel, line=node.lineno, rule="EX002",
                symbol=symbol_at(index, node.lineno),
                message=(
                    f"{cn}() bound to {name!r} is neither with-managed "
                    "nor closed in a finally — the handle leaks when an "
                    "exception fires"
                ),
            ))

    def _escapes_or_closed(self, fn, assign, name, finals) -> bool:
        after = assign.lineno
        closed_plain = False
        for node in _own_nodes(fn):
            ln = getattr(node, "lineno", 0)
            if ln <= after:
                continue
            # with-managed later: `with x:` / contextlib.closing(x)
            if isinstance(node, ast.With):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Name) and sub.id == name:
                            return True
            if isinstance(node, ast.Return) and node.value is not None:
                if any(
                    isinstance(s, ast.Name) and s.id == name
                    for s in ast.walk(node.value)
                ):
                    return True  # ownership transferred to the caller
            if isinstance(node, ast.Assign):
                if any(
                    isinstance(s, ast.Name) and s.id == name
                    for t in node.targets for s in ast.walk(t)
                ) or (
                    _self_table(node.targets[0]) is not None
                    and any(
                        isinstance(s, ast.Name) and s.id == name
                        for s in ast.walk(node.value)
                    )
                ):
                    return True  # stored: lifetime managed elsewhere
            if isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _CLOSERS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == name
                ):
                    if self._in_a_finally(fn, node, finals):
                        return True
                    closed_plain = True
                    continue
        if closed_plain:
            # closed, but only on the happy path: safe only when
            # nothing between open and close can raise
            closes = [
                n for n in ast.walk(fn)
                if isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _CLOSERS
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == name
            ]
            last = max(c.lineno for c in closes)
            between = [
                c for c in _stmts_between(fn, assign.lineno, last)
                if not (
                    isinstance(c.func, ast.Attribute)
                    and isinstance(c.func.value, ast.Name)
                    and c.func.value.id == name
                )
            ]
            return not between
        return False

    def _in_a_finally(self, fn, node, finals) -> bool:
        for t, _lo, _hi in finals:
            for fb in t.finalbody:
                for sub in ast.walk(fb):
                    if sub is node:
                        return True
        return False

    # -- EX003: exactly-once registrations ---------------------------------

    def _ex003(self, module, index, fn, finals, findings) -> None:
        # (table attr, key fingerprint) -> nodes. The key must match
        # between insert and removal: `pop(token)` pairs with
        # `self.X[token] = v`, while `popitem()` / `pop(oldest)` is
        # LRU *eviction* of some other entry — not this entry's
        # removal, and eviction-only tables (caches, dedup rings) are
        # exactly the ones whose entries are SUPPOSED to outlive the
        # inserting call.
        inserts: dict[tuple[str, str], list[ast.AST]] = {}
        removals: dict[tuple[str, str], list[ast.AST]] = {}
        for node in _own_nodes(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        table = _self_table(t.value)
                        key = _key_print(t.slice)
                        if table is not None and key is not None:
                            inserts.setdefault(
                                (table, key), []
                            ).append(node)
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        table = _self_table(t.value)
                        key = _key_print(t.slice)
                        if table is not None and key is not None:
                            removals.setdefault(
                                (table, key), []
                            ).append(node)
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REMOVERS
                and node.args
            ):
                table = _self_table(node.func.value)
                key = _key_print(node.args[0])
                if table is not None and key is not None:
                    removals.setdefault((table, key), []).append(node)
        # `self._cache[i] = self._cache.pop(i)` (LRU refresh) embeds
        # its pop INSIDE the insert — that's one atomic move, not a
        # paired removal
        for pair in list(removals):
            removals[pair] = [
                r for r in removals[pair]
                if not any(
                    any(sub is r for sub in ast.walk(ins))
                    for ins in inserts.get(pair, ())
                )
            ]
        for pair in sorted(set(inserts) & set(removals)):
            table = pair[0]
            if not removals[pair]:
                continue
            for ins in sorted(inserts[pair], key=lambda n: n.lineno):
                if self._insert_safe(fn, ins, table, removals[pair],
                                     finals):
                    continue
                findings.append(Finding(
                    path=module.repo_rel, line=ins.lineno, rule="EX003",
                    symbol=symbol_at(index, ins.lineno),
                    message=(
                        f"self.{table} entry inserted here but the "
                        "removal below is not exception-safe — a raise "
                        "in between leaks the entry; move the removal "
                        "into a finally"
                    ),
                ))

    def _insert_safe(self, fn, ins, table, removals, finals) -> bool:
        ins_end = getattr(ins, "end_lineno", ins.lineno)
        for rem in removals:
            rem_ln = rem.lineno
            if rem_ln <= ins_end:
                continue
            # removal inside a finally whose try body starts after the
            # insert: every raising statement between insert and
            # removal must be inside that protected body
            protecting = None
            for t, lo, hi in finals:
                if any(
                    sub is rem for fb in t.finalbody
                    for sub in ast.walk(fb)
                ):
                    protecting = (t, lo, hi)
                    break
            if protecting is not None:
                t, lo, hi = protecting
                unprotected = _calls_in(
                    _stmts_between(fn, ins_end, lo)
                )
                if not unprotected:
                    return True
                continue
            # plain removal: safe only when nothing between can raise
            between = _calls_in(_stmts_between(fn, ins_end, rem_ln))
            between = [
                c for c in between
                if not (
                    isinstance(c.func, ast.Attribute)
                    and c.func.attr in _REMOVERS
                    and _self_table(c.func.value) == table
                )
            ]
            if not between:
                return True
        return False
