"""Interprocedural lock-order / blocking-under-lock pass (LD100s).

PR 10's :mod:`locks` pass answers "is this attribute touched without
the lock" *inside one class*. The partition tier (DESIGN.md §26) added
the hazards that analysis cannot see: locks held across cross-process
protocol round-trips, and lock acquisition orders spread over many
classes. This pass generalizes the held-lock story to the whole repo,
on the :mod:`callgraph` engine:

- **Held-locks-at-entry**: a *private* function whose every resolved
  call site runs with lock L held is analyzed as entering with L held
  (the interprocedural version of locks.py's intra-class fixpoint).
  Public functions enter with nothing — their external callers are
  unknown, and an unknown caller must never fabricate a fact.
- **LD101 lock-order cycle**: every acquisition of lock B while lock A
  is held is an edge A→B in the global lock-acquisition-order graph;
  a cycle is a potential deadlock (two threads walking the cycle from
  different entry points block each other forever). Re-acquiring an
  ``RLock`` you already hold is reentrant and ignored; a self-edge on
  a plain ``Lock`` is reported — that one is a guaranteed single-thread
  deadlock.
- **LD102 blocking call under a lock**: a blocking primitive
  (``queue.get()``, ``.wait()``, ``.result()``, ``.join()``,
  ``time.sleep``, ``subprocess`` waits, socket reads) — or a call that
  *transitively reaches* one — executed while a lock is held. Waiting
  on a Condition you hold is THE condition-variable pattern (the wait
  releases it) and is exempt for that lock only.
- **LD103 transport round-trip under a lock**: a worker-transport send
  (or a call reaching one — the ``_broadcast``/``tile_pull``/
  ``partial_topk`` helpers that await a protocol reply) while a lock
  is held. A pipe send can block on a stalled peer, and the reply
  arrives on a reader thread that may need the very lock the sender
  holds: this is how single-process discipline becomes a distributed
  deadlock. LD103 subsumes LD102 at the same site (one finding per
  site, the sharper rule wins).

Witness chains name the path (``f -> g -> queue.get()``) so a finding
at an outer call site is actionable without re-deriving the analysis.
"""

from __future__ import annotations

import ast

from .callgraph import (
    CallGraph,
    FuncInfo,
    dotted_tail,
    propagate_reachability,
    strongly_connected,
)
from .astutil import call_name
from .core import Finding, Module

RULE_DOCS = {
    "LD101": (
        "lock-order cycle (potential deadlock)",
        "two code paths acquire these locks in opposite orders — two "
        "threads entering from different ends block each other forever; "
        "pick one global order (or baseline a provably single-threaded "
        "pairing with a justification)",
    ),
    "LD102": (
        "blocking call while holding a lock",
        "the lock is held across a wait (queue.get/.wait/.result/"
        ".join/sleep/subprocess) — every other thread needing it stalls "
        "for the full wait, and if the waited-on work needs the lock "
        "too, forever; move the wait outside the critical section",
    ),
    "LD103": (
        "transport send / protocol round-trip while holding a lock",
        "a worker-transport send can block on a stalled peer, and its "
        "reply is delivered by a reader thread that may need this very "
        "lock — single-process lock discipline becomes a distributed "
        "deadlock; send after releasing (the repo's routers do exactly "
        "this everywhere else)",
    ),
}

_LOCK_CTORS = ("threading.Lock", "threading.RLock")
_REENTRANT_CTORS = ("threading.RLock",)
_EXEMPT_METHODS = frozenset({"__init__", "__new__", "__del__"})

# dotted-callee names that block outright
_BLOCKING_NAMES = frozenset({
    "time.sleep", "subprocess.run", "subprocess.check_call",
    "subprocess.check_output", "subprocess.call", "select.select",
    "os.read", "input",
})
# attribute methods that block when called with no positional payload
# (queue.get() blocks; dict.get(k) doesn't — the payload IS the tell;
# same for Thread.join() vs "sep".join(parts))
_BLOCKING_ZERO_ARG_ATTRS = frozenset({"get", "join"})
# attribute methods that block regardless of arguments
_BLOCKING_ATTRS = frozenset({
    "wait", "result", "communicate", "recv", "recv_into", "accept",
    "acquire_timeout",
})


class _Lock:
    __slots__ = ("token", "reentrant")

    def __init__(self, token: str, reentrant: bool):
        self.token = token
        self.reentrant = reentrant


class _ModuleLocks:
    """Lock identities visible in one module: per-class self-attr locks
    (+ Condition aliases) and module-level locks."""

    def __init__(self, module: Module):
        self.module = module
        # class qual -> {attr: _Lock}
        self.class_locks: dict[str, dict[str, _Lock]] = {}
        # class qual -> {alias attr: underlying attr}
        self.class_aliases: dict[str, dict[str, str]] = {}
        # module-level name -> _Lock
        self.globals: dict[str, _Lock] = {}
        self._scan()

    def _scan(self) -> None:
        m = self.module
        for node in m.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                cn = call_name(node.value)
                if cn in _LOCK_CTORS or (
                    cn == "threading.Condition" and not node.value.args
                ):
                    name = node.targets[0].id
                    self.globals[name] = _Lock(
                        token=f"{m.repo_rel}:{name}",
                        reentrant=cn in _REENTRANT_CTORS,
                    )

        def classes(node: ast.AST, prefix: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    qual = (
                        f"{prefix}.{child.name}" if prefix else child.name
                    )
                    yield qual, child
                    yield from classes(child, qual)
                else:
                    yield from classes(child, prefix)

        for qual, cls in classes(m.tree, ""):
            locks: dict[str, _Lock] = {}
            aliases: dict[str, str] = {}
            for node in ast.walk(cls):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                t = node.targets[0]
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                cn = call_name(node.value)
                if cn in _LOCK_CTORS:
                    locks[t.attr] = _Lock(
                        token=f"{m.repo_rel}:{qual}.{t.attr}",
                        reentrant=cn in _REENTRANT_CTORS,
                    )
                elif cn == "threading.Condition":
                    if node.value.args:
                        arg = node.value.args[0]
                        if (
                            isinstance(arg, ast.Attribute)
                            and isinstance(arg.value, ast.Name)
                            and arg.value.id == "self"
                        ):
                            aliases[t.attr] = arg.attr
                    else:
                        # a bare Condition owns its own lock
                        locks[t.attr] = _Lock(
                            token=f"{m.repo_rel}:{qual}.{t.attr}",
                            reentrant=True,
                        )
            if locks or aliases:
                self.class_locks[qual] = locks
                self.class_aliases[qual] = aliases


def _blocking_primitive(call: ast.Call) -> str | None:
    """Witness string when this call is a known blocking primitive."""
    name = call_name(call)
    if name in _BLOCKING_NAMES:
        return f"{name}()"
    if isinstance(call.func, ast.Name) and call.func.id == "input":
        return "input()"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr in _BLOCKING_ATTRS:
            return f".{attr}()"
        if attr in _BLOCKING_ZERO_ARG_ATTRS and not call.args:
            return f".{attr}()"
    return None


def _transport_send(call: ast.Call) -> bool:
    tail = dotted_tail(call.func)
    return tail is not None and (
        tail.endswith("transport.send") or tail == "transport.send"
    )


class _FnFacts:
    """What one walk of a function body produced."""

    __slots__ = ("blocking", "sends", "acquires", "calls")

    def __init__(self):
        # (node, frozenset[token], witness, receiver_token|None)
        self.blocking: list[tuple] = []
        # (node, frozenset[token])
        self.sends: list[tuple] = []
        # (node, acquired _Lock, frozenset[token held])
        self.acquires: list[tuple] = []
        # (node, callee fid, frozenset[token])
        self.calls: list[tuple] = []


class InterLockPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        analyzed = [m for m in modules if m.root_kind != "tests"]
        if not analyzed:
            return []
        graph = CallGraph(analyzed)
        locks_by_mod = {m.repo_rel: _ModuleLocks(m) for m in analyzed}
        lock_kind: dict[str, bool] = {}  # token -> reentrant
        for ml in locks_by_mod.values():
            for lk in ml.globals.values():
                lock_kind[lk.token] = lk.reentrant
            for cl in ml.class_locks.values():
                for lk in cl.values():
                    lock_kind[lk.token] = lk.reentrant

        # ONE walk per function, recording facts with the LEXICAL held
        # sets; the entry-held fixpoint then runs over the recorded
        # call sites alone (effective held at any fact = recorded ∪
        # entry[function]) — same result as re-walking to fixpoint,
        # without the O(iterations × functions) re-walks.
        facts: dict[str, _FnFacts] = {
            fid: self._walk(graph.by_fid[fid], graph, locks_by_mod,
                            frozenset())
            for fid in sorted(graph.by_fid)
        }
        sites: dict[str, list[tuple[str, frozenset[str]]]] = {}
        for fid in sorted(facts):
            for _node, callee, held in facts[fid].calls:
                sites.setdefault(callee, []).append((fid, held))
        entry: dict[str, frozenset[str]] = {
            fid: frozenset() for fid in graph.by_fid
        }
        for _ in range(len(graph.by_fid) + 1):
            changed = False
            for fid in sorted(graph.by_fid):
                fn = graph.by_fid[fid]
                if not fn.private:
                    continue
                got = sites.get(fid)
                if not got:
                    continue
                new = frozenset.intersection(*[
                    held | entry[caller] for caller, held in got
                ])
                if new != entry[fid]:
                    entry[fid] = new
                    changed = True
            if not changed:
                break

        findings: list[Finding] = []
        self._report_order_cycles(graph, facts, entry, lock_kind,
                                  findings)
        self._report_blocking(graph, facts, entry, findings)
        return findings

    # -- body walk ---------------------------------------------------------

    def _walk(
        self, fn: FuncInfo, graph: CallGraph,
        locks_by_mod: dict[str, _ModuleLocks],
        entry_held: frozenset[str],
    ) -> _FnFacts:
        ml = locks_by_mod[fn.module.repo_rel]
        cls_locks = ml.class_locks.get(fn.cls or "", {})
        cls_aliases = ml.class_aliases.get(fn.cls or "", {})
        local_types = graph.local_types(fn)
        out = _FnFacts()
        exempt = fn.name in _EXEMPT_METHODS

        def lock_of(expr: ast.AST) -> _Lock | None:
            """The lock a with-item / receiver names, if any."""
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
            ):
                attr = expr.attr
                if attr in cls_aliases:
                    attr = cls_aliases[attr]
                return cls_locks.get(attr)
            if isinstance(expr, ast.Name):
                return ml.globals.get(expr.id)
            return None

        def scan(node: ast.AST, held: frozenset[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    # a closure runs later, on whatever thread calls it
                    # — its body is NOT this function's body. Nested
                    # defs are indexed and walked as functions in their
                    # own right; absorbing their facts here would make
                    # "defines a blocking callback" read as "blocks"
                    continue
                child_held = held
                if isinstance(child, ast.With):
                    # items acquire left-to-right: item N+1 is taken
                    # with item N already held, so `with a, b:` must
                    # produce the a->b order edge exactly like the
                    # nested-with spelling
                    for item in child.items:
                        lk = lock_of(item.context_expr)
                        if lk is None:
                            continue
                        if not exempt:
                            out.acquires.append((child, lk, child_held))
                        child_held = child_held | {lk.token}
                if isinstance(child, ast.Call) and not exempt:
                    self._classify_call(
                        child, held, fn, graph, local_types,
                        lock_of, out,
                    )
                scan(child, child_held)

        scan(fn.node, entry_held)
        return out

    def _classify_call(
        self, call: ast.Call, held, fn, graph, local_types, lock_of, out,
    ) -> None:
        # explicit .acquire() is an acquisition too (order edges); the
        # release-discipline half is the EX001 rule's business
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "acquire"
        ):
            lk = lock_of(call.func.value)
            if lk is not None:
                out.acquires.append((call, lk, held))
                return
        if _transport_send(call):
            out.sends.append((call, held))
            return
        witness = _blocking_primitive(call)
        if witness is not None:
            receiver = None
            if isinstance(call.func, ast.Attribute):
                lk = lock_of(call.func.value)
                if lk is not None:
                    receiver = lk.token
            out.blocking.append((call, held, witness, receiver))
            return
        callee = graph.resolve(fn, call, local_types)
        if callee is not None:
            out.calls.append((call, callee, held))

    # -- reporting ---------------------------------------------------------

    def _report_order_cycles(self, graph, facts, entry, lock_kind,
                             findings):
        edges: dict[str, set[str]] = {}
        sites: dict[tuple[str, str], tuple] = {}  # edge -> (fid, node)
        for fid in sorted(facts):
            for node, lk, held in facts[fid].acquires:
                for h in sorted(held | entry[fid]):
                    if h == lk.token:
                        if lock_kind.get(lk.token, True):
                            continue  # RLock re-entry is fine
                    edges.setdefault(h, set()).add(lk.token)
                    sites.setdefault((h, lk.token), (fid, node))
        for comp in strongly_connected(edges):
            in_cycle = [
                (a, b) for (a, b) in sorted(sites)
                if a in comp and b in comp
            ]
            if not in_cycle:
                continue
            where = sites[in_cycle[0]]
            fn = graph.by_fid[where[0]]
            order = " -> ".join(comp + [comp[0]]) if len(comp) > 1 \
                else f"{comp[0]} -> {comp[0]}"
            at = "; ".join(
                f"{a.split(':', 1)[1]} then {b.split(':', 1)[1]} in "
                f"{sites[(a, b)][0].split(':', 1)[1]}"
                for a, b in in_cycle
            )
            findings.append(Finding(
                path=fn.module.repo_rel, line=where[1].lineno,
                rule="LD101", symbol=fn.qual,
                message=(
                    f"lock-order cycle {order} (acquisitions: {at}) — "
                    "threads entering from different ends deadlock"
                ),
            ))

    def _report_blocking(self, graph, facts, entry, findings):
        # fixpoint facts: which functions transitively block / send
        call_edges: dict[str, set[str]] = {}
        for fid in sorted(facts):
            for _node, callee, _held in facts[fid].calls:
                call_edges.setdefault(fid, set()).add(callee)
        block_seeds = {
            fid: f[0][2]
            for fid, ff in sorted(facts.items())
            if (f := ff.blocking)
        }
        send_seeds = {
            fid: "transport.send"
            for fid, ff in sorted(facts.items()) if ff.sends
        }
        may_block = propagate_reachability(
            graph, block_seeds, edges=call_edges
        )
        may_send = propagate_reachability(
            graph, send_seeds, edges=call_edges
        )

        def chain(fids: list[str]) -> str:
            return " -> ".join(
                f.split(":", 1)[1] if ":" in f else f for f in fids
            )

        for fid in sorted(facts):
            fn = graph.by_fid[fid]
            ff = facts[fid]
            at_entry = entry[fid]
            reported: set[int] = set()

            def emit(node, rule, msg):
                if id(node) in reported:
                    return
                reported.add(id(node))
                findings.append(Finding(
                    path=fn.module.repo_rel, line=node.lineno,
                    rule=rule, symbol=fn.qual, message=msg,
                ))

            for node, held in ff.sends:
                held = held | at_entry
                if held:
                    emit(node, "LD103", (
                        "transport send while holding "
                        f"{_fmt_locks(held)} — the reply arrives on a "
                        "reader thread that may need this lock"
                    ))
            for node, held, witness, receiver in ff.blocking:
                effective = set(held | at_entry)
                if receiver is not None:
                    effective.discard(receiver)  # cv.wait releases it
                if effective:
                    emit(node, "LD102", (
                        f"blocking {witness} while holding "
                        f"{_fmt_locks(effective)}"
                    ))
            for node, callee, held in ff.calls:
                held = held | at_entry
                if not held:
                    continue
                if callee in may_send:
                    emit(node, "LD103", (
                        "call reaches a transport round-trip ("
                        f"{chain([callee] + may_send[callee][:-1])} -> "
                        f"{may_send[callee][-1]}) while holding "
                        f"{_fmt_locks(held)}"
                    ))
                elif callee in may_block:
                    emit(node, "LD102", (
                        "call reaches a blocking "
                        f"{may_block[callee][-1]} (via "
                        f"{chain([callee] + may_block[callee][:-1])}) "
                        f"while holding {_fmt_locks(held)}"
                    ))


def _fmt_locks(tokens) -> str:
    return "/".join(
        t.split(":", 1)[1] if ":" in t else t for t in sorted(tokens)
    )
