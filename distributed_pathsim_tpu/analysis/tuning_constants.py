"""Tuning-constants pass: no new hardcoded tile/bucket knobs.

Migrated from scripts/lint_tuning.py, same contract: any module-level
or class-level integer (or all-integer-tuple) constant whose name
contains a tile/bucket/index-geometry token must live in
``tuning/registry.py`` or be listed in ``registry.SANCTIONED_CONSTANTS``
with its justification. Everything else is a knob trying to escape the
registry — exactly how the pre-tuning heuristics fossilized
(KERNELS_r05: the promoted 8k tile lost to XLA at 32k).

- **TN001 hardcoded-tuning-constant**.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Module

RULE_DOCS = {
    "TN001": (
        "hardcoded tile/bucket constant outside tuning/registry.py",
        "tile/bucket choices are tuning knobs: register it in "
        "tuning/registry.py (or sanction it there in "
        "SANCTIONED_CONSTANTS with a justification)",
    ),
}

_EXEMPT_PREFIXES = ("tuning/", "analysis/")
_TOKENS = {
    "TILE", "BUCKET", "LADDER", "STRIPE", "BM", "BN", "BK",
    "CAP", "CENTROID", "NPROBE",
}
_SPLIT = re.compile(r"[^A-Za-z0-9]+")


def _name_matches(name: str) -> bool:
    parts = {p.upper() for p in _SPLIT.split(name) if p}
    parts |= {p[:-1] for p in parts if p.endswith("S")}
    return bool(parts & _TOKENS)


def _is_const_int(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, int) and not isinstance(
            node.value, bool
        )
    if isinstance(node, ast.Tuple):
        return bool(node.elts) and all(_is_const_int(e) for e in node.elts)
    if isinstance(node, ast.BinOp):
        return _is_const_int(node.left) and _is_const_int(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_const_int(node.operand)
    return False


def _const_assignments(tree: ast.Module):
    scopes: list[ast.AST] = [tree]
    scopes.extend(n for n in ast.walk(tree) if isinstance(n, ast.ClassDef))
    for scope in scopes:
        for stmt in scope.body:  # type: ignore[attr-defined]
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt, value = stmt.targets[0], stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                tgt, value = stmt.target, stmt.value
            else:
                continue
            if isinstance(tgt, ast.Name) and _is_const_int(value):
                yield tgt.id, stmt.lineno


def _sanctioned() -> dict:
    from ..tuning.registry import SANCTIONED_CONSTANTS

    return SANCTIONED_CONSTANTS


def scan_modules(
    modules: list[Module], sanctioned: dict | None = None
) -> list[Finding]:
    if sanctioned is None:
        sanctioned = _sanctioned()
    findings: list[Finding] = []
    for m in modules:
        if m.root_kind != "package":
            continue
        if m.rel.startswith(_EXEMPT_PREFIXES):
            continue
        allowed = sanctioned.get(m.rel, frozenset())
        for name, line in _const_assignments(m.tree):
            if _name_matches(name) and name not in allowed:
                findings.append(Finding(
                    path=m.repo_rel, line=line, rule="TN001",
                    symbol=name,
                    message=(
                        f"hardcoded tile/bucket constant {name!r} — "
                        "register it in tuning/registry.py or sanction "
                        "it in SANCTIONED_CONSTANTS"
                    ),
                ))
    return findings


class TuningConstantsPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        return scan_modules(modules)
