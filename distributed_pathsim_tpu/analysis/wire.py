"""Wire-contract pass: the JSONL protocol stays evolvable and clean.

- **WC001 unregistered-protocol-op**: every op string
  ``serving/protocol._dispatch_op`` compares against must appear in
  ``PROTOCOL_OPS`` — the registry the request-id-echo test iterates —
  and the router's ``ROUTED_OPS`` must be a subset of it. An
  unregistered op is an op whose responses the router's retry/hedge/
  dedup machinery was never proven able to correlate. (Migrated from
  scripts/lint_telemetry.py R8.)
- **WC002 undefaulted-wire-field**: reads of request/message dict
  fields in the protocol/router layer use ``.get(...)`` (or sit under
  an explicit ``.get``/``in`` guard). A bare ``req["field"]`` turns
  yesterday's clients — which don't send the new field — into
  KeyErrors; the protocol's compat story is "new fields are defaulted".
- **WC003 raw-print-on-wire-process**: ``print()`` anywhere in
  ``router/``, ``index/``, or ``obs/`` (CLI surfaces excepted):
  these packages run inside processes whose STDOUT IS the JSONL wire —
  a stray print corrupts the protocol stream. (Migrated R5/R6/R7.)
- **WC004 raw-stream-write**: ``sys.stdout.write``/``sys.stderr.write``
  outside utils/logging.py — skips the event sink's lock (stderr) or
  corrupts the wire (stdout). (Migrated R4.)
"""

from __future__ import annotations

import ast

from .astutil import call_name, dotted, is_print_call
from .core import Finding, Module, qualname_index, symbol_at

RULE_DOCS = {
    "WC001": (
        "protocol op handled but not registered in PROTOCOL_OPS",
        "PROTOCOL_OPS is the registry the request-id-echo test "
        "iterates — register the op so router retries/hedges/dedup are "
        "proven able to correlate its responses",
    ),
    "WC002": (
        "undefaulted wire-field read",
        "wire dicts are read with .get(...) (new fields must default) "
        "— a bare subscript breaks every client that predates the "
        "field",
    ),
    "WC003": (
        "print() in a package that owns the JSONL wire",
        "router/index/obs code runs in processes whose stdout IS the "
        "wire — report through runtime_event(); protocol lines go "
        "through the loop's locked writer",
    ),
    "WC004": (
        "raw sys.stdout/sys.stderr write",
        "direct stream writes skip the event sink's lock (stderr) or "
        "corrupt the JSONL wire (stdout); use runtime_event() or the "
        "locked protocol writer",
    ),
}

_PROTOCOL_FILE = "serving/protocol.py"
_ROUTER_OPS_FILE = "router/core.py"
_WIRE_READ_PREFIXES = ("serving/protocol.py", "router/")
_WIRE_NAMES = frozenset({"req", "obj", "msg", "wire"})
_PRINT_SCOPES = {
    "router/": frozenset({"router/cli.py"}),
    "index/": frozenset({"index/cli.py"}),
    "obs/": frozenset(),
}
_STREAM_WRITE_ALLOWED = frozenset({"utils/logging.py"})


def _frozenset_literal(tree: ast.Module, name: str) -> set[str] | None:
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == name
        ):
            out: set[str] = set()
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    out.add(sub.value)
            return out
    return None


class WireContractPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        by_rel = {m.rel: m for m in modules if m.root_kind == "package"}
        self._wc001(by_rel, findings)
        for m in modules:
            if m.root_kind != "package":
                continue
            if m.rel.startswith(_WIRE_READ_PREFIXES):
                self._wc002(m, findings)
            self._wc003(m, findings)
            self._wc004(m, findings)
        return findings

    def _wc001(self, by_rel: dict, findings: list[Finding]) -> None:
        proto = by_rel.get(_PROTOCOL_FILE)
        if proto is None:
            return  # not analyzing the package tree (fixture run)
        registered = _frozenset_literal(proto.tree, "PROTOCOL_OPS")
        if registered is None:
            findings.append(Finding(
                path=proto.repo_rel, line=1, rule="WC001",
                message=(
                    "PROTOCOL_OPS registry missing — protocol.py must "
                    "declare the op registry the request-id-echo test "
                    "iterates"
                ),
            ))
            registered = set()
        index = qualname_index(proto.tree)
        for node in ast.walk(proto.tree):
            if not isinstance(node, ast.Compare):
                continue
            if not (
                isinstance(node.left, ast.Name) and node.left.id == "op"
            ):
                continue
            for op_node, cmp in zip(node.comparators, node.ops):
                if not isinstance(cmp, (ast.Eq,)):
                    continue
                consts = [
                    c.value for c in ast.walk(op_node)
                    if isinstance(c, ast.Constant)
                    and isinstance(c.value, str)
                ]
                for op in consts:
                    if op not in registered:
                        findings.append(Finding(
                            path=proto.repo_rel, line=node.lineno,
                            rule="WC001",
                            symbol=symbol_at(index, node.lineno),
                            message=(
                                f"op {op!r} handled but not registered "
                                "in PROTOCOL_OPS"
                            ),
                        ))
        router = by_rel.get(_ROUTER_OPS_FILE)
        if router is not None and registered:
            routed = _frozenset_literal(router.tree, "ROUTED_OPS") or set()
            for op in sorted(routed - registered):
                findings.append(Finding(
                    path=router.repo_rel, line=1, rule="WC001",
                    message=(
                        f"ROUTED_OPS entry {op!r} is not in "
                        "PROTOCOL_OPS — the router would dispatch an op "
                        "no worker registers"
                    ),
                ))

    def _wc002(self, m: Module, findings: list[Finding]) -> None:
        index = qualname_index(m.tree)

        def guarded(stack: list[ast.AST], name: str, field: str) -> bool:
            for anc in stack:
                if not isinstance(anc, (ast.If, ast.IfExp)):
                    continue
                for sub in ast.walk(anc.test):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "get"
                        and dotted(sub.func.value) == name
                        and sub.args
                        and isinstance(sub.args[0], ast.Constant)
                        and sub.args[0].value == field
                    ):
                        return True
                    if (
                        isinstance(sub, ast.Compare)
                        and isinstance(sub.left, ast.Constant)
                        and sub.left.value == field
                        and any(isinstance(o, ast.In) for o in sub.ops)
                        # the membership test must be against THIS dict
                        # — `"f" in other` guards nothing about req["f"]
                        and any(dotted(c) == name for c in sub.comparators)
                    ):
                        return True
            return False

        def visit(node: ast.AST, stack: list[ast.AST]) -> None:
            for child in ast.iter_child_nodes(node):
                if (
                    isinstance(child, ast.Subscript)
                    and isinstance(child.ctx, ast.Load)
                    and isinstance(child.value, ast.Name)
                    and child.value.id in _WIRE_NAMES
                    and isinstance(child.slice, ast.Constant)
                    and isinstance(child.slice.value, str)
                ):
                    field = child.slice.value
                    if not guarded(stack, child.value.id, field):
                        findings.append(Finding(
                            path=m.repo_rel, line=child.lineno,
                            rule="WC002",
                            symbol=symbol_at(index, child.lineno),
                            message=(
                                f"{child.value.id}[{field!r}] read "
                                "without a default — old clients don't "
                                f"send {field!r}; use .get() or guard "
                                "the read"
                            ),
                        ))
                visit(child, stack + [child])

        visit(m.tree, [])

    def _wc003(self, m: Module, findings: list[Finding]) -> None:
        for prefix, allowed in _PRINT_SCOPES.items():
            if not m.rel.startswith(prefix) or m.rel in allowed:
                continue
            index = qualname_index(m.tree)
            for node in m.nodes:
                if is_print_call(node):
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="WC003",
                        symbol=symbol_at(index, node.lineno),
                        message=(
                            f"print() in {prefix} — this package runs "
                            "in processes whose stdout is the JSONL "
                            "wire; use runtime_event()"
                        ),
                    ))

    def _wc004(self, m: Module, findings: list[Finding]) -> None:
        if m.rel in _STREAM_WRITE_ALLOWED:
            return
        index = None
        for node in m.nodes:
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "write"
                and dotted(node.value) in ("sys.stdout", "sys.stderr")
            ):
                if index is None:
                    index = qualname_index(m.tree)
                findings.append(Finding(
                    path=m.repo_rel, line=node.lineno, rule="WC004",
                    symbol=symbol_at(index, node.lineno),
                    message=(
                        f"{dotted(node.value)}.write() — skips the "
                        "event sink's lock / corrupts the wire; use "
                        "runtime_event() or the locked protocol writer"
                    ),
                ))
