"""Unified invariant-checking static analysis (DESIGN.md §25).

One framework, one suppression story, for every machine-checkable
contract this repo's value proposition rests on:

- **recompile-safety** (recompile.py): tuning-knob resolution stays
  outside cached-jit cores, pad/shape decisions go through the
  sanctioned bucket helpers, static args stay hashable — the PR-3/PR-5
  zero-steady-state-recompile contracts, checked at the AST instead of
  only by compile-counter smoke tests.
- **lock discipline** (locks.py): for every class owning a
  ``threading.Lock``/``RLock``, attributes written under the lock must
  not be touched on paths that provably don't hold it.
- **determinism** (determinism.py): no unordered set/dict iteration
  into fingerprints/wire payloads, no score selection outside the
  shared ops/pathsim primitives, no wall-clock or unseeded RNG in
  deterministic paths.
- **wire contract** (wire.py): every protocol op registered in
  ``PROTOCOL_OPS``, wire-field reads defaulted (old clients keep
  working), stdout of wire-owning processes print-free.
- **interprocedural lock order** (interlocks.py, on callgraph.py):
  whole-repo held-locks-at-entry fixpoint, the global
  lock-acquisition-order graph (LD101 cycles = potential deadlock),
  blocking calls (LD102) and transport round-trips (LD103) reachable
  while a lock is held — DESIGN.md §27.
- **wire-schema gate** (wireschema.py): the per-op request/response
  field schema inferred by dataflow and checked in as the byte-stable
  ``artifacts/wire_schema.json``; backward-incompatible drift fails
  the build (WC101), stale files flag (WC102), dead fields flag
  (WC103).
- **exception safety** (exceptions.py): bare acquires (EX001), leaked
  handles (EX002), and pending-table registrations whose removal an
  exception can skip (EX003) — exactly-once on every exit path.
- **telemetry** (telemetry.py) and **tuning constants**
  (tuning_constants.py): the migrated ``scripts/lint_telemetry.py`` /
  ``scripts/lint_tuning.py`` rules, absorbed so there is ONE analyzer.

Run it as ``dpathsim lint`` or ``make lint`` (which also writes the
SARIF report to artifacts/lint.sarif); see core.py for the Finding
model, baseline semantics, and renderers, cache.py for the parse/mtime
cache that keeps the whole-repo run inside the tier-1 10 s gate, and
callgraph.py for the interprocedural engine.
"""

from .core import (  # noqa: F401
    Finding,
    Module,
    default_roots,
    load_baseline,
    load_modules,
    render_human,
    render_json,
    run_analysis,
)
from .cache import load_modules_cached  # noqa: F401
from .callgraph import CallGraph, propagate_reachability  # noqa: F401
from .registry import ALL_PASSES, MIGRATED_RULES, RULES  # noqa: F401
from .sarif import render_sarif  # noqa: F401
from .wireschema import infer_schema, render_schema  # noqa: F401
