"""Unified invariant-checking static analysis (DESIGN.md §25).

One framework, one suppression story, for every machine-checkable
contract this repo's value proposition rests on:

- **recompile-safety** (recompile.py): tuning-knob resolution stays
  outside cached-jit cores, pad/shape decisions go through the
  sanctioned bucket helpers, static args stay hashable — the PR-3/PR-5
  zero-steady-state-recompile contracts, checked at the AST instead of
  only by compile-counter smoke tests.
- **lock discipline** (locks.py): for every class owning a
  ``threading.Lock``/``RLock``, attributes written under the lock must
  not be touched on paths that provably don't hold it.
- **determinism** (determinism.py): no unordered set/dict iteration
  into fingerprints/wire payloads, no score selection outside the
  shared ops/pathsim primitives, no wall-clock or unseeded RNG in
  deterministic paths.
- **wire contract** (wire.py): every protocol op registered in
  ``PROTOCOL_OPS``, wire-field reads defaulted (old clients keep
  working), stdout of wire-owning processes print-free.
- **telemetry** (telemetry.py) and **tuning constants**
  (tuning_constants.py): the migrated ``scripts/lint_telemetry.py`` /
  ``scripts/lint_tuning.py`` rules, absorbed so there is ONE analyzer.

Run it as ``dpathsim lint`` or ``make lint``; see core.py for the
Finding model, baseline semantics, and renderers.
"""

from .core import (  # noqa: F401
    Finding,
    Module,
    default_roots,
    load_baseline,
    load_modules,
    render_human,
    render_json,
    run_analysis,
)
from .registry import ALL_PASSES, MIGRATED_RULES, RULES  # noqa: F401
