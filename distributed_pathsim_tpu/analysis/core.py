"""Framework core: module loading, the Finding model, baseline, renderers.

Design constraints (the satellites' contracts):

- **Deterministic**: the file walk is sorted, findings are sorted by
  (path, line, rule, message), and the JSON renderer emits sorted keys —
  two runs over the same tree produce byte-identical output, so lint
  diffs in CI are real diffs.
- **Fast enough to gate tier-1**: every file is read and parsed ONCE
  into a :class:`Module` shared by all passes (<10 s over the full repo,
  asserted by test).
- **Adoptable**: a checked-in baseline file
  (``analysis/baseline.json``) suppresses known findings so legacy code
  doesn't block turning a new rule on — but every entry needs a
  ``reason``, entries expire LOUDLY (an expired entry is itself an
  error finding), and an entry that no longer matches anything is also
  an error (stale suppressions must not accumulate).

Baseline entry shape::

    {"rule": "LD002", "path": "distributed_pathsim_tpu/obs/trace.py",
     "symbol": "Tracer.start_span",          # optional: enclosing qualname
     "match": "self.enabled",                # optional: message substring
     "reason": "benign racy read: ...",      # required
     "expires": "2027-01-01"}                # optional ISO date

A finding is suppressed by the first entry whose rule and path match it
exactly and whose ``symbol``/``match`` (when present) also match.
"""

from __future__ import annotations

import ast
import dataclasses
import datetime
import functools
import json
import pathlib


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at one site. ``path`` is repo-relative;
    ``symbol`` is the enclosing ``Class.method`` / function qualname
    (or "<module>") — the baseline's line-drift-proof anchor."""

    path: str
    line: int
    rule: str
    message: str
    symbol: str = "<module>"
    severity: str = "error"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.rule}] {self.symbol}: "
            f"{self.message}"
        )


@dataclasses.dataclass
class Module:
    """One parsed source file, shared by every pass: ``rel`` is the
    path relative to its root ("serving/cache.py" for package files),
    ``repo_rel`` the repo-relative path findings report, ``root_kind``
    one of "package" / "scripts" / "tests"."""

    path: pathlib.Path
    rel: str
    repo_rel: str
    root_kind: str
    text: str
    tree: ast.Module

    @property
    def lines(self) -> list[str]:
        return self.text.splitlines()

    @functools.cached_property
    def nodes(self) -> tuple:
        """Flat walk of the whole tree, computed once and shared by
        every pass — full-module scans dominate the tier-1 analysis
        budget, so passes iterate this instead of re-walking."""
        return tuple(ast.walk(self.tree))


def repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2]


def default_roots(repo: pathlib.Path | None = None) -> dict:
    """The trees ``dpathsim lint`` walks: the package, the dev scripts,
    and the test suite (fixture corpora under tests/fixtures are data,
    not code under analysis — skipped by :func:`load_modules`)."""
    repo = repo or repo_root()
    return {
        "package": repo / "distributed_pathsim_tpu",
        "scripts": repo / "scripts",
        "tests": repo / "tests",
    }


def load_modules(roots: dict, repo: pathlib.Path | None = None) -> list[Module]:
    """Parse every ``*.py`` under the given roots, sorted (the
    determinism contract starts at the walk). Unreadable/unparseable
    files are skipped — a syntax error in one file must not hide
    findings in the rest (the compiler will be plenty loud about it)."""
    repo = repo or repo_root()
    modules: list[Module] = []
    for kind in sorted(roots):
        root = pathlib.Path(roots[kind])
        if not root.is_dir():
            continue
        for path in sorted(root.rglob("*.py")):
            # fixture corpora under a scanned root are test DATA, not
            # code under analysis — but a root that IS a fixture tree
            # (the corpus tests point the analyzer at one) scans fully
            if "fixtures" in path.relative_to(root).parts:
                continue
            try:
                text = path.read_text(encoding="utf-8")
                tree = ast.parse(text, filename=str(path))
            except (OSError, SyntaxError):
                continue
            try:
                repo_rel = path.resolve().relative_to(repo.resolve()).as_posix()
            except ValueError:
                repo_rel = path.as_posix()
            modules.append(
                Module(
                    path=path,
                    rel=path.relative_to(root).as_posix(),
                    repo_rel=repo_rel,
                    root_kind=kind,
                    text=text,
                    tree=tree,
                )
            )
    return modules


# -- symbol resolution -------------------------------------------------------


def qualname_index(tree: ast.Module) -> dict[int, str]:
    """line → enclosing "Class.method"/function qualname, for every
    line covered by a def/class. Built once per module; passes anchor
    findings with :func:`symbol_at`."""
    index: dict[int, str] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                name = f"{prefix}.{child.name}" if prefix else child.name
                end = getattr(child, "end_lineno", child.lineno) or child.lineno
                for ln in range(child.lineno, end + 1):
                    index[ln] = name
                visit(child, name)
            else:
                visit(child, prefix)

    visit(tree, "")
    return index


def symbol_at(index: dict[int, str], line: int) -> str:
    return index.get(line, "<module>")


# -- baseline ----------------------------------------------------------------

BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: pathlib.Path | str | None = None) -> list[dict]:
    p = pathlib.Path(path) if path is not None else BASELINE_PATH
    if not p.exists():
        return []
    doc = json.loads(p.read_text(encoding="utf-8"))
    entries = doc["suppressions"] if isinstance(doc, dict) else doc
    for e in entries:
        if "reason" not in e or not str(e["reason"]).strip():
            raise ValueError(
                f"baseline entry without a reason: {e!r} — every "
                "suppression must say why it is not a bug"
            )
    return entries


def _entry_matches(entry: dict, f: Finding) -> bool:
    if entry.get("rule") != f.rule or entry.get("path") != f.path:
        return False
    if entry.get("symbol") is not None and entry["symbol"] != f.symbol:
        return False
    if entry.get("match") is not None and entry["match"] not in f.message:
        return False
    return True


def apply_baseline(
    findings: list[Finding],
    entries: list[dict],
    today: datetime.date | None = None,
) -> tuple[list[Finding], list[Finding]]:
    """(kept, suppressed). Expired entries and entries that matched
    nothing come back as synthetic error findings appended to ``kept``
    — the loud half of the suppression story."""
    today = today or datetime.date.today()
    kept: list[Finding] = []
    suppressed: list[Finding] = []
    used = [0] * len(entries)
    active = []
    for i, e in enumerate(entries):
        exp = e.get("expires")
        expired = (
            exp is not None and datetime.date.fromisoformat(exp) < today
        )
        active.append(not expired)
    for f in findings:
        hit = None
        for i, e in enumerate(entries):
            if active[i] and _entry_matches(e, f):
                hit = i
                break
        if hit is not None:
            used[hit] += 1
            suppressed.append(f)
        else:
            kept.append(f)
    for i, e in enumerate(entries):
        if not active[i]:
            kept.append(Finding(
                path=str(e.get("path")), line=0, rule="BASELINE",
                symbol=str(e.get("symbol") or "<entry>"),
                message=(
                    f"suppression for {e.get('rule')} expired on "
                    f"{e.get('expires')} — fix the finding or renew the "
                    f"entry (reason was: {e.get('reason')})"
                ),
            ))
        elif used[i] == 0:
            kept.append(Finding(
                path=str(e.get("path")), line=0, rule="BASELINE",
                symbol=str(e.get("symbol") or "<entry>"),
                message=(
                    f"stale suppression: no {e.get('rule')} finding "
                    "matches this entry any more — delete it"
                ),
            ))
    return sorted(kept), sorted(suppressed)


# -- driving -----------------------------------------------------------------


def run_analysis(
    roots: dict | None = None,
    rules: set[str] | None = None,
    baseline: list[dict] | None = None,
    repo: pathlib.Path | None = None,
    modules: list[Module] | None = None,
) -> dict:
    """Load once, run every pass, apply the baseline. Returns
    ``{"findings": [...], "suppressed": [...], "files": int}`` with
    both lists sorted. ``rules`` filters by rule id (a pass whose rules
    are all filtered out is skipped entirely)."""
    from .registry import ALL_PASSES

    repo = repo or repo_root()
    if modules is None:
        modules = load_modules(roots or default_roots(repo), repo)
    findings: list[Finding] = []
    for p in ALL_PASSES:
        pass_rules = set(p.rules)
        if rules is not None and not (pass_rules & rules):
            continue
        got = p.run(modules)
        if rules is not None:
            got = [f for f in got if f.rule in rules]
        findings.extend(got)
    findings.sort()
    if baseline is None:
        kept, suppressed = findings, []
    else:
        kept, suppressed = apply_baseline(findings, baseline)
    return {"findings": kept, "suppressed": suppressed,
            "files": len(modules)}


# -- renderers ---------------------------------------------------------------


def render_human(result: dict) -> str:
    from .registry import RULES

    out = []
    for f in result["findings"]:
        doc = RULES.get(f.rule)
        out.append(f.render())
        if doc is not None:
            out.append(f"    -> {doc.why}")
    out.append(
        f"dpathsim lint: {len(result['findings'])} finding(s), "
        f"{len(result['suppressed'])} baselined, "
        f"{result['files']} files"
    )
    return "\n".join(out)


def render_json(result: dict) -> str:
    """Stable, diffable: sorted findings (Finding is order-able), sorted
    keys, no timestamps."""
    doc = {
        "findings": [dataclasses.asdict(f) for f in result["findings"]],
        "suppressed": [dataclasses.asdict(f) for f in result["suppressed"]],
        "files": result["files"],
    }
    return json.dumps(doc, indent=2, sort_keys=True)
