"""Wire-schema inference + backward-compatibility gate (WC100s).

The JSONL protocol's per-op request/response field contracts live only
in code: ``_dispatch_op`` branches read ``req.get(...)``, handlers
return dict literals, and the routers construct wire dicts. This pass
*infers* that schema by dataflow over the call graph and turns it into
a machine-checked contract:

- the inferred schema is emitted as a checked-in, byte-stable
  ``artifacts/wire_schema.json`` (``dpathsim lint --write-wire-schema``
  regenerates it), covering every op in ``PROTOCOL_OPS``: request
  fields (required vs defaulted, consumer sites, producer sites) and
  response fields (producer sites, plus a ``response_complete`` marker
  for ops whose every return was statically enumerable);
- **WC101 backward-incompatible wire drift**: the checked-in schema
  records the contract old clients were built against — an op dropped,
  a request/response field removed, or an optional field turned
  required fails the build (old peers break);
- **WC102 schema file out of date**: compatible drift (new op, new
  defaulted field, a field relaxed to optional) still needs the file
  regenerated, or the contract record rots;
- **WC103 dead wire field**: a field some producer writes that no
  handler reads (a typo'd key silently ignored at the far end), or —
  for ops the routers themselves produce — a field a handler reads
  that no producer writes.

Inference walks: each op's ``_dispatch_op`` branch; every function the
wire dict is passed to (parameter-position dataflow over resolved call
edges); and — for the ``getattr(service, op)`` trampoline — every
``serving/`` function *named* the op with a ``req`` parameter (the
``PartitionService`` handler convention). ``req.get(key)`` loops over
module-level constant tuples (``_QUERY_KEYS``) resolve to their
elements. The dynamic cross-check (tests/test_wire_schema.py) replays
the router and partition smokes and asserts every field observed on
the live wire appears here — the inference-soundness half.
"""

from __future__ import annotations

import ast
import json
import pathlib

from .astutil import call_name
from .callgraph import CallGraph, FuncInfo
from .core import Finding, Module

RULE_DOCS = {
    "WC101": (
        "backward-incompatible wire-schema drift",
        "the checked-in artifacts/wire_schema.json records the contract "
        "existing peers were built against — removing an op or field, "
        "or turning an optional field required, breaks them; restore "
        "the contract or ship a compatibility path first",
    ),
    "WC102": (
        "wire_schema.json out of date",
        "the code's wire contract grew (new op / new defaulted field / "
        "field relaxed) but the checked-in schema wasn't regenerated — "
        "run `dpathsim lint --write-wire-schema` and commit the diff so "
        "drift reviews stay real diffs",
    ),
    "WC103": (
        "dead wire field",
        "a request field written by no reader (typo'd key, silently "
        "ignored at the far end) or — on router-produced ops — read by "
        "no writer (dead handler path); fix the mismatch or baseline a "
        "deliberately client-only field with a justification",
    ),
}

# fields every request may carry, handled by handle_request itself —
# not part of any per-op schema
ENVELOPE = ("deadline_ms", "id", "op", "request_id", "trace")

_PROTOCOL_FILE = "serving/protocol.py"
_DISPATCH_FN = "_dispatch_op"
SCHEMA_REL = "artifacts/wire_schema.json"
# where dynamic-dispatch fallbacks may resolve (the service handler
# convention lives in serving/; the trace ring export in obs/)
_HANDLER_PREFIXES = ("serving/", "obs/")


def _frozenset_literal(tree: ast.Module, name: str) -> set[str] | None:
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == name
        ):
            return {
                c.value for c in ast.walk(node.value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            }
    return None


def _const_tuples(tree: ast.Module) -> dict[str, tuple[str, ...]]:
    """Module-level ``NAME = ("a", "b", ...)`` string tuples/lists."""
    out: dict[str, tuple[str, ...]] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            elts = node.value.elts
            if elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in elts
            ):
                out[node.targets[0].id] = tuple(e.value for e in elts)
    return out


class _OpSchema:
    __slots__ = ("request", "response", "response_complete", "producers")

    def __init__(self):
        # field -> {"required": bool, "consumers": set[str]}
        self.request: dict[str, dict] = {}
        # field -> set[str] producer sites
        self.response: dict[str, set] = {}
        self.response_complete = True
        # field -> set[str] request-producer sites
        self.producers: dict[str, set] = {}

    def read(self, field: str, required: bool, site: str) -> None:
        slot = self.request.setdefault(
            field, {"required": False, "consumers": set()}
        )
        slot["required"] = slot["required"] or required
        slot["consumers"].add(site)


class _SchemaBuilder:
    def __init__(self, modules: list[Module]):
        from .callgraph import shared_package_graph

        self.graph = shared_package_graph(modules)
        self.modules = self.graph.modules
        self.by_rel = {m.rel: m for m in self.modules}
        self.consts = {
            m.repo_rel: _const_tuples(m.tree) for m in self.modules
        }
        self.ops: dict[str, _OpSchema] = {}

    # -- entry -------------------------------------------------------------

    def infer(self) -> dict | None:
        proto = self.by_rel.get(_PROTOCOL_FILE)
        if proto is None:
            return None
        registered = _frozenset_literal(proto.tree, "PROTOCOL_OPS")
        if not registered:
            return None
        dispatch = self.graph.by_fid.get(
            f"{proto.repo_rel}:{_DISPATCH_FN}"
        )
        if dispatch is None:
            return None
        for op in sorted(registered):
            self.ops[op] = _OpSchema()
        self._infer_handlers(dispatch, registered)
        self._scan_producers()
        return self._render()

    def _infer_handlers(self, dispatch: FuncInfo, registered) -> None:
        branches = {}
        for stmt in dispatch.node.body:
            if not isinstance(stmt, ast.If):
                continue
            t = stmt.test
            if (
                isinstance(t, ast.Compare)
                and isinstance(t.left, ast.Name)
                and t.left.id == "op"
                and len(t.ops) == 1
                and isinstance(t.ops[0], ast.Eq)
                and isinstance(t.comparators[0], ast.Constant)
                and isinstance(t.comparators[0].value, str)
            ):
                branches[t.comparators[0].value] = stmt.body
        for op in sorted(self.ops):
            schema = self.ops[op]
            visited: set[tuple[str, str]] = set()
            returns: list[tuple[ast.expr, str]] = []
            region = branches.get(op)
            if region is not None:
                self._walk_region(
                    region, dispatch, {"req"}, op, schema, visited,
                    returns,
                )
                self._region_returns(region, dispatch, returns)
            for fn in self._op_fallbacks(op):
                if (fn.fid, "req") not in visited:
                    visited.add((fn.fid, "req"))
                    self._walk_region(
                        fn.node.body, fn, {"req"}, op, schema, visited,
                        returns,
                    )
                self._collect_returns(fn, returns)
            self._infer_response(op, schema, returns)

    def _op_fallbacks(self, op: str) -> list[FuncInfo]:
        """The ``getattr(service, op)(req)`` trampoline targets: every
        serving-tier function named exactly like the op that takes a
        ``req`` parameter."""
        out = []
        for prefix in _HANDLER_PREFIXES:
            for fn in self.graph.functions_named(
                op, rel_prefix=prefix, with_param="req"
            ):
                if fn.module.rel != _PROTOCOL_FILE:
                    out.append(fn)
        return out

    # -- request-field dataflow --------------------------------------------

    def _walk_region(
        self, stmts, fn: FuncInfo, names: set[str], op: str,
        schema: _OpSchema, visited: set, returns: list,
    ) -> None:
        site = f"{fn.module.repo_rel}:{fn.qual}"
        consts = self.consts.get(fn.module.repo_rel, {})
        local_types = self.graph.local_types(fn)

        def const_elems(expr: ast.AST, env: dict) -> tuple[str, ...]:
            if isinstance(expr, ast.Name):
                if expr.id in consts:
                    return consts[expr.id]
                return env.get(expr.id, ())
            if isinstance(expr, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in expr.elts
            ):
                return tuple(e.value for e in expr.elts)
            return ()

        def guarded(stack: list, field: str) -> bool:
            for anc in stack:
                if not isinstance(anc, (ast.If, ast.IfExp)):
                    continue
                for sub in ast.walk(anc.test):
                    if (
                        isinstance(sub, ast.Compare)
                        and isinstance(sub.left, ast.Constant)
                        and sub.left.value == field
                        and any(isinstance(o, ast.In) for o in sub.ops)
                    ):
                        return True
            return False

        def visit(node: ast.AST, stack: list, env: dict) -> None:
            for child in ast.iter_child_nodes(node):
                child_env = env
                if isinstance(child, (ast.For, ast.comprehension)):
                    target = (
                        child.target if isinstance(child.target, ast.Name)
                        else None
                    )
                    it = child.iter
                    if target is not None:
                        elems = const_elems(it, env)
                        if elems:
                            child_env = dict(env)
                            child_env[target.id] = elems
                if isinstance(child, (ast.DictComp, ast.ListComp,
                                      ast.SetComp, ast.GeneratorExp)):
                    comp_env = dict(env)
                    for gen in child.generators:
                        if isinstance(gen.target, ast.Name):
                            elems = const_elems(gen.iter, comp_env)
                            if elems:
                                comp_env[gen.target.id] = elems
                    child_env = comp_env
                # req["field"] — a required read
                if (
                    isinstance(child, ast.Subscript)
                    and isinstance(child.ctx, ast.Load)
                    and isinstance(child.value, ast.Name)
                    and child.value.id in names
                ):
                    sl = child.slice
                    if isinstance(sl, ast.Constant) and isinstance(
                        sl.value, str
                    ):
                        if sl.value not in ENVELOPE:
                            schema.read(
                                sl.value,
                                required=not guarded(stack, sl.value),
                                site=site,
                            )
                    else:
                        for f in const_elems(sl, child_env):
                            if f not in ENVELOPE:
                                schema.read(f, False, site)
                # req.get("field" ...) — a defaulted read
                if (
                    isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr == "get"
                    and isinstance(child.func.value, ast.Name)
                    and child.func.value.id in names
                    and child.args
                ):
                    a0 = child.args[0]
                    if isinstance(a0, ast.Constant) and isinstance(
                        a0.value, str
                    ):
                        if a0.value not in ENVELOPE:
                            schema.read(a0.value, False, site)
                    else:
                        for f in const_elems(a0, child_env):
                            if f not in ENVELOPE:
                                schema.read(f, False, site)
                # "field" in req — a guard read
                if isinstance(child, ast.Compare) and any(
                    isinstance(o, ast.In) for o in child.ops
                ):
                    if (
                        isinstance(child.left, ast.Constant)
                        and isinstance(child.left.value, str)
                        and any(
                            isinstance(c, ast.Name) and c.id in names
                            for c in child.comparators
                        )
                        and child.left.value not in ENVELOPE
                    ):
                        schema.read(child.left.value, False, site)
                # the wire dict passed onward: follow into the callee
                if isinstance(child, ast.Call):
                    self._follow_call(
                        child, fn, names, local_types, op, schema,
                        visited, returns,
                    )
                visit(child, stack + [child], child_env)

        fake_root = ast.Module(body=list(stmts), type_ignores=[])
        visit(fake_root, [], {})

    def _follow_call(
        self, call, fn, names, local_types, op, schema, visited, returns,
    ) -> None:
        passed: list[tuple[int | str, str]] = []
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Name) and a.id in names:
                passed.append((i, a.id))
        for kw in call.keywords:
            if (
                kw.arg is not None
                and isinstance(kw.value, ast.Name)
                and kw.value.id in names
            ):
                passed.append((kw.arg, kw.value.id))
        if not passed:
            return
        callee_fid = self.graph.resolve(fn, call, local_types)
        if callee_fid is None:
            return
        callee = self.graph.by_fid[callee_fid]
        params = callee.params
        offset = 1 if callee.cls is not None and params[:1] == ["self"] \
            else 0
        for pos, _name in passed:
            if isinstance(pos, int):
                idx = pos + offset
                pname = params[idx] if idx < len(params) else None
            else:
                pname = pos if pos in params else None
            if pname is None or (callee_fid, pname) in visited:
                continue
            visited.add((callee_fid, pname))
            self._walk_region(
                callee.node.body, callee, {pname}, op, schema, visited,
                returns,
            )

    # -- response inference ------------------------------------------------

    def _collect_returns(self, fn: FuncInfo, returns: list) -> None:
        self._collect_returns_from(fn.node, fn, returns)

    def _region_returns(self, stmts, fn: FuncInfo, returns: list) -> None:
        fake = ast.Module(body=list(stmts), type_ignores=[])
        self._collect_returns_from(fake, fn, returns)

    def _collect_returns_from(self, root, fn, returns) -> None:
        def visit(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, ast.Return) and child.value is not None:
                    returns.append((child.value, fn))
                visit(child)

        visit(root)

    def _infer_response(self, op, schema, returns) -> None:
        seen_fids: set[str] = set()
        work = list(returns)
        depth = 0
        while work and depth < 6:
            depth += 1
            next_work: list = []
            for value, fn in work:
                self._one_return(
                    op, schema, value, fn, next_work, seen_fids
                )
            work = next_work
        if work:
            schema.response_complete = False

    def _one_return(self, op, schema, value, fn, next_work, seen) -> None:
        site = f"{fn.module.repo_rel}:{fn.qual}"
        if isinstance(value, ast.Dict):
            for k in value.keys:
                if k is None:  # **spread: not enumerable
                    schema.response_complete = False
                elif isinstance(k, ast.Constant) and isinstance(
                    k.value, str
                ):
                    schema.response.setdefault(k.value, set()).add(site)
                else:
                    schema.response_complete = False
            return
        if isinstance(value, ast.Call):
            # the `_partition_op(service, "<op>", req)` trampoline:
            # a string-literal argument equal to the op redirects to
            # the named-handler fallbacks
            if any(
                isinstance(a, ast.Constant) and a.value == op
                for a in value.args
            ):
                for target in self._op_fallbacks(op):
                    if target.fid not in seen:
                        seen.add(target.fid)
                        self._queue_returns(target, next_work)
                return
            resolved = self.graph.resolve(
                fn, value, self.graph.local_types(fn)
            )
            targets: list[FuncInfo] = []
            if resolved is not None:
                targets = [self.graph.by_fid[resolved]]
            elif isinstance(value.func, ast.Attribute):
                for prefix in _HANDLER_PREFIXES:
                    targets.extend(self.graph.functions_named(
                        value.func.attr, rel_prefix=prefix
                    ))
            if not targets:
                schema.response_complete = False
                return
            for target in targets:
                if target.fid not in seen:
                    seen.add(target.fid)
                    self._queue_returns(target, next_work)
            return
        schema.response_complete = False

    def _queue_returns(self, fn: FuncInfo, next_work: list) -> None:
        got: list = []
        self._collect_returns(fn, got)
        if not got:
            # a handler that returns nothing enumerable
            next_work.append((ast.Constant(value=None), fn))
        next_work.extend(got)

    # -- producers ---------------------------------------------------------

    def _scan_producers(self) -> None:
        from .core import qualname_index, symbol_at

        for m in self.modules:
            index = None
            for node in m.nodes:
                if not isinstance(node, ast.Dict):
                    continue
                ops_here: list[str] = []
                fields: list[str] = []
                for k, v in zip(node.keys, node.values):
                    if not (
                        isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                    ):
                        continue
                    if k.value == "op":
                        if isinstance(v, ast.Constant) and isinstance(
                            v.value, str
                        ):
                            ops_here.append(v.value)
                        elif isinstance(v, ast.IfExp):
                            for side in (v.body, v.orelse):
                                if isinstance(
                                    side, ast.Constant
                                ) and isinstance(side.value, str):
                                    ops_here.append(side.value)
                    elif k.value not in ENVELOPE:
                        fields.append(k.value)
                ops_here = [o for o in ops_here if o in self.ops]
                if not ops_here:
                    continue
                if index is None:
                    index = qualname_index(m.tree)
                site = f"{m.repo_rel}:{symbol_at(index, node.lineno)}"
                for o in ops_here:
                    schema = self.ops[o]
                    for f in fields:
                        schema.producers.setdefault(f, set()).add(site)
                    if not fields:
                        schema.producers.setdefault("", set()).add(site)

    # -- rendering ---------------------------------------------------------

    def _render(self) -> dict:
        ops_doc = {}
        for op in sorted(self.ops):
            s = self.ops[op]
            produced_sites = sorted(
                {x for f, sites in s.producers.items() for x in sites}
            )
            ops_doc[op] = {
                "request": {
                    f: {
                        "required": s.request[f]["required"],
                        "consumers": sorted(s.request[f]["consumers"]),
                        "producers": sorted(s.producers.get(f, ())),
                    }
                    for f in sorted(s.request)
                },
                "response": {
                    f: {"producers": sorted(s.response[f])}
                    for f in sorted(s.response)
                },
                "response_complete": s.response_complete,
                "produced_by": produced_sites,
                "extra_produced": sorted(
                    f for f in s.producers
                    if f and f not in s.request
                ),
            }
        return {
            "_doc": [
                "Inferred JSONL wire schema (analysis/wireschema.py, "
                "DESIGN.md §27).",
                "Regenerate with `dpathsim lint --write-wire-schema`. "
                "The lint gate fails on backward-incompatible drift "
                "(WC101) and on a stale file (WC102).",
                "request fields: required=false means defaulted "
                "(yesterday's clients may omit it). consumers/producers "
                "are <path>:<qualname> sites.",
            ],
            "envelope": list(ENVELOPE),
            "ops": ops_doc,
        }


def infer_schema(modules: list[Module]) -> dict | None:
    """The inferred schema document, or None when the analyzed tree has
    no protocol module (fixture corpora for other rules)."""
    return _SchemaBuilder(modules).infer()


def render_schema(schema: dict) -> str:
    return json.dumps(schema, indent=2, sort_keys=True) + "\n"


def schema_path_for(modules: list[Module]) -> pathlib.Path | None:
    """Derive ``<repo>/artifacts/wire_schema.json`` from the analyzed
    protocol module's location (fixture trees carry their own)."""
    for m in modules:
        if m.rel == _PROTOCOL_FILE and m.root_kind == "package":
            parts = pathlib.PurePosixPath(m.repo_rel).parts
            root = m.path.resolve().parents[len(parts) - 1]
            return root / SCHEMA_REL
    return None


class WireSchemaPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        builder = _SchemaBuilder(modules)
        inferred = builder.infer()
        if inferred is None:
            return []
        findings: list[Finding] = []
        self._dead_fields(builder, findings)
        path = schema_path_for(builder.modules)
        if path is None or not path.exists():
            # no checked-in contract to gate against (the byte-stable
            # regeneration test is what forces the real repo's file to
            # exist and match)
            return sorted(findings)
        try:
            recorded = json.loads(path.read_text(encoding="utf-8"))
        except ValueError:
            findings.append(Finding(
                path=SCHEMA_REL, line=1, rule="WC102",
                symbol="<schema>",
                message="wire_schema.json is not valid JSON — regenerate",
            ))
            return sorted(findings)
        self._diff(recorded, inferred, findings)
        return sorted(findings)

    # -- drift -------------------------------------------------------------

    def _diff(self, recorded: dict, inferred: dict, findings) -> None:
        rec_ops = recorded.get("ops") or {}
        inf_ops = inferred.get("ops") or {}

        def incompatible(msg: str) -> None:
            findings.append(Finding(
                path=SCHEMA_REL, line=1, rule="WC101",
                symbol="<schema>", message=msg,
            ))

        def outdated(msg: str) -> None:
            findings.append(Finding(
                path=SCHEMA_REL, line=1, rule="WC102",
                symbol="<schema>", message=msg,
            ))

        for op in sorted(rec_ops):
            if op not in inf_ops:
                incompatible(
                    f"op {op!r} dropped from the protocol — clients "
                    "built against the recorded schema still send it"
                )
                continue
            rec, inf = rec_ops[op], inf_ops[op]
            rec_req = rec.get("request") or {}
            inf_req = inf.get("request") or {}
            for f in sorted(rec_req):
                if f not in inf_req:
                    incompatible(
                        f"request field {op}.{f!r} removed — recorded "
                        "consumers no longer read it; senders that set "
                        "it are now silently ignored"
                    )
                elif (
                    not rec_req[f].get("required")
                    and inf_req[f].get("required")
                ):
                    incompatible(
                        f"request field {op}.{f!r} turned required — "
                        "clients built against the recorded schema may "
                        "omit it and now break"
                    )
                elif (
                    rec_req[f].get("required")
                    and not inf_req[f].get("required")
                ):
                    outdated(
                        f"request field {op}.{f!r} relaxed to optional "
                        "— regenerate the schema file"
                    )
            for f in sorted(inf_req):
                if f not in rec_req:
                    outdated(
                        f"new request field {op}.{f!r} not in the "
                        "schema file — regenerate"
                    )
            if rec.get("response_complete") and inf.get(
                "response_complete"
            ):
                rec_resp = rec.get("response") or {}
                inf_resp = inf.get("response") or {}
                for f in sorted(rec_resp):
                    if f not in inf_resp:
                        incompatible(
                            f"response field {op}.{f!r} removed — "
                            "recorded consumers expect it"
                        )
                for f in sorted(inf_resp):
                    if f not in rec_resp:
                        outdated(
                            f"new response field {op}.{f!r} not in the "
                            "schema file — regenerate"
                        )
        for op in sorted(inf_ops):
            if op not in rec_ops:
                outdated(
                    f"new op {op!r} not in the schema file — regenerate"
                )

    # -- dead fields -------------------------------------------------------

    def _dead_fields(self, builder: _SchemaBuilder, findings) -> None:
        for op in sorted(builder.ops):
            s = builder.ops[op]
            produced = {f for f in s.producers if f}
            consumed = set(s.request)
            for f in sorted(produced - consumed):
                site = sorted(s.producers[f])[0]
                path, qual = site.split(":", 1)
                findings.append(Finding(
                    path=path, line=1, rule="WC103", symbol=qual,
                    message=(
                        f"request field {op}.{f!r} is produced here but "
                        "read by no handler — a typo'd or obsolete key "
                        "the far end silently ignores"
                    ),
                ))
            if not s.producers:
                continue  # nobody in-repo sends this op: client-only
            for f in sorted(consumed - produced):
                site = sorted(s.request[f]["consumers"])[0]
                path, qual = site.split(":", 1)
                findings.append(Finding(
                    path=path, line=1, rule="WC103", symbol=qual,
                    message=(
                        f"request field {op}.{f!r} is read here but "
                        "produced by no in-repo sender — dead handler "
                        "path, or a deliberately client-only field "
                        "(baseline it with the reason)"
                    ),
                ))
