"""SARIF 2.1.0 export: `dpathsim lint --sarif` for CI annotations.

One run, one tool (``dpathsim-lint``), the full rule catalog as
``rules`` (so viewers render titles and help text), one ``result`` per
non-baselined finding and one *suppressed* result per baselined one
(SARIF's own suppression model — CI dashboards can show what the
baseline is carrying). Deterministic: sorted findings in, sorted keys
out, no timestamps — the artifact diffs like the JSON renderer does.
"""

from __future__ import annotations

import json

_SARIF_VERSION = "2.1.0"
_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _result(f, suppressed: bool) -> dict:
    out = {
        "ruleId": f.rule,
        "level": "error" if f.severity == "error" else "warning",
        "message": {"text": f"{f.symbol}: {f.message}"},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": max(int(f.line), 1)},
            },
            "logicalLocations": [{"fullyQualifiedName": f.symbol}],
        }],
    }
    if suppressed:
        out["suppressions"] = [{
            "kind": "external",
            "justification": (
                "baselined in distributed_pathsim_tpu/analysis/"
                "baseline.json (every entry carries a reason and loud "
                "expiry)"
            ),
        }]
    return out


def render_sarif(result: dict) -> str:
    """``result`` is :func:`~.core.run_analysis` output."""
    from .registry import RULES

    rules = [
        {
            "id": rid,
            "name": RULES[rid].title,
            "shortDescription": {"text": RULES[rid].title},
            "fullDescription": {"text": RULES[rid].why},
            "properties": {"pass": RULES[rid].pass_name},
        }
        for rid in sorted(RULES)
    ]
    # the synthetic BASELINE rule (expired/stale suppressions) has no
    # registry entry but can appear in findings
    rules.append({
        "id": "BASELINE",
        "name": "baseline bookkeeping error",
        "shortDescription": {"text": "baseline bookkeeping error"},
        "fullDescription": {"text": (
            "an expired suppression (fix the finding or renew it) or a "
            "stale one matching nothing (delete it)"
        )},
        "properties": {"pass": "core"},
    })
    doc = {
        "$schema": _SCHEMA,
        "version": _SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "dpathsim-lint",
                "informationUri": (
                    "https://github.com/example/distributed-pathsim-tpu"
                ),
                "rules": rules,
            }},
            "columnKind": "utf16CodeUnits",
            "results": (
                [_result(f, False) for f in result["findings"]]
                + [_result(f, True) for f in result["suppressed"]]
            ),
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"
