"""Rule catalog: every pass, every rule id, and the migration map.

``RULES`` is the one authoritative id → doc table (the CLI's
``--list-rules``, the human renderer's "why" lines, and the test
suite's fixture-coverage assertion all read it). ``MIGRATED_RULES``
records which legacy ad-hoc lint rule each unified rule subsumes — the
subsumption test walks it to prove the old scripts' checks all
survived the migration.
"""

from __future__ import annotations

import dataclasses

from .batch_rules import BatchDoorwayPass
from .compaction_rules import CompactionDoorwayPass
from .compress_rules import CompressedLayoutPass
from .determinism import DeterminismPass
from .exceptions import ExceptionSafetyPass
from .interlocks import InterLockPass
from .learned_rules import LearnedDoorwayPass
from .locks import LockDisciplinePass
from .metapath_ir import MetapathIRPass
from .partition import PartitionOwnershipPass
from .recompile import RecompileSafetyPass
from .telemetry import TelemetryPass
from .tuning_constants import TuningConstantsPass
from .wire import WireContractPass
from .wireschema import WireSchemaPass


@dataclasses.dataclass(frozen=True)
class RuleDoc:
    id: str
    title: str
    why: str
    pass_name: str


# rule-family display names for the grouped `--list-rules` catalog
PASS_FAMILIES: dict[str, str] = {
    "RecompileSafetyPass": "recompile-safety (RS)",
    "LockDisciplinePass": "lock discipline, intra-class (LD001+)",
    "InterLockPass": "lock order / blocking-under-lock, "
                     "interprocedural (LD101+)",
    "DeterminismPass": "determinism (DT)",
    "WireContractPass": "wire contract, syntactic (WC001+)",
    "WireSchemaPass": "wire schema inference + compat gate (WC101+)",
    "TelemetryPass": "telemetry (TL)",
    "TuningConstantsPass": "tuning constants (TN)",
    "PartitionOwnershipPass": "partition ownership (PT)",
    "ExceptionSafetyPass": "exception safety / exactly-once (EX)",
    "MetapathIRPass": "metapath planner IR, interprocedural (MP)",
    "CompressedLayoutPass": "compressed factor layouts, "
                            "interprocedural (CF)",
    "CompactionDoorwayPass": "compaction swap doorway (CP)",
    "BatchDoorwayPass": "batch block-sweep doorway (BT)",
    "LearnedDoorwayPass": "learned score doorway (LN)",
}

ALL_PASSES = (
    RecompileSafetyPass(),
    LockDisciplinePass(),
    InterLockPass(),
    DeterminismPass(),
    WireContractPass(),
    WireSchemaPass(),
    TelemetryPass(),
    TuningConstantsPass(),
    PartitionOwnershipPass(),
    ExceptionSafetyPass(),
    MetapathIRPass(),
    CompressedLayoutPass(),
    CompactionDoorwayPass(),
    BatchDoorwayPass(),
    LearnedDoorwayPass(),
)

RULES: dict[str, RuleDoc] = {}
for _p in ALL_PASSES:
    for _rid, (_title, _why) in _p.rules.items():
        RULES[_rid] = RuleDoc(
            id=_rid, title=_title, why=_why,
            pass_name=type(_p).__name__,
        )

# legacy rule (scripts/lint_telemetry.py, scripts/lint_tuning.py) →
# the unified rule that subsumes it
MIGRATED_RULES: dict[str, str] = {
    "wall-clock-duration": "DT003",       # lint_telemetry R1
    "raw-stderr-print": "TL001",          # lint_telemetry R2
    "event-sink-bypass": "TL002",         # lint_telemetry R3
    "raw-stream-write": "WC004",          # lint_telemetry R4
    "router-raw-print": "WC003",          # lint_telemetry R5
    "index-raw-print": "WC003",           # lint_telemetry R6
    "obs-raw-print": "WC003",             # lint_telemetry R7
    "protocol-op-registry": "WC001",      # lint_telemetry R8
    "hardcoded-tuning-constant": "TN001", # lint_tuning
}
