"""Recompile-safety pass: the zero-steady-state-recompile contracts.

The serving tier's latency story rests on two PR-3/PR-5 invariants that
until now only compile-counter smoke tests enforced *after the fact*:

- **RS001 knob-in-jit**: tuning-knob resolution
  (``tuning.dispatch.choose()`` and friends) must happen OUTSIDE any
  ``@jax.jit``-decorated (or ``_*_jit``-named) core. A knob resolved
  inside a traced function is frozen into the compiled program — the
  table changes, the program silently doesn't (and re-tracing to honor
  it would be exactly the steady-state recompile the contract forbids).
- **RS002 unbucketed-shape**: in the serving tier (``serving/``, the
  index probe path), batch padding and device-shape construction go
  through the sanctioned bucket helpers (``bucket_for``/``pad_rows``/
  ``bucket_ladder``/``resolve_ladder``). A raw ``np.pad``/``jnp.pad``
  or a ``jnp.zeros(len(...))``-style Python-value-dependent shape in a
  function that never consults the ladder compiles one program per
  distinct size — the unbounded-compile regression the pow-2 buckets
  exist to prevent.
- **RS003 mutable-static-arg**: a jit ``static_argnames`` parameter
  whose default or annotation is a list/dict/set is unhashable — it
  fails at call time at best, and at worst invites "fix" by
  list→tuple conversion per call, defeating the compile cache.
"""

from __future__ import annotations

import ast

from .astutil import call_name, jit_decorated, static_argnames, walk_functions
from .core import Finding, Module

RULE_DOCS = {
    "RS001": (
        "tuning-knob resolution inside a jitted core",
        "choose()/active_table() inside a traced function freezes the "
        "knob at trace time — resolve knobs before entering the jitted "
        "core (see tuning/dispatch.py's contract)",
    ),
    "RS002": (
        "unbucketed pad/shape in the serving tier",
        "serving-tier shapes must come from the bucket ladder "
        "(bucket_for/pad_rows/resolve_ladder) — a Python-value-"
        "dependent shape compiles one XLA program per distinct size",
    ),
    "RS003": (
        "unhashable static argument on a jitted function",
        "static_argnames values are compile-cache keys and must be "
        "hashable — a list/dict/set default or annotation will fail "
        "(or invite per-call conversions that defeat the cache)",
    ),
}

_KNOB_CALLS = frozenset({
    "choose", "dispatch.choose", "tuning.choose",
    "active_table", "dispatch.active_table",
    "install_table", "install_from_env",
})
_BUCKET_HELPERS = frozenset({
    "bucket_for", "pad_rows", "bucket_ladder", "resolve_ladder",
    "bk.bucket_for", "bk.pad_rows", "bk.bucket_ladder",
    "buckets.bucket_for", "buckets.pad_rows", "buckets.bucket_ladder",
})
# the helpers themselves (and the registry's one implementation) are
# where the raw pad/shape code is SUPPOSED to live
_HELPER_DEFS = frozenset({
    "bucket_for", "pad_rows", "bucket_ladder", "resolve_ladder",
})
_PAD_CALLS = frozenset({"np.pad", "jnp.pad", "numpy.pad"})
_SHAPE_CTORS = frozenset({
    "jnp.zeros", "jnp.ones", "jnp.full", "jnp.empty", "jnp.arange",
})
_RS002_SCOPE = ("serving/", "index/mips.py")


def _jit_functions(tree: ast.Module):
    for qual, fn in walk_functions(tree):
        if jit_decorated(fn) or fn.name.endswith("_jit"):
            yield qual, fn


class RecompileSafetyPass:
    rules = RULE_DOCS

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        for m in modules:
            if m.root_kind == "tests":
                continue
            self._rs001(m, findings)
            if m.root_kind == "package" and m.rel.startswith(_RS002_SCOPE):
                self._rs002(m, findings)
            self._rs003(m, findings)
        return findings

    def _rs001(self, m: Module, findings: list[Finding]) -> None:
        for qual, fn in _jit_functions(m.tree):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node) or ""
                if cn in _KNOB_CALLS or cn.endswith(".dispatch.choose"):
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="RS001",
                        symbol=qual,
                        message=(
                            f"{cn}() inside jitted core {fn.name!r} — "
                            "the knob's value is frozen at trace time; "
                            "resolve it in the wrapper, pass it in as "
                            "a static arg"
                        ),
                    ))

    def _rs002(self, m: Module, findings: list[Finding]) -> None:
        for qual, fn in walk_functions(m.tree):
            if fn.name in _HELPER_DEFS:
                continue
            params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
            bucket_sane = "bucket" in params or any(
                isinstance(n, ast.Call)
                and (call_name(n) or "") in _BUCKET_HELPERS
                for n in ast.walk(fn)
            )
            if bucket_sane:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node) or ""
                if cn in _PAD_CALLS:
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="RS002",
                        symbol=qual,
                        message=(
                            f"{cn}() without a bucket-ladder-derived "
                            "size — pad through serving.buckets."
                            "pad_rows/bucket_for so the compiled-shape "
                            "set stays bounded"
                        ),
                    ))
                elif cn in _SHAPE_CTORS and any(
                    isinstance(sub, ast.Call)
                    and call_name(sub) == "len"
                    for a in node.args[:1]
                    for sub in ast.walk(a)
                ):
                    findings.append(Finding(
                        path=m.repo_rel, line=node.lineno, rule="RS002",
                        symbol=qual,
                        message=(
                            f"{cn}(len(...)) — a Python-value-dependent "
                            "device shape compiles per distinct size; "
                            "round it through the bucket ladder"
                        ),
                    ))

    def _rs003(self, m: Module, findings: list[Finding]) -> None:
        for qual, fn in walk_functions(m.tree):
            statics = set(static_argnames(fn))
            if not statics or not jit_decorated(fn):
                continue
            for a in fn.args.args + fn.args.kwonlyargs:
                if a.arg not in statics:
                    continue
                ann = a.annotation
                if isinstance(ann, ast.Subscript):
                    base = (call_name(ann.value) if isinstance(
                        ann.value, ast.Call) else None) or (
                        ann.value.id if isinstance(ann.value, ast.Name)
                        else None
                    )
                    if base in ("list", "dict", "set", "List", "Dict",
                                "Set"):
                        findings.append(Finding(
                            path=m.repo_rel, line=a.lineno, rule="RS003",
                            symbol=qual,
                            message=(
                                f"static arg {a.arg!r} annotated as "
                                f"unhashable {base} — static args are "
                                "compile-cache keys; use a tuple/"
                                "frozenset"
                            ),
                        ))
            defaults = fn.args.defaults
            pos = fn.args.args
            pairs = list(zip(pos[len(pos) - len(defaults):], defaults))
            pairs += [
                (a, d) for a, d in zip(fn.args.kwonlyargs,
                                       fn.args.kw_defaults)
                if d is not None
            ]
            for a, d in pairs:
                if a.arg in statics and isinstance(
                    d, (ast.List, ast.Dict, ast.Set, ast.DictComp,
                        ast.ListComp, ast.SetComp)
                ):
                    findings.append(Finding(
                        path=m.repo_rel, line=d.lineno, rule="RS003",
                        symbol=qual,
                        message=(
                            f"static arg {a.arg!r} defaults to an "
                            "unhashable literal — static args are "
                            "compile-cache keys; use a tuple/frozenset"
                        ),
                    ))
