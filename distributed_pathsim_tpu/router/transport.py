"""How the router reaches a worker: subprocess pipes or an in-process thread.

Both transports present the same tiny surface to the router core —
``start(on_message, on_death)``, ``send(dict)``, ``kill()``,
``terminate()`` — so the failover/hedging/fencing machinery is tested
against the exact code that runs in production:

- :class:`SubprocessTransport` — a real ``dpathsim worker`` child
  process, JSONL over its stdin/stdout. Death is detected two ways:
  the reader thread sees EOF (process exited → ``on_death``), and any
  ``send`` into a broken pipe raises :class:`WorkerGone`.
- :class:`InprocTransport` — a :class:`~.worker.WorkerRuntime` driven
  by a queue on a daemon thread. ``kill()`` simulates a hard kill
  deterministically: replies are suppressed from that instant (the
  pipe is gone), queued and in-flight requests are lost, ``on_death``
  fires. This is what the chaos tests use — same runtime code, no
  subprocess startup cost, and fault-plan seams fire in-process where
  the test can assert on them.

Thread-safety: ``send`` may be called from any router thread (writer
lock per transport); ``on_message``/``on_death`` are invoked from the
transport's reader thread and must not block for long.
"""

from __future__ import annotations

import json
import os
import queue
import subprocess
import threading
from typing import Callable

from ..resilience import inject
from ..utils.logging import runtime_event
from .worker import WorkerRuntime

OnMessage = Callable[[str, dict], None]
OnDeath = Callable[[str, str], None]


class WorkerGone(RuntimeError):
    """The transport's peer is dead; the send did not happen."""


class SubprocessTransport:
    """One ``dpathsim worker`` child process.

    ``argv`` is the full child command line (the router CLI builds it
    from its own serving flags); stderr passes through to the parent's
    so worker runtime events stay operator-visible."""

    def __init__(self, worker_id: str, argv: list[str],
                 env: dict | None = None):
        self.worker_id = worker_id
        self.argv = list(argv)
        self.env = dict(env) if env is not None else dict(os.environ)
        self.ready_info: dict | None = None
        self._ready = threading.Event()
        self._proc: subprocess.Popen | None = None
        self._wlock = threading.Lock()
        self._dead = False
        self._on_message: OnMessage | None = None
        self._on_death: OnDeath | None = None

    def start(self, on_message: OnMessage, on_death: OnDeath) -> None:
        self._on_message = on_message
        self._on_death = on_death
        self._proc = subprocess.Popen(
            self.argv,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=None,  # inherit: worker events reach the operator
            text=True,
            env=self.env,
        )
        threading.Thread(
            target=self._read_loop,
            name=f"pathsim-router-read-{self.worker_id}",
            daemon=True,
        ).start()

    @property
    def alive(self) -> bool:
        return (
            not self._dead
            and self._proc is not None
            and self._proc.poll() is None
        )

    def wait_ready(self, timeout: float = 120.0) -> dict:
        """Block until the worker's ``ready`` event (startup includes a
        backend build + bucket warmup — allow for it)."""
        if not self._ready.wait(timeout):
            raise TimeoutError(
                f"worker {self.worker_id} not ready in {timeout}s"
            )
        return self.ready_info or {}

    def send(self, obj: dict) -> None:
        proc = self._proc
        if self._dead or proc is None or proc.poll() is not None:
            raise WorkerGone(f"worker {self.worker_id} is dead")
        line = json.dumps(obj) + "\n"
        try:
            with self._wlock:
                proc.stdin.write(line)
                proc.stdin.flush()
        except (BrokenPipeError, OSError) as exc:
            raise WorkerGone(
                f"worker {self.worker_id} pipe broken: {exc}"
            ) from exc

    def _read_loop(self) -> None:
        proc = self._proc
        assert proc is not None and proc.stdout is not None
        for line in proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                runtime_event(
                    "router_worker_garbage", worker_id=self.worker_id,
                    line=line[:120], echo=False,
                )
                continue
            if obj.get("event") == "ready":
                self.ready_info = obj
                self._ready.set()
            if self._on_message is not None:
                try:
                    self._on_message(self.worker_id, obj)
                except Exception as exc:
                    # a handler bug must not kill the reader thread —
                    # that would silently drop every later response
                    runtime_event(
                        "router_handler_error", worker_id=self.worker_id,
                        error=repr(exc),
                    )
        # EOF: the worker exited (clean drain or a crash — the exit
        # code distinguishes them for the death event)
        rc = proc.wait()
        if not self._dead:
            self._dead = True
            if self._on_death is not None:
                self._on_death(self.worker_id, f"exit {rc}")

    def kill(self) -> None:
        """Hard kill (SIGKILL): the chaos path — no drain, no goodbye;
        the reader's EOF delivers the death."""
        if self._proc is not None:
            self._proc.kill()

    def terminate(self) -> None:
        """Graceful stop request (SIGTERM → worker drain)."""
        if self._proc is not None:
            self._proc.terminate()

    def close(self, timeout: float = 10.0) -> None:
        self._dead = True
        proc = self._proc
        if proc is None:
            return
        if proc.poll() is None:
            try:
                self.send_quiet({"op": "shutdown"})
            except Exception:
                pass
            try:
                proc.wait(timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        for stream in (proc.stdin, proc.stdout):
            try:
                if stream:
                    stream.close()
            except OSError:
                pass

    def send_quiet(self, obj: dict) -> None:
        """close()'s best-effort goodbye: bypasses the dead-flag guard
        (close sets it first so on_death stays quiet)."""
        proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        with self._wlock:
            proc.stdin.write(json.dumps(obj) + "\n")
            proc.stdin.flush()


_SHUTDOWN = object()


class InprocTransport:
    """A WorkerRuntime on a thread, for deterministic tests.

    Construction takes the runtime (the caller owns the service and its
    teardown). ``kill()`` makes the loss WINDOW explicit: everything
    queued or in flight at that instant is gone, exactly like a killed
    process — the router's zero-lost-request property is only meaningful
    if the test can create real loss."""

    def __init__(self, worker_id: str, runtime: WorkerRuntime):
        self.worker_id = worker_id
        self.runtime = runtime
        self._q: queue.Queue = queue.Queue()
        self._killed = False
        self._started = False
        self._on_message: OnMessage | None = None
        self._on_death: OnDeath | None = None
        self.ready_info: dict | None = None
        self._ready = threading.Event()

    def start(self, on_message: OnMessage, on_death: OnDeath) -> None:
        self._on_message = on_message
        self._on_death = on_death
        self._started = True
        threading.Thread(
            target=self._loop,
            name=f"pathsim-inproc-worker-{self.worker_id}",
            daemon=True,
        ).start()

    @property
    def alive(self) -> bool:
        return self._started and not self._killed

    def wait_ready(self, timeout: float = 30.0) -> dict:
        # genuinely wait: the loop thread publishes ready_info; a racy
        # empty return here would seed the router with a (None, 0)
        # token and permanently fence the replica
        if not self._ready.wait(timeout):
            raise TimeoutError(
                f"inproc worker {self.worker_id} not ready in {timeout}s"
            )
        return self.ready_info or {}

    def _emit(self, obj: dict) -> None:
        # a killed worker's pipe is gone: replies vanish, they don't
        # arrive late — dedup at the router handles the OTHER race
        # (answer already sent when the kill landed)
        if self._killed:
            return
        if self._on_message is not None:
            try:
                self._on_message(self.worker_id, obj)
            except Exception as exc:
                # same contract as the subprocess reader: a router
                # handler bug must not poison the worker's threads
                runtime_event(
                    "router_handler_error", worker_id=self.worker_id,
                    error=repr(exc),
                )

    def _loop(self) -> None:
        svc = self.runtime.service
        self.ready_info = {
            "event": "ready", "worker_id": self.worker_id, "n": svc.n,
            "backend": svc.backend.name,
            "base_fp": svc.consistency_token[0],
            "delta_seq": svc.consistency_token[1],
        }
        self._ready.set()
        self._emit(self.ready_info)
        while True:
            req = self._q.get()
            if req is _SHUTDOWN or self._killed:
                return
            try:
                directive = self.runtime.handle(req, self._emit)
            except inject.InjectedCrash:
                # the chaos hard-kill: the "process" dies mid-request
                self.kill()
                return
            except Exception as exc:
                # an unhandled exception kills a real worker process
                # too (EOF → on_death) — mirror that, don't hang
                runtime_event(
                    "worker_crash", worker_id=self.worker_id,
                    error=repr(exc),
                )
                self.kill()
                return
            if directive == "shutdown":
                self.runtime.wait_idle()
                return
            if directive == "drain":
                self.runtime.wait_idle()
                self._emit({"event": "drained",
                            "worker_id": self.worker_id, "clean": True})
                self._die("exit 0")
                return

    def send(self, obj: dict) -> None:
        if self._killed:
            raise WorkerGone(f"worker {self.worker_id} is dead")
        self._q.put(obj)

    def kill(self) -> None:
        if self._killed:
            return
        self._killed = True
        # drop everything queued: a killed process never saw it
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._q.put(_SHUTDOWN)  # wake the loop so the thread exits
        if self._on_death is not None:
            self._on_death(self.worker_id, "killed")

    def terminate(self) -> None:
        """Graceful stop: the in-band drain op."""
        self.send({"op": "drain"})

    def _die(self, reason: str) -> None:
        if not self._killed:
            self._killed = True
            if self._on_death is not None:
                self._on_death(self.worker_id, reason)

    def close(self, timeout: float = 10.0) -> None:
        self._killed = True
        self._q.put(_SHUTDOWN)
