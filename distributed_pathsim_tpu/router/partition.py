"""PartitionRouter: one graph sharded across many workers.

PR 6's :class:`~.core.Router` fans whole queries over N *replicas* of
one graph — the largest servable HIN is whatever fits one worker. This
router shards the graph itself (DESIGN.md §26): each worker holds a
contiguous row-range slice of the half-chain factor (plus chained
mirrors of its successors' ranges, so every range survives worker
deaths), and a query becomes a two-phase scatter-gather over the wire:

1. **tile_pull** — fetch the source row's factor tile ``C[s, :]`` from
   a holder of the owning range (the boundary-column exchange; the
   jax-sharded backend's ring-step does the same dance across chips,
   this is the same exchange lifted onto the router's wire);
2. **partial_topk / partial_scores** — scatter the tile to ONE holder
   per range; each scores its own rows locally and returns top-k
   candidates (exact integer counts + denominators, oracle tie order);
3. **merge** — the router recomputes every candidate's f64 score with
   ``ops.pathsim.score_candidates`` and selects with
   ``topk_from_candidate_scores`` (the PR-7 exact-merge primitives).
   Since each range's true top-k is a prefix of its local order, the
   union of per-range top-k covers the global top-k, and every number
   entering the merge is an exact integer — the result is bit-identical
   to a single-host oracle, (−score, ascending col) ties included.

Robustness inherits the PR-6 contracts one level down:

- **Zero lost requests**: every sub-request (tile or partial) of a
  pending query is re-dispatched to another holder of its range when a
  worker dies mid-batch; chained replication guarantees a surviving
  holder for every range up to ``replication − 1`` deaths.
- **Routed deltas**: an ``update`` broadcast becomes a two-phase routed
  delta — phase 1 (``part_update``) applies the row-filtered delta at
  every holder (O(Δ) re-encode, owners only) and returns per-range
  Δcolsum contributions; the router aggregates exactly one contribution
  per range (integer sums: holder-independent) and phase 2
  (``set_colsum``) seals the new global denominators. Fencing is
  per-partition: each range carries a row epoch and the fleet a colsum
  epoch; a worker that missed a phase lags the head, is fenced from
  dispatch, and is caught up by ordered idempotent replay
  (request-id dedup at the worker).
- **Epoch-coherent answers**: every partial response carries the
  worker's sealed update seq; a scatter whose parts straddle an update
  is detected at merge and restarted — a query answers from ONE graph
  epoch, never a mix.
- **Observability**: the router emits the same request/latency metric
  families the replicate router does (the PR-9 SLO engine runs
  unchanged over the merged stream and judges the worst partition
  through the per-worker scrape), plus per-partition dispatch
  counters; slow/errored/failed-over requests land in the flight
  recorder. Every scatter opens a fleet-level root span and every
  sub-request (``resolve`` / ``tile_pull`` / per-range partials)
  carries its dispatch span's context on the wire, so a
  partition-mode request stitches into ONE cross-process Perfetto
  tree (``collect_trace_parts``/``write_fleet_trace``; zero broken
  parent links gated in ``make partition-smoke``).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time

import numpy as np

from concurrent.futures import Future

from ..data.partition import PartitionMap
from ..obs import fleet as obs_fleet
from ..obs.flight import FlightRecorder
from ..obs.metrics import get_registry
from ..obs.slo import SLOEngine, default_specs
from ..obs.trace import get_tracer, to_wire
from ..ops import pathsim
from ..resilience import Deadline, inject
from ..utils.logging import runtime_event
from .core import DOWN, SUSPECT, UP, RouterShed
from .transport import WorkerGone

# client ops this router scatters over partitions (a subset of
# serving.protocol.PROTOCOL_OPS, like core.ROUTED_OPS)
SCATTER_OPS = frozenset({"topk", "scores"})

# merge-time epoch-mismatch restarts before a query fails: each restart
# means an update sealed mid-scatter, so >3 in a row is a stuck fleet,
# not bad luck
_MAX_RESTARTS = 3


@dataclasses.dataclass
class PartitionRouterConfig:
    partitions: int = 2
    replication: int = 2
    heartbeat_interval_s: float = 0.25
    heartbeat_miss_limit: int = 4
    max_inflight: int = 512
    default_deadline_ms: float | None = None
    max_attempts: int = 4            # holders tried per sub-request
    update_timeout_s: float = 60.0
    drain_timeout_s: float = 30.0
    park_timeout_s: float = 10.0
    ready_timeout_s: float = 180.0
    scrape_interval_s: float = 5.0
    slo_specs: tuple = ()
    slow_ms: float | None = None
    flight_capacity: int = 256


class _PartWorker:
    __slots__ = (
        "wid", "index", "transport", "status", "last_pong",
        "applied_seq", "colsum_seq", "row_seq", "held", "ready",
        "catchup_active", "last_health", "pong_seq",
        "last_metrics", "metrics_seq", "metrics_mono",
    )

    def __init__(self, wid: str, index: int, transport):
        self.wid = wid
        self.index = index
        self.transport = transport
        self.status = UP
        self.last_pong = time.monotonic()
        self.applied_seq = 0
        self.colsum_seq = 0
        self.row_seq: dict[int, int] = {}
        self.held: tuple[int, ...] = ()
        self.ready = False
        self.catchup_active = False
        self.last_health: dict = {}
        self.pong_seq = 0
        self.last_metrics: dict | None = None
        self.metrics_seq = 0
        self.metrics_mono = 0.0


class _Scatter:
    """One pending client query across its sub-requests. ``assigned``
    maps a sub-request key — ``"rs"`` (resolve), ``"tl"`` (tile), or a
    range index — to the worker currently carrying it."""

    __slots__ = (
        "rid", "req", "op", "future", "row", "k", "deadline", "t0",
        "stage", "tile", "parts", "assigned", "tried", "failovers",
        "restarts", "parked", "span", "sub_spans",
    )

    def __init__(self, rid, req, op, future, row, k, deadline,
                 span=None):
        self.rid = rid
        self.req = req
        self.op = op
        self.future = future
        self.row = row
        self.k = k
        self.deadline = deadline
        self.t0 = time.monotonic()
        self.stage = "resolve" if row is None else "tile"
        self.tile: dict | None = None
        self.parts: dict[int, dict] = {}
        self.assigned: dict = {}
        self.tried: dict = {}
        self.failovers = 0
        self.restarts = 0
        self.parked = False
        # tracing: the fleet-level root span and one child span per
        # sub-request dispatch (resolve / tile_pull / partial per
        # range), each carried to its worker on the wire so a
        # partition-mode request renders as ONE Perfetto tree
        self.span = span
        self.sub_spans: dict = {}


class _Epoch:
    """One routed delta in the replay log: the phase wires (stable
    ``request_id`` per phase — what makes catch-up replays idempotent)
    and the ranges whose rows it re-encoded."""

    __slots__ = ("seq", "part_wire", "colsum_wire", "ranges", "rid")

    def __init__(self, seq, part_wire, colsum_wire, ranges, rid):
        self.seq = seq
        self.part_wire = part_wire
        self.colsum_wire = colsum_wire
        self.ranges = ranges
        self.rid = rid


class _Collector:
    """Fan-out ack collection for one broadcast phase."""

    def __init__(self, waiting):
        self._cv = threading.Condition()
        self.waiting = set(waiting)
        self.acks: dict[str, dict] = {}
        self.failures: dict[str, str] = {}

    def resolve(self, wid: str, obj: dict | None, error: str | None) -> None:
        with self._cv:
            if wid not in self.waiting:
                return
            self.waiting.discard(wid)
            if error is not None:
                self.failures[wid] = error
            else:
                self.acks[wid] = obj or {}
            if not self.waiting:
                self._cv.notify_all()

    def wait(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cv:
            while self.waiting:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    for wid in list(self.waiting):
                        self.failures[wid] = "ack timeout"
                    self.waiting.clear()
                    return False
                self._cv.wait(remaining)
        return True


class PartitionRouter:
    """Owns P partition-worker transports (worker ``w{i}`` carries
    partition index ``i``) and the scatter-gather pending table.
    ``transports`` is ``{worker_id: transport}``; worker ids must be
    ``w0..w{P-1}`` so partition indices are unambiguous."""

    def __init__(self, transports: dict,
                 config: PartitionRouterConfig | None = None):
        if not transports:
            raise ValueError("partition router needs at least one worker")
        self.config = config or PartitionRouterConfig()
        if len(transports) != self.config.partitions:
            raise ValueError(
                f"{len(transports)} transports for "
                f"{self.config.partitions} partitions — partition mode "
                "runs exactly one worker per partition index"
            )
        self._lock = threading.RLock()
        self.workers: dict[str, _PartWorker] = {}
        for i in range(self.config.partitions):
            wid = f"w{i}"
            if wid not in transports:
                raise ValueError(f"missing transport for {wid}")
            self.workers[wid] = _PartWorker(wid, i, transports[wid])
        self.pmap: PartitionMap | None = None
        self.n = 0
        self.v = 0
        self._base_fp: str | None = None
        self._pending: dict[str, _Scatter] = {}
        self._epochs: list[_Epoch] = []
        self._compacted_to = 0
        self._head_seq = 0
        self._head_row_seq: dict[int, int] = {}
        self._rid_seq = itertools.count(1)
        self._hb_seq = itertools.count(1)
        self._mx_seq = itertools.count(1)
        # update ATTEMPTS get distinct request_ids (an aborted seq is
        # retried under a fresh attempt — reusing the id would let the
        # workers' dedup replay the aborted attempt's cached acks)
        self._attempt_seq = itertools.count(1)
        self._update_lock = threading.Lock()
        self._updating = False
        self._collectors: dict[str, _Collector] = {}
        self._draining = False
        self._closed = threading.Event()
        self._maintenance: threading.Thread | None = None
        reg = get_registry()
        self._m_requests = reg.counter(
            "dpathsim_router_requests_total",
            "router requests by outcome",
        )
        self._m_latency = reg.histogram(
            "dpathsim_router_request_seconds",
            "router submit-to-resolve latency by outcome",
        )
        self._m_failovers = reg.counter(
            "dpathsim_router_failovers_total",
            "re-dispatches after worker death/stall/retriable failure",
        )
        self._m_part_dispatch = reg.counter(
            "dpathsim_partition_dispatch_total",
            "partial sub-requests dispatched, by partition index",
        )
        self._m_restarts = reg.counter(
            "dpathsim_partition_epoch_restarts_total",
            "scatters restarted because an update sealed mid-flight",
        ).labels()
        specs = tuple(self.config.slo_specs) or default_specs()
        self.slo = SLOEngine(specs, on_alert=self._on_slo_alert)
        slow_ms = self.config.slow_ms
        if slow_ms is None:
            slow_ms = next(
                (s.threshold * 1e3 for s in specs
                 if s.kind == "latency" and s.threshold), 1000.0,
            )
        self._slow_s = float(slow_ms) / 1e3
        self.flight = FlightRecorder(self.config.flight_capacity)
        self._shutdown_dumped = False
        # optional shutdown artifact paths (set by the CLI): flight
        # records AND the stitched fleet trace — partition scatters
        # carry trace context on every sub-request wire, so a
        # partition-mode request is one connected cross-process tree
        # (the PR-11 follow-up; audited in ``make partition-smoke``)
        self.flight_out: str | None = None
        self.fleet_trace_out: str | None = None
        self.trace_scrape_limit = 20_000

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        cfg = self.config
        for w in self.workers.values():
            w.transport.start(self._on_message, self._on_death)
        fps = {}
        for w in self.workers.values():
            info = w.transport.wait_ready(cfg.ready_timeout_s)
            fps[w.wid] = info.get("base_fp")
            self.n = max(self.n, int(info.get("n", 0)))
        base = next(iter(fps.values()))
        if any(fp != base for fp in fps.values()):
            raise ValueError(
                f"partitions disagree on the base graph: {fps} — every "
                "partition must slice the same dataset/config"
            )
        self._base_fp = base
        self.pmap = PartitionMap(n=max(self.n, 1), p=cfg.partitions)
        # transports are live (reader threads touch worker state under
        # the lock), so seed shared maps under it too
        with self._lock:
            self._head_row_seq = {g: 0 for g in range(cfg.partitions)}
        self._exchange_colsum()
        now = time.monotonic()
        with self._lock:
            for w in self.workers.values():
                w.last_pong = now
        self._maintenance = threading.Thread(
            target=self._maintenance_loop,
            name="pathsim-partrouter-maint", daemon=True,
        )
        self._maintenance.start()
        runtime_event(
            "partition_router_ready", partitions=cfg.partitions,
            replication=cfg.replication, n=self.n, v=self.v,
            fingerprint=base,
        )

    def _exchange_colsum(self) -> None:
        """Startup boundary exchange: pull every worker's per-range
        colsum contribution, aggregate exactly one per range (integer
        sums — any holder's contribution is bit-identical), broadcast
        the global colsum. Workers cannot score anything before this."""
        acks, _failures = self._broadcast(
            {"op": "part_info", "request_id": "pi0"}, "pi",
            timeout=self.config.update_timeout_s,
        )
        if not acks:
            raise RuntimeError("no partition answered part_info")
        by_range: dict[int, dict] = {}
        v = 0
        for wid in sorted(acks):
            result = acks[wid].get("result") or {}
            v = max(v, int(result.get("v") or 0))
            part = result.get("partition") or {}
            with self._lock:
                w = self.workers.get(wid)
                if w is not None:
                    w.held = tuple(int(g) for g in part.get("held") or ())
                    w.row_seq = {int(g): 0 for g in w.held}
            for g_str, payload in (result.get("colsum") or {}).items():
                g = int(g_str)
                # prefer the owner's contribution; any holder's is
                # bit-identical, so first-by-sorted-wid is fine too
                if g not in by_range or self.workers[wid].index == g:
                    by_range[g] = payload
        self.v = v
        missing = [
            g for g in range(self.config.partitions)
            if g not in by_range and self.pmap.range_of(g)[0]
            < self.pmap.range_of(g)[1]
        ]
        g_sum = np.zeros(max(v, 1), dtype=np.float64)
        for payload in by_range.values():
            cols = np.asarray(payload.get("cols") or [], dtype=np.int64)
            vals = np.asarray(payload.get("vals") or [], dtype=np.float64)
            g_sum[cols] += vals
        nz = np.flatnonzero(g_sum)
        wire = {
            "op": "set_colsum", "mode": "init", "request_id": "pc0",
            "cols": [int(c) for c in nz],
            "vals": [float(g_sum[c]) for c in nz],
        }
        acks, _failures = self._broadcast(
            wire, "ci", timeout=self.config.update_timeout_s,
        )
        with self._lock:
            for wid in acks:
                w = self.workers.get(wid)
                if w is not None:
                    w.ready = True
        if missing:
            # a range with rows but no contribution would silently
            # zero its denominators — refuse to serve that
            raise RuntimeError(
                f"no colsum contribution for ranges {missing}"
            )
        runtime_event(
            "partition_colsum_exchanged", v=self.v,
            nnz=int(nz.shape[0]), workers=sorted(acks), echo=False,
        )

    def _broadcast(self, wire: dict, tag: str, timeout: float,
                   targets=None) -> tuple[dict, dict]:
        """Send one request to every live worker (or ``targets``),
        collect acks. Returns ({wid: ok-response}, {wid: error})."""
        token = f"{tag}{next(self._mx_seq)}"  # no ':' — it delimits ids
        with self._lock:
            if targets is None:
                targets = [
                    w for w in self.workers.values()
                    if w.status != DOWN and w.transport.alive
                ]
            col = _Collector([w.wid for w in targets])
            self._collectors[token] = col
        try:
            for w in targets:
                per = dict(wire)
                per["id"] = f"cl:{token}:{w.wid}"
                try:
                    if tag in ("up", "cs"):
                        # the delta_broadcast chaos seam: an injected
                        # error means THIS partition misses the phase —
                        # it lags the head and is fenced until catch-up
                        # replay
                        inject.fire("delta_broadcast")
                    w.transport.send(per)
                except (inject.InjectedFault, WorkerGone) as exc:
                    col.resolve(w.wid, None, repr(exc))
            col.wait(timeout)
        finally:
            # exactly-once: an exception between registration and this
            # removal must not leave a dead collector entry that every
            # later _mark_down walks forever (EX003)
            with self._lock:
                self._collectors.pop(token, None)
        acks = {
            wid: obj for wid, obj in col.acks.items() if obj.get("ok")
        }
        failures = dict(col.failures)
        for wid, obj in col.acks.items():
            if not obj.get("ok"):
                failures[wid] = str(obj.get("error", "?"))
        return acks, failures

    def close(self) -> None:
        self._closed.set()
        for w in self.workers.values():
            w.transport.close()

    def drain(self) -> bool:
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + self.config.drain_timeout_s
        clean = True
        with self._lock:
            pending = len(self._pending)
        while time.monotonic() < deadline:
            with self._lock:
                pending = len(self._pending)
                if not pending and not self._updating:
                    break
            time.sleep(0.005)
        else:
            clean = False
        # dumps BEFORE the workers terminate: the stitched trace needs
        # one last span-ring scrape, and a drained worker can't answer
        self._shutdown_dumps()
        for w in self.workers.values():
            if w.transport.alive:
                try:
                    w.transport.terminate()
                except Exception:
                    pass
        runtime_event("partition_router_drain", clean=clean,
                      pending=pending)
        return clean

    def collect_trace_parts(self, timeout: float = 5.0) -> list[dict]:
        """The stitched-export inputs: this process's span ring plus a
        ``trace``-op scrape of every live partition worker (same
        contract as the replicate router's: a SIGKILLed worker's
        un-scraped spans are absence, not breakage)."""
        limit = self.trace_scrape_limit
        acks, _failures = self._broadcast(
            {"op": "trace", "limit": limit}, "tr", timeout=timeout,
        )
        parts = [{**get_tracer().export_state(limit=limit),
                  "process": "router"}]
        for wid in sorted(acks):
            result = acks[wid].get("result") or {}
            if "spans" in result:
                parts.append({**result, "process": f"worker {wid}"})
        return parts

    def write_fleet_trace(self, path: str,
                          parts: list[dict] | None = None) -> int:
        """One stitched Perfetto file for the partition fleet; returns
        the span-event count."""
        if parts is None:
            parts = self.collect_trace_parts()
        n = obs_fleet.write_fleet_trace(path, parts)
        runtime_event("fleet_trace_written", path=path, spans=n)
        return n

    def _shutdown_dumps(self) -> None:
        if self._shutdown_dumped:
            return
        self._shutdown_dumped = True
        if not (self.flight_out or self.fleet_trace_out):
            return
        try:
            parts = (
                self.collect_trace_parts()
                if get_tracer().enabled and self.fleet_trace_out
                else []
            )
            if self.flight_out:
                info = self.flight.dump(self.flight_out, parts)
                runtime_event("flight_dump", **info)
            if self.fleet_trace_out:
                self.write_fleet_trace(self.fleet_trace_out, parts=parts)
        except Exception as exc:
            runtime_event("fleet_dump_failed", error=repr(exc))

    # -- submission --------------------------------------------------------

    def submit(self, req: dict) -> Future:
        op = req.get("op", "topk")
        fut: Future = Future()
        with self._lock:
            draining = self._draining
        if draining:
            fut.set_result({"id": req.get("id"), "ok": False,
                            "error": "draining", "draining": True})
            return fut
        if op == "ping":
            fut.set_result({"id": req.get("id"), "ok": True,
                            "result": {"pong": True}})
            return fut
        if op in ("stats", "health"):
            fut.set_result({"id": req.get("id"), "ok": True,
                            "result": self.stats()})
            return fut
        if op == "fleet_metrics":
            resp = {"id": req.get("id"), "ok": True,
                    "result": self.fleet_metrics(
                        refresh=bool(req.get("refresh", True)))}
            if req.get("request_id") is not None:
                resp["request_id"] = req.get("request_id")
            fut.set_result(resp)
            return fut
        if op == "flight_dump":
            fut.set_result({"id": req.get("id"), "ok": True,
                            "result": self.flight.snapshot()})
            return fut
        if op == "update":
            return self._submit_update(req, fut)
        if op not in SCATTER_OPS:
            fut.set_result({"id": req.get("id"), "ok": False,
                            "error": f"unknown op {op!r}"})
            return fut
        # the fleet-level trace root: head sampling decides here, once
        # for the whole scatter — every sub-request wire propagates it
        root = get_tracer().start_span(
            "router.request", op=op, row=req.get("row"), mode="partition",
        )
        with self._lock:
            if len(self._pending) >= self.config.max_inflight:
                self._m_requests.inc(outcome="shed")
                get_tracer().finish(root, outcome="shed")
                self.flight.keep(["shed"], op=op, row=req.get("row"),
                                 where="admission")
                raise RouterShed(
                    f"router pending table at bound "
                    f"({self.config.max_inflight})"
                )
            rid = f"r{next(self._rid_seq)}"
            row = req.get("row")
            row = int(row) if row is not None else None
            k = int(req.get("k") or 10)
            deadline = Deadline.from_ms(
                req.get("deadline_ms", self.config.default_deadline_ms)
            )
            p = _Scatter(rid, req, op, fut, row, k, deadline, span=root)
            self._pending[rid] = p
        self._advance(p)
        return fut

    def request(self, req: dict, timeout: float = 60.0) -> dict:
        return self.submit(req).result(timeout=timeout)

    # -- scatter dispatch --------------------------------------------------

    def _holders(self, g: int) -> list[str]:
        """Preference-ordered worker ids holding range ``g``."""
        return [
            f"w{i}"
            for i in self.pmap.holders_of(g, self.config.replication)
        ]

    def _eligible(self, p: _Scatter, key, holders) -> tuple[str | None, str]:
        """Next worker for sub-request ``key``, under the lock."""
        tried = p.tried.setdefault(key, set())
        fenced = live = 0
        for wid in holders:
            w = self.workers.get(wid)
            if w is None or w.status != UP or not w.transport.alive:
                continue
            live += 1
            if wid in tried:
                continue
            if not w.ready or w.colsum_seq != self._head_seq:
                fenced += 1
                continue
            if isinstance(key, int):
                if self._head_row_seq.get(key, 0) != w.row_seq.get(key, -1):
                    fenced += 1
                    continue
            return wid, ""
        if fenced:
            return None, "fenced"
        if live:
            return None, "exhausted"
        return None, "no live holders"

    def _advance(self, p: _Scatter) -> None:
        """Dispatch whatever the scatter's current stage needs. Any
        sub-request that cannot be placed parks the whole query (a
        holder coming back, catching up, or an update sealing makes it
        placeable again)."""
        if p.deadline is not None and p.deadline.expired:
            self._fail(p, "deadline exceeded")
            return
        with self._lock:
            if p.rid not in self._pending:
                return
            if self._updating:
                p.parked = True
                return
        if p.stage == "resolve":
            self._dispatch_sub(
                p, "rs",
                [w.wid for w in self.workers.values()],
                {"op": "resolve",
                 "source": p.req.get("source"),
                 "source_id": p.req.get("source_id")},
            )
            return
        if p.stage == "tile":
            g0 = self.pmap.owner_of(p.row)
            self._dispatch_sub(
                p, "tl", self._holders(g0),
                {"op": "tile_pull", "row": p.row},
            )
            return
        # stage "parts": one partial per non-empty range not yet answered
        for g in range(self.config.partitions):
            lo, hi = self.pmap.range_of(g)
            if lo >= hi:
                continue
            with self._lock:
                have = g in p.parts or g in p.assigned
            if have:
                continue
            # per-op wires: partial_scores ignores row/k (the full
            # slice includes the self pair by definition), so sending
            # them was dead weight the schema gate flags (WC103)
            if p.op == "topk":
                wire = {
                    "op": "partial_topk",
                    "range": g, "row": p.row, "k": p.k,
                    "cols": p.tile.get("cols"),
                    "vals": p.tile.get("vals"),
                    "d_source": p.tile.get("d_source"),
                }
            else:
                wire = {
                    "op": "partial_scores",
                    "range": g,
                    "cols": p.tile.get("cols"),
                    "vals": p.tile.get("vals"),
                    "d_source": p.tile.get("d_source"),
                }
            if not self._dispatch_sub(p, g, self._holders(g), wire):
                return  # parked or failed; stop fanning out

    def _dispatch_sub(self, p: _Scatter, key, holders, wire: dict) -> bool:
        """Place one sub-request; True if it went out (or the query is
        already resolved), False if the query parked/failed instead."""
        while True:
            if p.deadline is not None and p.deadline.expired:
                self._fail(p, "deadline exceeded")
                return False
            exhausted = False
            with self._lock:
                if p.rid not in self._pending:
                    return True
                tried = p.tried.setdefault(key, set())
                if len(tried) >= self.config.max_attempts:
                    exhausted = True
                    wid = None
                else:
                    wid, why = self._eligible(p, key, holders)
            if exhausted:
                # the replicate router's fail-fast bound, per
                # sub-request: a key refused by max_attempts distinct
                # holders fails instead of cycling forever
                self._fail(p, "max attempts exhausted")
                return False
            if wid is None:
                self._park_or_fail(p, why)
                return False
            tracer = get_tracer()
            with self._lock:
                if p.rid not in self._pending:
                    return True
                w = self.workers[wid]
                p.tried.setdefault(key, set()).add(wid)
                p.assigned[key] = wid
                attempt = None
                if p.span is not None:
                    # one span per sub-request dispatch, all siblings
                    # under the scatter root; a failed-over
                    # sub-request's earlier span seals as superseded
                    attempt = tracer.start_span(
                        "router.dispatch", parent=p.span.context,
                        worker=wid, sub=str(key), op=wire.get("op"),
                    )
                    tracer.finish(
                        p.sub_spans.pop(key, None), outcome="superseded"
                    )
                    p.sub_spans[key] = attempt
            out = dict(wire)
            sub = key if isinstance(key, str) else f"g{key}"
            out["id"] = f"q:{p.rid}:{sub}"
            out["request_id"] = f"{p.rid}.{sub}"
            if p.deadline is not None:
                out["deadline_ms"] = max(p.deadline.remaining_ms(), 0.0)
            if tracer.enabled:
                # the worker's serve.op span parents under THIS
                # dispatch span; a sampled-out root propagates the
                # drop so the fleet-wide head rate stays configured
                out["trace"] = to_wire(
                    attempt.context if attempt is not None else None,
                    sampled=attempt is not None,
                )
            if isinstance(key, int):
                self._m_part_dispatch.inc(partition=str(key))
            try:
                w.transport.send(out)
                return True
            except WorkerGone:
                with self._lock:
                    if p.assigned.get(key) == wid:
                        del p.assigned[key]
                    tracer.finish(
                        p.sub_spans.pop(key, None), outcome="send_failed"
                    )
                self._mark_down(wid, DOWN, "send failed")

    def _park_or_fail(self, p: _Scatter, verdict: str) -> None:
        if verdict in ("deadline exceeded",):
            self._fail(p, verdict)
            return
        with self._lock:
            recoverable = any(
                w.status in (UP, SUSPECT)
                and (w.transport.alive or w.status == SUSPECT)
                for w in self.workers.values()
            )
            if recoverable and p.rid in self._pending:
                p.parked = True
                runtime_event("partition_router_parked", rid=p.rid,
                              reason=verdict, echo=False)
                return
        self._fail(p, verdict)

    # -- responses ---------------------------------------------------------

    def _on_message(self, wid: str, obj: dict) -> None:
        if "event" in obj:
            return
        rid = obj.get("id")
        if not isinstance(rid, str):
            return
        if rid.startswith("hb:"):
            self._on_pong(wid, obj)
            return
        if rid.startswith("mx:"):
            self._on_metrics(wid, obj)
            return
        if rid.startswith("cl:"):
            token = rid.split(":", 2)[1]
            with self._lock:
                col = self._collectors.get(token)
            if col is not None:
                if obj.get("ok"):
                    col.resolve(wid, obj, None)
                else:
                    col.resolve(wid, None, str(obj.get("error", "?")))
            return
        if rid.startswith("cu:"):
            self._on_catchup_ack(wid, rid, obj)
            return
        if not rid.startswith("q:"):
            return
        parts = rid.split(":", 2)
        if len(parts) != 3:
            return
        _, prid, sub = parts
        with self._lock:
            p = self._pending.get(prid)
            if p is None:
                return
            key = int(sub[1:]) if sub.startswith("g") else sub
            if p.assigned.get(key) != wid:
                return  # a late answer from a failed-over sub-request
            del p.assigned[key]
            get_tracer().finish(
                p.sub_spans.pop(key, None),
                outcome="ok" if obj.get("ok") else "worker_error",
            )
        if not obj.get("ok"):
            retriable = bool(
                obj.get("shed") or obj.get("draining")
                or obj.get("transient")
            ) and not obj.get("deadline_exceeded")
            if not retriable:
                self._fail(p, str(obj.get("error", "worker error")))
                return
            p.failovers += 1
            self._m_failovers.inc(reason="worker_error")
            self._advance(p)
            return
        result = obj.get("result") or {}
        try:
            self._absorb(p, key, result)
        except Exception as exc:
            # a malformed partial (or a merge bug) must resolve the
            # scatter, not leak it: an unhandled exception here is
            # swallowed by the transport reader's guard and the client
            # future would hang forever
            self._fail(p, f"merge failed: {exc!r}")

    def _absorb(self, p: _Scatter, key, result: dict) -> None:
        """Fold one ok sub-response into the scatter and advance."""
        if key == "rs":
            row = result.get("row")
            if row is None:
                self._fail(p, "resolve returned no row")
                return
            p.row = int(row)
            p.stage = "tile"
            self._advance(p)
            return
        if key == "tl":
            if result.get("wrong_owner"):
                # label-resolved row landed off-owner: re-aim
                p.row = int(result.get("row", p.row or 0))
                p.stage = "tile"
                with self._lock:
                    p.tried.pop("tl", None)
                self._advance(p)
                return
            p.tile = result
            p.stage = "parts"
            self._advance(p)
            return
        with self._lock:
            p.parts[key] = result
            done = all(
                g in p.parts
                for g in range(self.config.partitions)
                if self.pmap.range_of(g)[0] < self.pmap.range_of(g)[1]
            )
        if done:
            self._merge(p)

    def _merge(self, p: _Scatter) -> None:
        """All parts in: verify epoch coherence, then the exact merge."""
        seqs = {p.tile.get("seq")} | {
            part.get("seq") for part in p.parts.values()
        }
        if len(seqs) > 1:
            # an update sealed mid-scatter: restart from the tile so
            # the answer comes from ONE graph epoch
            p.restarts += 1
            self._m_restarts.inc()
            if p.restarts > _MAX_RESTARTS:
                self._fail(p, "epoch moved during scatter (stuck)")
                return
            with self._lock:
                p.tile = None
                p.parts.clear()
                p.assigned.clear()
                p.tried.clear()
                p.stage = "tile"
            runtime_event("partition_epoch_restart", rid=p.rid,
                          echo=False)
            self._advance(p)
            return
        if p.op == "topk":
            resp = self._merge_topk(p)
        else:
            resp = self._merge_scores(p)
        self._resolve(p, resp)

    def _merge_topk(self, p: _Scatter) -> dict:
        cands = []
        for g in sorted(p.parts):
            cands.extend(p.parts[g].get("cands") or ())
        if not cands:
            return {"ok": True, "result": {"row": int(p.row), "topk": []}}
        m = np.asarray([[float(c.get("m") or 0.0) for c in cands]])
        d = np.asarray([[float(c.get("d") or 0.0) for c in cands]])
        cols = np.asarray(
            [[int(c.get("col") or 0) for c in cands]], dtype=np.int64
        )
        d_source = float(p.tile.get("d_source") or 0.0)
        scores = pathsim.score_candidates(
            m, np.asarray([d_source]), d, xp=np
        )
        vals, idxs = pathsim.topk_from_candidate_scores(scores, cols, p.k)
        ident = {
            int(c.get("col") or 0): (c.get("id"), c.get("label"))
            for c in cands
        }
        hits = []
        for v, j in zip(vals[0], idxs[0]):
            if not np.isfinite(v):
                continue
            i_id, lab = ident[int(j)]
            hits.append({"id": i_id, "label": lab, "score": float(v)})
        return {"ok": True, "result": {"row": int(p.row), "topk": hits}}

    def _merge_scores(self, p: _Scatter) -> dict:
        d_source = float(p.tile.get("d_source") or 0.0)
        chunks = []
        for g in sorted(p.parts):
            part = p.parts[g]
            counts = np.asarray(part.get("counts") or [],
                                dtype=np.float64)
            denoms = np.asarray(part.get("denoms") or [],
                                dtype=np.float64)
            if counts.shape[0] == 0:
                continue
            chunks.append(pathsim.score_candidates(
                counts[None, :], np.asarray([d_source]),
                denoms[None, :], xp=np,
            )[0])
        scores = (
            np.concatenate(chunks) if chunks
            else np.empty(0, dtype=np.float64)
        )
        return {"ok": True,
                "result": {"row": int(p.row), "scores": scores.tolist()}}

    def _resolve(self, p: _Scatter, resp: dict) -> None:
        elapsed = time.monotonic() - p.t0
        tracer = get_tracer()
        with self._lock:
            if self._pending.pop(p.rid, None) is None:
                return
            stale = list(p.sub_spans.values())
            p.sub_spans.clear()
        # seal the trace: outstanding sub-request spans (failed-over
        # stragglers) close as superseded, then the root carries the
        # outcome — one complete tree per scatter
        for span in stale:
            tracer.finish(span, outcome="superseded")
        tracer.finish(p.span, outcome="ok" if resp.get("ok") else "error")
        client = dict(resp)
        client["id"] = p.req.get("id")
        client["request_id"] = p.rid
        client["latency_ms"] = round(elapsed * 1e3, 3)
        if p.failovers:
            client["failovers"] = p.failovers
        outcome = "ok" if resp.get("ok") else "error"
        self._m_requests.inc(outcome=outcome)
        self._m_latency.observe(elapsed, outcome=outcome)
        reasons = []
        if outcome == "error":
            reasons.append("error")
        if resp.get("shed"):
            reasons.append("shed")
        if p.failovers:
            reasons.append("failover")
        if p.restarts:
            reasons.append("epoch_restart")
        if elapsed > self._slow_s:
            reasons.append("slow")
        if reasons:
            self.flight.keep(
                reasons, rid=p.rid, op=p.op, row=p.row,
                elapsed_ms=round(elapsed * 1e3, 3), outcome=outcome,
                error=resp.get("error"), failovers=p.failovers,
            )
        p.future.set_result(client)

    def _fail(self, p: _Scatter, error: str, **flags) -> None:
        resp = {"ok": False, "error": error, **flags}
        if error == "deadline exceeded":
            resp["deadline_exceeded"] = True
        self._resolve(p, resp)

    # -- death, heartbeats, catch-up ---------------------------------------

    def _on_death(self, wid: str, reason: str) -> None:
        self._mark_down(wid, DOWN, reason)

    def _mark_down(self, wid: str, status: str, reason: str) -> None:
        orphans: list[tuple[_Scatter, object]] = []
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status == DOWN or w.status == status:
                return
            w.status = status
            for p in self._pending.values():
                for key, awid in list(p.assigned.items()):
                    if awid == wid:
                        del p.assigned[key]
                        orphans.append((p, key))
        runtime_event(
            "partition_worker_down", worker_id=wid, status=status,
            reason=reason, orphaned=len(orphans),
        )
        get_registry().counter(
            "dpathsim_router_worker_down_total",
            "workers marked down/suspect, by cause",
        ).inc(status=status)
        # also resolve any collector still waiting on this worker
        with self._lock:
            cols = list(self._collectors.values())
        for col in cols:
            col.resolve(wid, None, reason)
        seen = set()
        for p, _key in orphans:
            if p.rid in seen:
                continue
            seen.add(p.rid)
            p.failovers += 1
            self._m_failovers.inc(reason=reason.split(" ")[0] or "death")
            self._advance(p)

    def _maintenance_loop(self) -> None:
        cfg = self.config
        interval = cfg.heartbeat_interval_s
        tick = max(min(interval, 0.05), 0.005)
        next_probe = 0.0
        next_scrape = 0.0
        while not self._closed.wait(tick):
            now = time.monotonic()
            if now >= next_probe:
                next_probe = now + interval
                self._probe_workers(now)
            if cfg.scrape_interval_s and now >= next_scrape:
                next_scrape = now + cfg.scrape_interval_s
                try:
                    merged, _ = obs_fleet.merge_registry_snapshots(
                        self.metric_parts()
                    )
                    self.slo.observe(merged, now)
                except Exception as exc:
                    runtime_event("fleet_slo_error", error=repr(exc))
                self._scrape_workers()
            self._retry_parked(now)

    def _probe_workers(self, now: float) -> None:
        cfg = self.config
        for w in list(self.workers.values()):
            if w.status == DOWN or not w.transport.alive:
                continue
            try:
                inject.fire("heartbeat")
                w.transport.send(
                    {"id": f"hb:{w.wid}:{next(self._hb_seq)}",
                     "op": "health"}
                )
            except inject.InjectedFault:
                pass
            except WorkerGone:
                self._mark_down(w.wid, DOWN, "heartbeat send failed")
                continue
            silence = now - w.last_pong
            if (
                w.status == UP
                and silence > cfg.heartbeat_interval_s
                * cfg.heartbeat_miss_limit
            ):
                self._mark_down(
                    w.wid, SUSPECT,
                    f"stall {silence * 1e3:.0f}ms without pong",
                )

    def _on_pong(self, wid: str, obj: dict) -> None:
        if not obj.get("ok"):
            return
        result = obj.get("result") or {}
        part = result.get("partition") or {}
        catchup_from = None
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status == DOWN:
                return
            w.last_pong = time.monotonic()
            w.last_health = result
            w.pong_seq += 1
            if part:
                w.applied_seq = int(part.get("update_seq") or 0)
                w.colsum_seq = int(part.get("colsum_seq") or 0)
                w.ready = bool(part.get("ready"))
                w.held = tuple(int(g) for g in part.get("held") or ())
                w.row_seq = {
                    int(g): int(s)
                    for g, s in (part.get("row_seq") or {}).items()
                }
            if w.status == SUSPECT:
                w.status = UP
                runtime_event("partition_worker_up", worker_id=wid,
                              echo=False)
            if (
                w.applied_seq < self._head_seq
                and not w.catchup_active
                and not self._updating
            ):
                w.catchup_active = True
                catchup_from = w.applied_seq + 1
            self._compact_epochs()
        if catchup_from is not None:
            self._send_catchup(wid, catchup_from, phase="pu")

    def _compact_epochs(self) -> None:
        """Drop the replay payloads of routed-delta epochs every live
        worker has sealed — called under the lock whenever a worker's
        applied seq advances. Without this a long-lived router under
        sustained deltas retains every update's full edge lists
        forever. Entries keep their slot (seq indexing stays stable);
        only a worker behind the horizon would need a compacted
        payload, and the horizon IS the min live applied seq."""
        live = [
            w.applied_seq for w in self.workers.values()
            if w.status != DOWN
        ]
        if not live:
            return
        horizon = min(min(live), len(self._epochs))
        for i in range(self._compacted_to, horizon):
            self._epochs[i].part_wire = None
            self._epochs[i].colsum_wire = None
        self._compacted_to = max(self._compacted_to, horizon)

    def _send_catchup(self, wid: str, seq: int, phase: str) -> None:
        """Ordered idempotent replay of a missed routed delta: phase
        ``pu`` (part_update) then ``cs`` (set_colsum), each carrying
        the ORIGINAL request_id so the worker's dedup replays cached
        acks for anything it already applied."""
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status != UP:
                if w is not None:
                    w.catchup_active = False
                return
            if not 1 <= seq <= len(self._epochs):
                w.catchup_active = False
                return
            epoch = self._epochs[seq - 1]
            base = (
                epoch.part_wire if phase == "pu" else epoch.colsum_wire
            )
            if base is None:
                # compacted: shouldn't happen (the horizon tracks the
                # slowest LIVE worker) — leave the replica fenced and
                # say so rather than replaying garbage
                w.catchup_active = False
                runtime_event(
                    "partition_catchup_impossible", worker_id=wid,
                    seq=seq,
                )
                return
            wire = dict(base)
            wire["id"] = f"cu:{wid}:{phase}:{seq}"
        runtime_event("partition_catchup", worker_id=wid, seq=seq,
                      phase=phase, echo=False)
        try:
            w.transport.send(wire)
        except WorkerGone:
            self._mark_down(wid, DOWN, "catchup send failed")

    def _on_catchup_ack(self, wid: str, rid: str, obj: dict) -> None:
        try:
            _, _, phase, seq_s = rid.split(":", 3)
            seq = int(seq_s)
        except ValueError:
            return
        if not obj.get("ok"):
            with self._lock:
                w = self.workers.get(wid)
                if w is not None:
                    w.catchup_active = False
            runtime_event("partition_catchup_failed", worker_id=wid,
                          seq=seq, phase=phase,
                          error=str(obj.get("error", "?")))
            return
        if phase == "pu":
            self._send_catchup(wid, seq, phase="cs")
            return
        next_seq = None
        with self._lock:
            w = self.workers.get(wid)
            if w is None:
                return
            w.applied_seq = max(w.applied_seq, seq)
            w.colsum_seq = max(w.colsum_seq, seq)
            result = obj.get("result") or {}
            for g, s in (result.get("row_seq") or {}).items():
                w.row_seq[int(g)] = int(s)
            if w.applied_seq < self._head_seq:
                next_seq = w.applied_seq + 1
            else:
                w.catchup_active = False
            self._compact_epochs()
        if next_seq is not None:
            self._send_catchup(wid, next_seq, phase="pu")

    def _retry_parked(self, now: float) -> None:
        ready: list[_Scatter] = []
        with self._lock:
            if self._updating:
                return
            for p in self._pending.values():
                if p.parked:
                    ready.append(p)
        for p in ready:
            if p.deadline is not None and p.deadline.expired:
                self._fail(p, "deadline exceeded")
                continue
            if (
                p.deadline is None
                and now - p.t0 > self.config.park_timeout_s
            ):
                self._fail(p, "no live holders")
                continue
            with self._lock:
                if p.rid not in self._pending:
                    continue
                p.parked = False
                # a resurrected/caught-up holder deserves a fresh try
                for key in list(p.tried):
                    if key not in p.assigned:
                        p.tried[key] = set()
            self._advance(p)

    # -- routed deltas -----------------------------------------------------

    def _submit_update(self, req: dict, fut: Future) -> Future:
        """The two-phase routed delta, serialized. Runs the exchange on
        a helper thread so the submitting client is not blocked inside
        the router lock; the returned future resolves when phase 2 is
        sealed (or the update times out)."""
        threading.Thread(
            target=self._run_update, args=(req, fut),
            name="pathsim-partrouter-update", daemon=True,
        ).start()
        return fut

    def _run_update(self, req: dict, fut: Future) -> None:
        cfg = self.config
        with self._update_lock:
            with self._lock:
                self._updating = True
                seq = self._head_seq + 1
            attempt = next(self._attempt_seq)
            try:
                part_wire = {
                    "op": "part_update", "seq": seq,
                    "attempt": attempt,
                    "request_id": f"pu{attempt}",
                    "add_nodes": req.get("add_nodes") or (),
                    "add_edges": req.get("add_edges") or (),
                    "remove_edges": req.get("remove_edges") or (),
                }
                acks, failures = self._broadcast(
                    part_wire, "up", timeout=cfg.update_timeout_s,
                )
                if not acks:
                    # surface the workers' own refusal (e.g. "edge
                    # deltas only"), not just the empty-ack fact
                    why = next(iter(failures.values()), "no live workers")
                    fut.set_result({
                        "id": req.get("id"), "ok": False,
                        "error": f"update applied on no partition: "
                                 f"{why}",
                        "detail": failures,
                    })
                    return
                # COVERAGE: every non-empty range must have an acked
                # holder, else that range's Δcolsum contribution (and
                # its row re-encode) would be silently lost — sealing
                # would fork the head from the true graph. Abort: the
                # stage mutated nothing, the client retries cleanly.
                covered: set[int] = set()
                for wid in acks:
                    result = acks[wid].get("result") or {}
                    covered.update(
                        int(g) for g in result.get("held") or ()
                    )
                uncovered = [
                    g for g in range(cfg.partitions)
                    if self.pmap.range_of(g)[0] < self.pmap.range_of(g)[1]
                    and g not in covered
                ]
                if uncovered:
                    self._broadcast(
                        {"op": "set_colsum", "mode": "abort",
                         "seq": seq, "attempt": attempt,
                         "request_id": f"pa{attempt}"},
                        "cs", timeout=cfg.update_timeout_s,
                        targets=[
                            self.workers[wid] for wid in acks
                            if self.workers[wid].transport.alive
                        ],
                    )
                    runtime_event(
                        "partition_update_aborted", seq=seq,
                        attempt=attempt, uncovered=uncovered,
                    )
                    fut.set_result({
                        "id": req.get("id"), "ok": False,
                        "error": (
                            "update aborted: range(s) "
                            f"{uncovered} have no live, current "
                            "holder — retry when the fleet recovers"
                        ),
                        "transient": True,
                    })
                    return
                by_range: dict[int, dict] = {}
                ranges: set[int] = set()
                re_encoded = 0
                for wid in sorted(acks):
                    result = acks[wid].get("result") or {}
                    re_encoded = max(
                        re_encoded, int(result.get("re_encoded") or 0)
                    )
                    ranges.update(
                        int(g) for g in result.get("affected_ranges")
                        or ()
                    )
                    for g_str, payload in (
                        result.get("contrib") or {}
                    ).items():
                        g = int(g_str)
                        if g not in by_range or (
                            self.workers[wid].index == g
                        ):
                            by_range[g] = payload
                dg = np.zeros(max(self.v, 1), dtype=np.float64)
                for payload in by_range.values():
                    cols = np.asarray(payload.get("cols") or [],
                                      dtype=np.int64)
                    vals = np.asarray(payload.get("vals") or [],
                                      dtype=np.float64)
                    dg[cols] += vals
                nz = np.flatnonzero(dg)
                colsum_wire = {
                    "op": "set_colsum", "mode": "delta", "seq": seq,
                    "attempt": attempt,
                    "request_id": f"pc{attempt}",
                    "cols": [int(c) for c in nz],
                    "vals": [float(dg[c]) for c in nz],
                }
                targets = [
                    self.workers[wid] for wid in acks
                    if self.workers[wid].status == UP
                    and self.workers[wid].transport.alive
                ]
                acks2, _failures2 = self._broadcast(
                    colsum_wire, "cs", timeout=cfg.update_timeout_s,
                    targets=targets,
                )
                with self._lock:
                    self._epochs.append(_Epoch(
                        seq=seq, part_wire=part_wire,
                        colsum_wire=colsum_wire,
                        ranges=tuple(sorted(ranges)), rid=f"u{seq}",
                    ))
                    self._head_seq = seq
                    for g in ranges:
                        if g in self._head_row_seq:
                            self._head_row_seq[g] += 1
                    for wid in acks2:
                        w = self.workers.get(wid)
                        if w is None:
                            continue
                        w.applied_seq = seq
                        w.colsum_seq = seq
                        result2 = acks2[wid].get("result") or {}
                        for g, s in (
                            result2.get("row_seq") or {}
                        ).items():
                            w.row_seq[int(g)] = int(s)
                    sealed = sorted(acks2)
                    lagging = sorted(
                        w.wid for w in self.workers.values()
                        if w.status != DOWN and w.applied_seq < seq
                    )
                    self._compact_epochs()
                runtime_event(
                    "partition_update", seq=seq, sealed=len(sealed),
                    lagging=lagging, re_encoded=re_encoded,
                    ranges=sorted(ranges),
                )
                fut.set_result({
                    "id": req.get("id"), "ok": bool(sealed),
                    "result": {
                        "mode": "routed-delta", "seq": seq,
                        "sealed": sealed, "lagging": lagging,
                        "re_encoded_rows": re_encoded,
                        "affected_ranges": sorted(ranges),
                        "base_fp": self._base_fp,
                        "delta_seq": seq,
                    },
                })
            except Exception as exc:  # surface, never hang the client
                fut.set_result({
                    "id": req.get("id"), "ok": False,
                    "error": f"routed update failed: {exc!r}",
                })
                runtime_event("partition_update_error", error=repr(exc))
            finally:
                with self._lock:
                    self._updating = False

    # -- observability -----------------------------------------------------

    def _scrape_workers(self) -> None:
        for w in list(self.workers.values()):
            if w.status == DOWN or not w.transport.alive:
                continue
            try:
                w.transport.send(
                    {"id": f"mx:{w.wid}:{next(self._mx_seq)}",
                     "op": "metrics"}
                )
            except WorkerGone:
                continue

    def _on_metrics(self, wid: str, obj: dict) -> None:
        if not obj.get("ok"):
            return
        result = obj.get("result") or {}
        registry = result.get("registry")
        if not isinstance(registry, dict):
            return
        with self._lock:
            w = self.workers.get(wid)
            if w is None:
                return
            w.last_metrics = registry
            w.metrics_seq += 1
            w.metrics_mono = time.monotonic()

    def metric_parts(self) -> dict:
        parts = {"router": get_registry().snapshot()}
        with self._lock:
            for wid, w in self.workers.items():
                if w.last_metrics is not None:
                    parts[wid] = w.last_metrics
        return parts

    def _on_slo_alert(self, info: dict) -> None:
        runtime_event(
            "slo_alert", slo=info["slo"], kind=info["kind"],
            objective=info["objective"],
            burn={k: round(v, 3) for k, v in info["burn"].items()},
        )

    def fleet_metrics(self, refresh: bool = True,
                      timeout: float = 5.0) -> dict:
        if refresh:
            with self._lock:
                seq0 = {w.wid: w.metrics_seq
                        for w in self.workers.values()}
            self._scrape_workers()
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._lock:
                    done = all(
                        w.status == DOWN or not w.transport.alive
                        or w.metrics_seq > seq0.get(wid, 0)
                        for wid, w in self.workers.items()
                    )
                if done:
                    break
                time.sleep(0.005)
        parts = self.metric_parts()
        merged, unmergeable = obs_fleet.merge_registry_snapshots(parts)
        return {
            "router": self.stats()["router"],
            "merged": merged,
            "unmergeable": unmergeable,
            "workers_scraped": sorted(k for k in parts if k != "router"),
            "slo": self.slo.snapshot(),
            "flight": {
                "kept_total": self.flight.kept_total,
                "dropped": self.flight.dropped,
                "capacity": self.flight.capacity,
            },
        }

    def worker_health(self, wid: str, timeout: float = 10.0) -> dict:
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status == DOWN:
                return {}
            seq0 = w.pong_seq
        try:
            w.transport.send(
                {"id": f"hb:{wid}:{next(self._hb_seq)}", "op": "health"}
            )
        except WorkerGone:
            return {}
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if w.pong_seq > seq0:
                    return dict(w.last_health)
            time.sleep(0.005)
        return {}

    def stats(self) -> dict:
        with self._lock:
            return {
                "router": {
                    "mode": "partition",
                    "partitions": self.config.partitions,
                    "replication": self.config.replication,
                    "workers": {
                        w.wid: {
                            "status": w.status,
                            "partition": w.index,
                            "held": list(w.held),
                            "applied_seq": w.applied_seq,
                            "lag": self._head_seq - w.applied_seq,
                            "ready": w.ready,
                            "row_seq": {
                                str(g): s
                                for g, s in sorted(w.row_seq.items())
                            },
                        }
                        for w in self.workers.values()
                    },
                    "pending": len(self._pending),
                    "epochs": self._head_seq,
                    "head_row_seq": {
                        str(g): s
                        for g, s in sorted(self._head_row_seq.items())
                    },
                    "n": self.n,
                    "v": self.v,
                    "draining": self._draining,
                    "obs": {
                        "slo_alerts": dict(self.slo.alert_counts),
                        "flight_kept": self.flight.kept_total,
                        "flight_dropped": self.flight.dropped,
                    },
                },
            }
