"""Horizontal multi-host serving tier: router / worker split.

One warm :class:`~..serving.PathSimService` is one failure domain; this
package turns N of them into one fault-tolerant service (DESIGN.md §22,
ROADMAP open item 4). Public surface:

- :class:`Router` / :class:`RouterConfig` — the fan-out core: routes
  queries over worker replicas (consistent-hash-by-row for cache
  affinity, row-range alternative), re-dispatches the in-flight work of
  a dead or stalled replica, hedges against the slow tail, broadcasts
  deltas with ``(base_fp, delta_seq)`` fencing, sheds when every
  replica is saturated, and drains gracefully on SIGTERM (core.py);
- :class:`HashRing` / :class:`RangeRouter` — the routing policies
  (hashring.py);
- :class:`WorkerRuntime` / :func:`worker_loop` — the worker side of the
  wire protocol: async query handling, request-id dedup, health probes,
  graceful drain (worker.py);
- :class:`SubprocessTransport` / :class:`InprocTransport` — how the
  router reaches a worker: a real ``dpathsim worker`` child process, or
  an in-process thread for deterministic chaos tests (transport.py);
- :class:`Autoscaler` / :class:`AutoscaleConfig` — the closed loop:
  queue-depth / shed / SLO-burn signals drive worker spawn (epoch
  catch-up replay) and drain (SIGTERM primitive) with tick-counted
  hysteresis and a deterministic decision log (autoscale.py,
  DESIGN.md §30);
- the firehose update pipeline — bounded update-queue admission with
  backpressure and delta coalescing (K queued updates folded into one
  broadcast, firehose.py);
- the ``dpathsim router`` / ``dpathsim worker`` / ``dpathsim
  fleet-stats`` subcommands (cli.py).

The router also hosts the fleet observability plane (DESIGN.md §24):
cross-process trace stitching over the protocol's ``trace`` context,
an exact (bucket-wise) merge of scraped per-worker metric registries,
a multi-window burn-rate SLO engine over the merged stream, and a
tail-sampled flight recorder for slow/errored/shed/hedged/failed-over
requests (``flight_dump`` op + SIGTERM drain dump).
"""

from .autoscale import AutoscaleConfig, Autoscaler
from .core import Router, RouterConfig, RouterShed
from .hashring import HashRing, RangeRouter, make_policy
from .partition import PartitionRouter, PartitionRouterConfig
from .transport import InprocTransport, SubprocessTransport, WorkerGone
from .worker import WorkerRuntime, worker_loop

__all__ = [
    "AutoscaleConfig",
    "Autoscaler",
    "HashRing",
    "InprocTransport",
    "PartitionRouter",
    "PartitionRouterConfig",
    "RangeRouter",
    "Router",
    "RouterConfig",
    "RouterShed",
    "SubprocessTransport",
    "WorkerGone",
    "WorkerRuntime",
    "make_policy",
    "worker_loop",
]
