"""Routing policies: which replica owns a row, and who comes next.

Both policies answer the same question — a *preference order* over the
live workers for a given routing key — because the router needs more
than an owner: failover re-dispatch, hedged sends, and fencing all walk
the same order looking for the next eligible replica, and that order
must be deterministic (a chaos run's reroute decisions reproduce
exactly).

- :class:`HashRing` — consistent hashing with virtual nodes. Cache
  affinity is the point: the same row always lands on the same replica
  (its tier-1/tier-2 entries stay hot there), and when a replica dies
  only ~1/N of the keyspace moves instead of everything reshuffling
  (the classic Karger construction; SNIPPETS.md has no retrieval for
  this — it is standard art).
- :class:`RangeRouter` — contiguous row ranges. The fallback geometry
  for workloads with strong row locality (range scans, bulk rankings)
  where hashing would scatter a hot band over every replica; also the
  natural shape for a future bigger-than-one-host graph split, where a
  worker *holds* only its range.

Hashes are sha256 over stable strings — never Python ``hash()``, whose
per-process randomization would route the same row differently on every
restart and silently destroy affinity.
"""

from __future__ import annotations

import bisect
import hashlib

from ..data.partition import PartitionMap


def _h64(s: str) -> int:
    """Stable 64-bit point on the ring for a key string."""
    return int.from_bytes(
        hashlib.sha256(s.encode("utf-8")).digest()[:8], "big"
    )


class HashRing:
    """Consistent-hash preference order over worker ids.

    ``vnodes`` virtual points per worker smooth the keyspace split (a
    plain one-point-per-worker ring can give one worker 3× the load of
    another at small N). ``preference(key)`` walks the ring clockwise
    from the key's point and returns each distinct worker in encounter
    order — position 0 is the owner (affinity target), the rest are the
    failover/hedge order.
    """

    def __init__(self, worker_ids: list[str], vnodes: int = 64):
        if not worker_ids:
            raise ValueError("hash ring needs at least one worker")
        self.vnodes = int(vnodes)
        self._workers = sorted(worker_ids)  # order-independent ring
        self._points: list[int] = []
        self._owner_at: dict[int, str] = {}
        for wid in self._workers:
            for v in range(self.vnodes):
                pt = _h64(f"{wid}#{v}")
                # collisions across 64-bit points are ~impossible; if
                # one happens the sorted-worker order makes it stable
                if pt not in self._owner_at:
                    self._owner_at[pt] = wid
                    self._points.append(pt)
        self._points.sort()

    @property
    def workers(self) -> tuple[str, ...]:
        return tuple(self._workers)

    def preference(self, key: int | str) -> tuple[str, ...]:
        """All workers, owner first, in deterministic ring order."""
        pt = _h64(f"row:{key}")
        i = bisect.bisect_right(self._points, pt)
        seen: list[str] = []
        for off in range(len(self._points)):
            wid = self._owner_at[self._points[(i + off) % len(self._points)]]
            if wid not in seen:
                seen.append(wid)
                if len(seen) == len(self._workers):
                    break
        return tuple(seen)

    def owner(self, key: int | str) -> str:
        return self.preference(key)[0]

    def owner_of(self, row: int | str) -> str:
        """Alias of :meth:`owner` — the stable ownership API both
        policies export (range mode adds :meth:`range_of`)."""
        return self.owner(row)

    def without(self, worker_id: str) -> "HashRing":
        """The ring minus one member (worker death): every key that
        worker owned moves to its ring successor; every other key keeps
        its owner — the minimal-disruption property tests assert."""
        rest = [w for w in self._workers if w != worker_id]
        return HashRing(rest, vnodes=self.vnodes)


class RangeRouter:
    """Contiguous row-range ownership over ``n_rows``.

    Worker ``i`` of W owns rows ``[i*ceil(n/W), (i+1)*ceil(n/W))`` —
    the ceil-division geometry shared with
    :class:`~..data.partition.PartitionMap`, so routing and *ownership*
    (partition mode, where a worker only HOLDS its ranges) can never
    disagree. Preference order is owner, then neighbors outward (the
    replicas most likely to have adjacent rows warm). Non-integer keys
    (label queries) fall back to a stable hash into the row space, so
    the interface stays total.

    The stable ownership API — :meth:`owner_of` (row → worker id,
    strict on the row domain) and :meth:`range_of` (worker id →
    half-open row range) — is what the partitioned fleet builds on;
    the boundary-row property tests in tests/test_partition.py pin it.
    """

    def __init__(self, worker_ids: list[str], n_rows: int):
        if not worker_ids:
            raise ValueError("range router needs at least one worker")
        self._workers = sorted(worker_ids)
        self.n_rows = max(int(n_rows), 1)
        self._pmap = PartitionMap(n=self.n_rows, p=len(self._workers))
        self._span = self._pmap.span

    @property
    def workers(self) -> tuple[str, ...]:
        return tuple(self._workers)

    def preference(self, key: int | str) -> tuple[str, ...]:
        if not isinstance(key, int):
            key = _h64(f"label:{key}") % self.n_rows
        w = len(self._workers)
        i = min(max(int(key), 0) // self._span, w - 1)
        order = [i]
        for off in range(1, w):
            if i + off < w:
                order.append(i + off)
            if i - off >= 0:
                order.append(i - off)
        return tuple(self._workers[j] for j in order[:w])

    def owner(self, key: int | str) -> str:
        return self.preference(key)[0]

    def owner_of(self, row: int) -> str:
        """Worker id owning ``row`` — strict on ``[0, n_rows)`` (an
        out-of-range row is a caller bug, not a routing choice; the
        forgiving clamp lives in :meth:`preference` for label keys)."""
        return self._workers[self._pmap.owner_of(int(row))]

    def range_of(self, worker_id: str) -> tuple[int, int]:
        """Half-open row range ``[lo, hi)`` this worker owns. The last
        worker absorbs the ceil-division remainder; with a single
        worker the range is the whole row space."""
        try:
            i = self._workers.index(worker_id)
        except ValueError:
            raise KeyError(
                f"unknown worker {worker_id!r} "
                f"(members: {self._workers})"
            ) from None
        return self._pmap.range_of(i)

    def without(self, worker_id: str) -> "RangeRouter":
        rest = [w for w in self._workers if w != worker_id]
        return RangeRouter(rest, n_rows=self.n_rows)


def make_policy(
    routing: str, worker_ids: list[str], n_rows: int, vnodes: int = 64
):
    """``--routing`` flag → policy instance."""
    if routing == "hash":
        return HashRing(worker_ids, vnodes=vnodes)
    if routing == "range":
        return RangeRouter(worker_ids, n_rows=n_rows)
    raise ValueError(f"unknown routing policy {routing!r} (hash|range)")
