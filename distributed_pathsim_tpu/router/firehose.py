"""Router-side firehose pipelining: bounded update admission + coalescing.

PR 6's ``update`` path broadcasts every delta individually: one wire
round trip, one fencing epoch, one worker-side drain per delta. Under a
sustained firehose that serializes the fleet on broadcast latency long
before the O(Δ) patch math saturates. This module gives the router the
two missing pieces (DESIGN.md §30):

- **Bounded admission with backpressure**: updates land in a bounded
  queue; past the bound the submitter gets an immediate
  ``backpressure`` error instead of unbounded queue growth — the
  firehose's producer sees the signal and can throttle, exactly like
  query-side shed.
- **Coalescing**: a pump drains the queue and folds up to K queued
  updates into ONE broadcast (the product-rule ΔC composes, so K
  epochs become one). Record-level folding cancels add/remove pairs of
  the same edge key and concatenates node appends in order;
  within-window conflicts a single batch cannot express (the same edge
  key added twice) split the window instead of failing it. Every
  member future resolves with the group's result plus its own id.

The same cancellation semantics exist one layer down for dense-index
``DeltaBatch`` objects (:func:`~..data.delta.coalesce_deltas`), where
the K-coalesced == K-sequential property is tested bit-exactly across
all four backends.
"""

from __future__ import annotations

import dataclasses


def _edge_key(rec: dict) -> tuple:
    """Stable identity of one edge record: by id when the record uses
    ids, by dense row when it uses rows. Rows are append-only, so row
    keys stay valid across a window that also appends nodes. An edge
    addressed by id in one update and by row in another does NOT
    cancel — the merged batch would then be rejected whole by the
    delta machinery, which is why a failed coalesced broadcast falls
    back to sequential replay (core.py)."""
    rel = rec.get("rel")
    src = (
        ("id", rec["src"]) if rec.get("src") is not None
        else ("row", int(rec.get("src_row", -1)))
    )
    dst = (
        ("id", rec["dst"]) if rec.get("dst") is not None
        else ("row", int(rec.get("dst_row", -1)))
    )
    return (rel, src, dst)


@dataclasses.dataclass
class UpdateGroup:
    """One coalesced broadcast: the merged wire records plus the
    member requests whose futures it resolves."""

    members: list
    add_nodes: list
    add_edges: list
    remove_edges: list

    @property
    def merged_wire(self) -> dict:
        return {
            "op": "update",
            "add_nodes": list(self.add_nodes),
            "add_edges": list(self.add_edges),
            "remove_edges": list(self.remove_edges),
            # every router broadcast asks for the affected-row SET
            # (fencing needs it); _submit_update stamps it regardless —
            # declared here so the wire schema records the producer
            "want_rows": True,
        }


class _WindowState:
    """Running fold of one group: net edge signs + appended-id sets."""

    def __init__(self):
        self.nodes: list = []
        self.node_ids: set = set()
        # edge key → (+1 record) | (-1 record); cancelled keys removed
        self.net: dict[tuple, tuple[int, dict]] = {}

    def try_fold(self, req: dict) -> bool:
        """Fold one update's records in; False (state untouched) when
        the update conflicts with the window and must start a new
        group. Conflicts: an appended id already appended in-window, or
        an edge key transitioning add→add / remove→remove."""
        staged_nodes = []
        staged_ids = set()
        for rec in req.get("add_nodes") or ():
            key = (rec.get("type"), rec.get("id"))
            if key in self.node_ids or key in staged_ids:
                return False
            staged_ids.add(key)
            staged_nodes.append(rec)
        staged_net: dict[tuple, tuple[int, dict] | None] = {}
        for field, sign in (("add_edges", 1), ("remove_edges", -1)):
            for rec in req.get(field) or ():
                key = _edge_key(rec)
                if key in staged_net:
                    cur = staged_net[key]
                else:
                    cur = self.net.get(key)
                cur_sign = cur[0] if cur is not None else 0
                if cur_sign == sign:
                    return False
                staged_net[key] = (
                    None if cur_sign == -sign else (sign, rec)
                )
        self.nodes.extend(staged_nodes)
        self.node_ids |= staged_ids
        # commit by REPLACING the map (pure rebuild, no paired
        # insert/remove on the live table): a cancelled key simply
        # isn't carried over
        merged = {
            k: v for k, v in self.net.items() if k not in staged_net
        }
        merged.update({
            k: v for k, v in staged_net.items() if v is not None
        })
        self.net = merged
        return True

    def group(self, members: list) -> UpdateGroup:
        return UpdateGroup(
            members=members,
            add_nodes=list(self.nodes),
            add_edges=[r for s, r in self.net.values() if s > 0],
            remove_edges=[r for s, r in self.net.values() if s < 0],
        )


def coalesce_update_groups(reqs: list, max_group: int) -> list[UpdateGroup]:
    """Fold a queue drain into broadcast groups, in order: each group
    holds up to ``max_group`` conflict-free updates. Ordering within
    and across groups preserves submission order, so the sequential
    semantics every client observed before coalescing are unchanged —
    only the broadcast count shrinks."""
    groups: list[UpdateGroup] = []
    state = _WindowState()
    members: list = []

    def flush():
        nonlocal state, members
        if members:
            groups.append(state.group(members))
        state = _WindowState()
        members = []

    for req in reqs:
        if members and (
            len(members) >= max_group or not state.try_fold(req)
        ):
            flush()
        if not members and not state.try_fold(req):
            # a SELF-conflicting update (e.g. one batch adding the same
            # edge twice): pass its records through verbatim as a
            # singleton group so the workers reject it with their own
            # diagnostic — coalescing must never launder an invalid
            # update into an empty no-op broadcast
            flush()
            groups.append(UpdateGroup(
                members=[req],
                add_nodes=list(req.get("add_nodes") or ()),
                add_edges=list(req.get("add_edges") or ()),
                remove_edges=list(req.get("remove_edges") or ()),
            ))
            continue
        members.append(req)
    flush()
    return groups
