"""The worker side of the router/worker split.

A worker is one warm :class:`~..serving.PathSimService` speaking an
*asynchronous* variant of the serve JSONL protocol: the read loop never
blocks on query work. ``topk`` requests are submitted to the service's
coalescer and answered out of order when their future resolves (matched
by ``id``/``request_id``), so concurrent router traffic actually
coalesces into batched dispatches, and ``health`` probes stay
answerable while queries are in flight — which is exactly what lets the
router tell a *dead* worker (no pong) from a *stalled* one (pongs flow,
answers don't; hedging territory).

Robustness contracts implemented here:

- **Idempotent retries**: mutating ops (``update``, ``invalidate``)
  dedup by ``request_id`` — a re-delivered broadcast replays the cached
  ack instead of applying the delta twice (the router re-sends missed
  deltas during catch-up, and a hedged/failed-over send may arrive
  after the original succeeded).
- **Graceful drain** (SIGTERM or the in-band ``drain`` op): stop
  accepting queries (each gets a retriable ``draining`` error the
  router reroutes), complete every in-flight request, emit the final
  accounting event, exit 0. No accepted request is dropped.
- **Chaos seam** ``worker_dispatch`` (resilience/inject.py): fired
  before each query submit. ``error`` → a retriable per-request
  failure; ``delay`` → a stalled read loop (the stall the router's
  hedging exists for); ``crash`` → the process dies like a real kill.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import OrderedDict
from typing import IO, Callable

import numpy as np

from ..obs.trace import from_wire, get_tracer
from ..resilience import (
    Deadline,
    inject,
    policy_from_env,
    resilient_call,
)
from ..serving.coalescer import LoadShedError, ServiceClosed
from ..serving.protocol import handle_request
from ..serving.service import PathSimService
from ..utils.logging import runtime_event

# ops whose effect must apply exactly once across retries — everything
# else is a deterministic read, safe to repeat anywhere. The partition
# pair (part_update / set_colsum) is what makes routed-delta catch-up
# replays idempotent: a re-delivered phase replays its cached ack.
MUTATING_OPS = frozenset({
    "update", "invalidate", "part_update", "set_colsum",
})

_DEDUP_CAPACITY = 1024

_NULL_CTX = contextlib.nullcontext()


class WorkerRuntime:
    """Protocol state for one worker process: async query completion,
    request-id dedup, drain bookkeeping. ``reply`` callables passed to
    :meth:`handle` must be thread-safe (completion fires on the
    coalescer's completer thread)."""

    def __init__(self, service: PathSimService, worker_id: str = "w0"):
        self.service = service
        self.worker_id = worker_id
        self.draining = False
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._inflight: set = set()
        # request_id → response for mutating ops (bounded: the router
        # only ever retries recent requests; an evicted entry re-applies,
        # which for update is rejected loudly by the delta machinery)
        self._done: OrderedDict[str, dict] = OrderedDict()
        self.dedup_hits = 0

    # -- bookkeeping -------------------------------------------------------

    def _track(self, token) -> None:
        with self._lock:
            self._inflight.add(token)

    def _untrack(self, token) -> None:
        with self._lock:
            self._inflight.discard(token)
            if not self._inflight:
                self._idle.notify_all()

    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def begin_drain(self, reason: str = "drain op") -> None:
        if not self.draining:
            self.draining = True
            runtime_event("worker_draining", worker_id=self.worker_id,
                          reason=reason, echo=False)

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until every in-flight request has been answered (the
        drain contract). False on timeout — the caller still exits, but
        loudly."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True

    # -- request handling --------------------------------------------------

    def handle(self, req: dict, reply: Callable[[dict], None]) -> str:
        """Process one request; returns a loop directive: ``"ok"``,
        ``"shutdown"``, or ``"drain"``. Every return path has called
        ``reply`` exactly once (async ops: will call it)."""
        op = req.get("op", "topk")
        rid = req.get("id")
        request_id = req.get("request_id")
        if op == "shutdown":
            reply({"id": rid, "ok": True, "result": {"shutdown": True}})
            return "shutdown"
        if op == "drain":
            self.begin_drain()
            reply({"id": rid, "ok": True, "result": {"draining": True}})
            return "drain"
        if op == "topk":
            self._handle_topk(req, reply)
            return "ok"
        if op in MUTATING_OPS and request_id is not None:
            with self._lock:
                cached = self._done.get(request_id)
            if cached is not None:
                # idempotent retry: same request_id → same answer,
                # the delta applied exactly once
                self.dedup_hits += 1
                reply({**cached, "id": rid, "deduped": True})
                return "ok"
        resp = handle_request(self.service, req)
        if op in MUTATING_OPS and request_id is not None and resp.get("ok"):
            with self._lock:
                self._done[request_id] = resp
                while len(self._done) > _DEDUP_CAPACITY:
                    self._done.popitem(last=False)
        reply(resp)
        return "ok"

    def _handle_topk(self, req: dict, reply: Callable[[dict], None]) -> None:
        """The async hot path: resolve + submit on the read thread,
        answer from the future's completion.

        Trace stitching: a ``trace`` context on the wire parents this
        worker's spans under the router's dispatch span. The
        ``worker.request`` span covers the full async lifecycle —
        opened here on the read thread, finished when the future
        resolves on the completer thread — and the service's
        ``serve.request`` tree hangs under it (the remote context is
        activated around the submit). A ``sampled: false`` context
        creates nothing anywhere downstream."""
        rid = req.get("id")
        request_id = req.get("request_id")
        deadline = Deadline.from_ms(req.get("deadline_ms"))
        tracer = get_tracer()
        rctx = from_wire(req.get("trace"))
        wspan = (
            tracer.start_span(
                "worker.request", parent=rctx,
                worker=self.worker_id, op="topk",
            )
            if rctx is not None else None
        )

        def fail(error: str, **flags) -> None:
            tracer.finish(wspan, outcome="error", error=error)
            resp = {"id": rid, "ok": False, "error": error, **flags}
            if request_id is not None:
                resp["request_id"] = request_id
            reply(resp)

        if self.draining:
            fail("draining", draining=True)
            return
        if deadline is not None and deadline.expired:
            fail("deadline expired on arrival", deadline_exceeded=True)
            return
        try:
            row = self.service.resolve(
                source=req.get("source"), source_id=req.get("source_id"),
                row=req.get("row"),
            )
        except KeyError as exc:
            fail(str(exc.args[0] if exc.args else exc))
            return
        k = int(req.get("k") or self.service.config.k_default)
        mode = req.get("mode")
        if mode not in (None, "exact", "ann", "learned"):
            fail(f"unknown topk mode {mode!r}")
            return
        t0 = time.perf_counter()
        # Transient dispatch faults retry LOCALLY first, under a policy
        # CLAMPED to the caller's remaining budget (deadline_ms →
        # Deadline → RetryPolicy.deadline_s): a local retry is cheaper
        # than a router round-trip, but it must never spend time the
        # caller no longer has — when the budget (or attempts) runs
        # out, the transient error surfaces and the router reroutes.
        # The worker_dispatch seam fires per attempt: error → local
        # retry then retriable reply, delay → this read loop stalls
        # (the router's hedging territory), crash → the process dies
        # mid-batch (failover re-dispatch territory).
        policy = policy_from_env(max_attempts=2)
        if deadline is not None:
            policy = deadline.clamp(policy)
        # fallback annotation for the router's tail sampler: a
        # side-effect-free peek (the answering path counts it), read
        # BEFORE the submit so the response can say "this ann request
        # will answer exactly, and why" — what lets the fleet flight
        # recorder keep 100% of ann-degraded requests
        try:
            ann_fallback = self.service.ann_fallback_reason(row, mode)
        except Exception:
            ann_fallback = None
        try:
            learned_fallback = self.service.learned_fallback_reason(
                row, mode
            )
        except Exception:
            learned_fallback = None
        # the remote trace context (or this worker's request span)
        # becomes the submit's ambient parent: the coalescer pipeline's
        # spans land inside the fleet trace
        ctx = wspan.context if wspan is not None else rctx
        try:
            # mode rides through: a replica WITHOUT an index answers an
            # "ann" request exactly (counted as a no_index fallback) —
            # which is what makes re-dispatching an ann query onto any
            # surviving replica always safe
            with tracer.activate(ctx) if ctx is not None else _NULL_CTX:
                future = resilient_call(
                    "worker_dispatch",
                    lambda: self.service.submit_topk(row, k, mode=mode),
                    policy,
                )
        except LoadShedError:
            fail("shed", shed=True)
            return
        except ServiceClosed:
            fail("worker closed", transient=True)
            return
        except inject.InjectedFault as exc:
            fail(str(exc), transient=True)
            return
        token = object()
        self._track(token)

        def on_done(fut) -> None:
            try:
                exc = fut.exception()
                if exc is not None:
                    fail(f"dispatch failed: {exc!r}", transient=True)
                    return
                vals, idxs = fut.result()
                hits = []
                for v, i in zip(vals, idxs):
                    if not np.isfinite(v):
                        continue
                    i_id, lab = self.service._ident(int(i))
                    hits.append(
                        {"id": i_id, "label": lab, "score": float(v)}
                    )
                result = {"row": int(row), "topk": hits}
                if ann_fallback is not None:
                    result["ann_fallback"] = ann_fallback
                if learned_fallback is not None:
                    result["learned_fallback"] = learned_fallback
                resp = {
                    "id": rid,
                    "ok": True,
                    "result": result,
                    "latency_ms": round(
                        (time.perf_counter() - t0) * 1e3, 3
                    ),
                }
                if request_id is not None:
                    resp["request_id"] = request_id
                tracer.finish(wspan, outcome="ok")
                reply(resp)
            finally:
                self._untrack(token)

        future.add_done_callback(on_done)


def worker_loop(
    runtime: WorkerRuntime, in_stream: IO[str], out_stream: IO[str]
) -> int:
    """The worker process's main loop: JSONL in, JSONL out (responses
    out of order; matched by id). First line out is the ``ready`` event
    the router waits for. Returns 0 on shutdown/drain/EOF.

    SIGTERM (latched by the resilience preemption handler, installed by
    the worker CLI) takes effect at the next protocol event, same
    semantics as serve_loop's drain; the router's own drain path uses
    the in-band ``drain`` op, which needs no signal delivery."""
    from ..resilience import preemption_handler

    wlock = threading.Lock()

    def emit(obj: dict) -> None:
        line = json.dumps(obj) + "\n"
        with wlock:
            out_stream.write(line)
            out_stream.flush()

    svc = runtime.service
    emit({
        "event": "ready",
        "worker_id": runtime.worker_id,
        "n": svc.n,
        "backend": svc.backend.name,
        "base_fp": svc.consistency_token[0],
        "delta_seq": svc.consistency_token[1],
        "metapath": svc.metapath.name,
    })

    def finish(reason: str) -> int:
        runtime.begin_drain(reason)
        drained = runtime.wait_idle()
        try:
            svc.coalescer.drain()
        except TimeoutError:
            drained = False  # report it, still exit cleanly
        runtime_event(
            "worker_drained", worker_id=runtime.worker_id, reason=reason,
            clean=drained, dedup_hits=runtime.dedup_hits, echo=False,
        )
        emit({"event": "drained", "worker_id": runtime.worker_id,
              "clean": drained})
        return 0

    for line in in_stream:
        if preemption_handler.requested():
            return finish(preemption_handler.reason or "signal")
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as exc:
            emit({"id": None, "ok": False, "error": f"bad request: {exc}"})
            continue
        directive = runtime.handle(req, emit)
        if directive == "shutdown":
            runtime.wait_idle()
            return 0
        if directive == "drain":
            return finish("drain op")
        if preemption_handler.requested():
            return finish(preemption_handler.reason or "signal")
    return finish("eof")
