"""``dpathsim router`` / ``dpathsim worker`` — the horizontal tier's CLIs.

``worker`` is ``serve`` with the router-facing loop (router/worker.py):
async query completion, health probes, request-id dedup, graceful
drain. It accepts every serve flag plus ``--worker-id``, and grows one
dataset scheme: ``--dataset synthetic:authors=..,papers=..,venues=..,
seed=..`` builds the deterministic synthetic HIN in-process — the same
graph for every worker given the same spec, which is what the router's
same-base-fingerprint startup check enforces (and what lets tests and
benches bring up a replica set with no file staging).

``router`` spawns N ``worker`` children with the SAME serving flags,
waits for their ready events, and speaks the serve JSONL protocol
upstream on stdin/stdout — a drop-in horizontal replacement for one
``dpathsim serve`` process::

    dpathsim router --workers 2 --dataset dblp/dblp_small.gexf \
        --backend jax --routing hash

SIGTERM drains gracefully: new requests are rejected, in-flight ones
complete, workers drain in turn, exit 0.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading

from ..utils.logging import RunLogger, runtime_event, set_event_sink
from .core import Router, RouterConfig, RouterShed
from .transport import SubprocessTransport


def _parse_synthetic(spec: str) -> dict:
    """``synthetic:authors=384,papers=640,venues=12,seed=7`` → kwargs."""
    fields = {}
    body = spec.split(":", 1)[1]
    for part in body.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        fields[key.strip()] = int(val)
    kwargs = {
        "n_authors": fields.pop("authors"),
        "n_papers": fields.pop("papers"),
        "n_venues": fields.pop("venues"),
        "n_topics": fields.pop("topics", 0),
        "seed": fields.pop("seed", 0),
    }
    if fields.pop("ids", 0):
        kwargs["materialize_ids"] = True
    if fields:
        raise ValueError(f"unknown synthetic dataset fields {sorted(fields)}")
    return kwargs


def build_worker_parser() -> argparse.ArgumentParser:
    from ..serving.cli import build_serve_parser

    p = build_serve_parser()
    p.prog = "dpathsim worker"
    p.description = (
        "router-facing PathSim worker: one warm replica speaking the "
        "async JSONL protocol (health probes, request-id dedup, "
        "graceful drain) on stdin/stdout"
    )
    p.add_argument("--worker-id", default="w0",
                   help="replica identity (routing, events, heartbeats); "
                   "must not contain ':'")
    # partition mode (DESIGN.md §26): this worker holds only a row-range
    # slice of the half-chain factor and serves the partition exchange
    # ops instead of whole queries
    p.add_argument("--partition-index", type=int, default=None,
                   help="partition index this worker owns (enables "
                   "partition mode; requires --partitions)")
    p.add_argument("--partitions", type=int, default=None,
                   help="total partition count of the fleet")
    p.add_argument("--partition-replication", type=int, default=2,
                   help="chained replication factor: this worker also "
                   "mirrors the next R-1 partitions' ranges")
    return p


def _build_worker_hin(args):
    """Dataset spec → the FULL encoded HIN (partition workers
    fingerprint it whole before slicing, so every partition of the
    same spec agrees on the base graph)."""
    from ..data.delta import with_headroom

    if args.dataset.startswith("synthetic:"):
        from ..data.synthetic import synthetic_hin

        hin = synthetic_hin(**_parse_synthetic(args.dataset))
    else:
        from ..engine import load_dataset

        hin = load_dataset(
            args.dataset,
            use_native={"auto": None, "python": False,
                        "native": True}[args.loader],
        )
    if args.headroom:
        hin = with_headroom(hin, args.headroom)
    return hin


def _build_partition_service(args):
    """Partition-flag args → PartitionService holding only its slice
    (the full HIN is fingerprinted, sliced, and dropped)."""
    from ..ops.metapath import compile_metapath
    from ..serving.partition import PartitionConfig, PartitionService

    if args.partitions is None or args.partitions < 1:
        raise ValueError("--partition-index requires --partitions >= 1")
    if not 0 <= args.partition_index < args.partitions:
        raise ValueError(
            f"--partition-index {args.partition_index} out of range "
            f"[0, {args.partitions})"
        )
    hin = _build_worker_hin(args)
    metapath = compile_metapath(args.metapath, hin.schema)
    return PartitionService(
        hin, metapath,
        part_index=args.partition_index,
        n_parts=args.partitions,
        replication=args.partition_replication,
        config=PartitionConfig(
            variant=args.variant, k_default=args.k,
            factor_format=args.factor_format,
        ),
    )


def _check_factor_format(args) -> None:
    """Same refusal the batch CLI makes: --factor-format selects the
    jax-sparse resident layout; other backends would swallow it via
    **options and serve uncompressed with no diagnostic."""
    if args.factor_format is not None and args.backend != "jax-sparse":
        raise ValueError(
            "--factor-format selects the resident layout of the "
            "sparse half-chain factor and requires --backend "
            "jax-sparse (partition mode honors it regardless of "
            "--backend: the slice layout is its own surface)"
        )


def _build_worker_service(args):
    """Serve-flag args → warm PathSimService (GEXF through the engine
    bootstrap; ``synthetic:`` specs built in-process)."""
    _check_factor_format(args)
    from ..config import RunConfig
    from ..serving.service import ServeConfig, build_service

    serve_config = ServeConfig(
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        queue_depth=args.queue_depth,
        cache_entries=args.cache_entries,
        tile_cache_bytes=int(args.tile_cache_mb * (1 << 20)),
        k_default=args.k,
        warm=not args.no_warm,
        batch_events=args.batch_events,
        delta_threshold=args.delta_threshold,
        topk_mode=args.topk_mode,
        index_path=args.index,
        ann_nprobe=args.ann_nprobe,
        ann_cand_mult=args.ann_cand_mult,
        ann_centroids=args.ann_centroids,
        ann_cluster_cap=args.ann_cluster_cap,
        ann_variant=args.ann_variant,
        ann_shadow_every=args.ann_shadow_every,
        ann_auto_refresh=not args.no_ann_refresh,
        learned_checkpoint=args.learned_checkpoint,
        learned_dim=args.learned_dim,
        learned_steps=args.learned_steps,
        learned_neg_ratio=args.learned_neg_ratio,
        learned_cand_mult=args.learned_cand_mult,
        learned_shadow_every=args.learned_shadow_every,
        learned_recall_floor=args.learned_recall_floor,
        learned_auto_refresh=not args.no_learned_refresh,
        memo_budget_mb=args.memo_budget_mb,
        max_metapaths=args.max_metapaths,
        compact_auto=not args.no_compact,
        compact_chain_len=args.compact_chain_len,
        compact_headroom_frac=args.compact_headroom_frac,
        compact_headroom=args.compact_headroom,
        compact_cooldown_s=args.compact_cooldown,
    )
    if args.dataset.startswith("synthetic:"):
        from ..backends.base import create_backend
        from ..ops.metapath import compile_metapath
        from ..serving.service import PathSimService

        # ONE spec-to-HIN path shared with partition workers: replica
        # and partition builds of the same --dataset must produce the
        # same base graph (the router's base_fp startup check)
        hin = _build_worker_hin(args)
        metapath = compile_metapath(args.metapath, hin.schema)
        extra = (
            {"factor_format": args.factor_format}
            if args.factor_format else {}
        )
        return PathSimService(
            create_backend(args.backend, hin, metapath, **extra),
            variant=args.variant,
            config=serve_config,
        )
    config = RunConfig(
        dataset=args.dataset,
        backend=args.backend,
        metapath=args.metapath,
        variant=args.variant,
        loader=args.loader,
        dtype=args.dtype,
        n_devices=args.n_devices,
        tile_rows=args.tile_rows,
        approx=args.approx,
        factor_format=args.factor_format,
        headroom=args.headroom,
        echo=False,
        tuning_table=args.tuning_table,
        tuning=not args.no_tuning,
    )
    return build_service(config, serve_config)


def worker_main(argv: list[str] | None = None) -> int:
    args = build_worker_parser().parse_args(argv)
    if ":" in args.worker_id:
        raise ValueError("--worker-id must not contain ':'")
    from ..cli import _apply_platform

    _apply_platform(args.platform)

    from .. import obs
    from ..resilience import preemption_handler
    from .worker import WorkerRuntime, worker_loop

    obs.configure(
        metrics=not args.no_metrics,
        tracing=True if args.trace_out else None,
        trace_sample=args.trace_sample,
    )
    exporter = (
        obs.PrometheusTextfileExporter(
            args.metrics_file, interval_s=args.metrics_interval
        )
        if args.metrics_file
        else None
    )
    logger = RunLogger(output_path=None, echo=False,
                       metrics_path=args.metrics)
    set_event_sink(logger)
    installed = preemption_handler.install()
    service = None
    try:
        if args.partition_index is not None:
            service = _build_partition_service(args)
        else:
            service = _build_worker_service(args)
        if exporter is not None:
            exporter.start()
        runtime = WorkerRuntime(service, worker_id=args.worker_id)
        print(
            f"worker {args.worker_id}: {service.metapath.name} over "
            f"{service.n} rows (backend={service.backend.name})",
            file=sys.stderr,
        )
        return worker_loop(runtime, sys.stdin, sys.stdout)
    finally:
        if service is not None:
            service.close()
        if exporter is not None:
            exporter.stop()  # final flush: the drain contract's tail
        if args.trace_out:
            print(obs.dump_trace(args.trace_out), file=sys.stderr)
        if installed:
            preemption_handler.uninstall()
            preemption_handler.reset()
        set_event_sink(None)
        logger.close()


# flags forwarded verbatim from the router's command line to each
# worker child (store-value flags; store-true flags handled below)
_FORWARD_VALUE = (
    "dataset", "backend", "metapath", "variant", "loader", "platform",
    "dtype", "k", "max_batch", "max_wait_ms", "queue_depth",
    "cache_entries", "tile_cache_mb", "headroom", "delta_threshold",
    "tuning_table", "topk_mode", "index", "ann_nprobe", "ann_cand_mult",
    "ann_centroids", "ann_cluster_cap", "ann_variant",
    "ann_shadow_every", "learned_checkpoint", "learned_dim",
    "learned_steps", "learned_neg_ratio", "learned_cand_mult",
    "learned_shadow_every", "learned_recall_floor",
    "metrics_interval", "trace_sample",
    "factor_format", "compact_chain_len", "compact_headroom_frac",
    "compact_headroom", "compact_cooldown",
)
_FORWARD_TRUE = (
    "no_warm", "no_metrics", "no_tuning", "approx", "no_ann_refresh",
    "no_learned_refresh", "no_compact",
)
# artifact-path flags forwarded with a per-worker suffix: a fleet run
# with --metrics-file/--trace-out/--metrics must leave N+1 artifacts
# (one per process), not N processes clobbering one path — and a
# worker left exporting to nowhere (the pre-§24 state: metrics enabled,
# nothing exporting them) leaves nothing at all
_FORWARD_PATH = ("metrics_file", "trace_out", "metrics")


def _suffix_path(path: str, wid: str) -> str:
    """``fleet.prom`` → ``fleet.w0.prom`` (suffix before the extension
    so collectors globbing ``*.prom`` still pick every worker up)."""
    root, ext = os.path.splitext(path)
    return f"{root}.{wid}{ext}" if ext else f"{path}.{wid}"


def build_router_parser() -> argparse.ArgumentParser:
    from ..serving.cli import build_serve_parser

    p = build_serve_parser()
    p.prog = "dpathsim router"
    p.description = (
        "fault-tolerant horizontal serving: fan the serve JSONL "
        "protocol over N dpathsim-worker replicas with failover, "
        "hedging, and delta fencing"
    )
    p.add_argument("--workers", type=int, default=2,
                   help="worker replica count (replicate mode) / "
                   "partition count (partition mode)")
    p.add_argument("--mode", default="replicate",
                   choices=("replicate", "partition"),
                   help="replicate: N full copies of the graph; "
                   "partition: ONE graph row-sharded across N workers "
                   "with distributed half-chain multiply and exact "
                   "global top-k merge (DESIGN.md §26)")
    p.add_argument("--replication", type=int, default=2,
                   help="partition mode: chained replication factor "
                   "(each worker mirrors the next R-1 partitions' "
                   "ranges; R>=2 survives worker death with zero lost "
                   "requests)")
    p.add_argument("--routing", default="hash", choices=("hash", "range"),
                   help="replica selection: consistent-hash-by-row "
                   "(cache affinity) or contiguous row ranges")
    p.add_argument("--hedge-ms", type=float, default=100.0,
                   help="age at which an in-flight query is hedged to "
                   "the next replica (0 disables)")
    p.add_argument("--heartbeat-interval", type=float, default=0.25,
                   help="seconds between health probes per worker")
    p.add_argument("--heartbeat-miss", type=int, default=4,
                   help="unanswered intervals before a worker is "
                   "routed around")
    p.add_argument("--max-inflight", type=int, default=512,
                   help="router admission bound (pending requests)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request budget when the client "
                   "sends none")
    p.add_argument("--scrape-interval", type=float, default=5.0,
                   help="seconds between fleet metrics scrapes (each "
                   "worker's registry pulled and merged exactly; 0 "
                   "disables the scrape loop and the SLO engine's "
                   "periodic evaluation)")
    p.add_argument("--slo-specs", default=None,
                   help="JSON file of SLO specs (see DESIGN.md §24); "
                   "default: built-in availability / p99-latency / "
                   "update-visible / ann-recall objectives")
    p.add_argument("--slow-ms", type=float, default=None,
                   help="flight-recorder tail threshold: requests "
                   "slower than this are kept; default: the latency "
                   "SLO's p99 target")
    p.add_argument("--flight-capacity", type=int, default=256,
                   help="flight-recorder ring bound (records)")
    p.add_argument("--flight-out", default=None,
                   help="write the flight recording (records + kept "
                   "span trees) here at drain/SIGTERM; the in-band "
                   "'flight_dump' op dumps on demand")
    # -- firehose update pipelining (DESIGN.md §30) --------------------
    p.add_argument("--update-queue", type=int, default=0,
                   help="bounded update-queue admission: queue up to "
                   "this many updates for the coalescing pump; past "
                   "the bound submitters get an immediate "
                   "'backpressure' error (0 = legacy one-broadcast-"
                   "per-update)")
    p.add_argument("--update-coalesce", type=int, default=8,
                   help="max queued updates folded into ONE broadcast "
                   "(conflicting windows split automatically)")
    p.add_argument("--update-flush-ms", type=float, default=5.0,
                   help="how long the pump lingers for more queued "
                   "updates before broadcasting")
    # -- closed-loop autoscale (router/autoscale.py) -------------------
    p.add_argument("--autoscale", action="store_true",
                   help="let queue-depth / shed / SLO-burn signals "
                   "spawn and drain workers between --workers (the "
                   "floor) and --max-workers; implies epoch-replay "
                   "retention so spawned workers can catch up")
    p.add_argument("--max-workers", type=int, default=None,
                   help="autoscale ceiling (default: 2x --workers)")
    p.add_argument("--autoscale-interval", type=float, default=1.0,
                   help="seconds between autoscale signal evaluations")
    return p


def _worker_argv(args, index: int, partition: bool = False) -> list[str]:
    argv = [sys.executable, "-m", "distributed_pathsim_tpu.cli", "worker",
            "--worker-id", f"w{index}"]
    if partition:
        argv += ["--partition-index", str(index),
                 "--partitions", str(args.workers),
                 "--partition-replication", str(args.replication)]
    for name in _FORWARD_VALUE:
        val = getattr(args, name)
        if val is None:
            continue
        argv += [f"--{name.replace('_', '-')}", str(val)]
    for name in _FORWARD_PATH:
        val = getattr(args, name)
        if val is None:
            continue
        argv += [f"--{name.replace('_', '-')}",
                 _suffix_path(str(val), f"w{index}")]
    for name in _FORWARD_TRUE:
        if getattr(args, name):
            argv.append(f"--{name.replace('_', '-')}")
    return argv


def router_loop(router: Router, in_stream, out_stream) -> int:
    """Upstream JSONL loop: responses stream back as their futures
    resolve (out of order; clients match on ``id``)."""
    from ..resilience import preemption_handler

    wlock = threading.Lock()

    def respond(resp: dict) -> None:
        line = json.dumps(resp) + "\n"
        with wlock:
            out_stream.write(line)
            out_stream.flush()

    for line in in_stream:
        if preemption_handler.requested():
            router.drain()
            return 0
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as exc:
            respond({"id": None, "ok": False, "error": f"bad request: {exc}"})
            continue
        op = req.get("op")
        if op in ("shutdown", "drain"):
            clean = router.drain()
            respond({"id": req.get("id"), "ok": True,
                     "result": {"shutdown": op == "shutdown",
                                "draining": True, "clean": clean}})
            return 0
        try:
            fut = router.submit(req)
        except RouterShed as exc:
            respond({"id": req.get("id"), "ok": False, "error": str(exc),
                     "shed": True})
            continue
        fut.add_done_callback(lambda f: respond(f.result()))
        if preemption_handler.requested():
            router.drain()
            return 0
    router.drain()
    return 0


def router_main(argv: list[str] | None = None) -> int:
    args = build_router_parser().parse_args(argv)
    if args.workers < 1:
        raise ValueError("--workers must be >= 1")
    from .. import obs
    from ..resilience import preemption_handler

    # the router traces too: its root/dispatch spans are the trunk
    # every worker subtree stitches into (fleet head sampling is the
    # ROUTER's decision, propagated on the wire)
    obs.configure(
        metrics=not args.no_metrics,
        tracing=True if args.trace_out else None,
        trace_sample=args.trace_sample,
    )
    slo_specs: tuple = ()
    if args.slo_specs:
        with open(args.slo_specs, encoding="utf-8") as f:
            slo_specs = obs.specs_from_json(f.read())
    logger = RunLogger(output_path=None, echo=False,
                       metrics_path=args.metrics)
    set_event_sink(logger)
    installed = preemption_handler.install()
    partition_mode = args.mode == "partition"
    transports = {
        f"w{i}": SubprocessTransport(
            f"w{i}", _worker_argv(args, i, partition=partition_mode)
        )
        for i in range(args.workers)
    }
    if partition_mode:
        from .partition import PartitionRouter, PartitionRouterConfig

        router = PartitionRouter(
            transports,
            PartitionRouterConfig(
                partitions=args.workers,
                replication=args.replication,
                heartbeat_interval_s=args.heartbeat_interval,
                heartbeat_miss_limit=args.heartbeat_miss,
                max_inflight=args.max_inflight,
                default_deadline_ms=args.deadline_ms,
                scrape_interval_s=args.scrape_interval,
                slo_specs=slo_specs,
                slow_ms=args.slow_ms,
                flight_capacity=args.flight_capacity,
            ),
        )
    else:
        router = Router(
            transports,
            RouterConfig(
                routing=args.routing,
                hedge_ms=args.hedge_ms or None,
                heartbeat_interval_s=args.heartbeat_interval,
                heartbeat_miss_limit=args.heartbeat_miss,
                max_inflight=args.max_inflight,
                default_deadline_ms=args.deadline_ms,
                scrape_interval_s=args.scrape_interval,
                slo_specs=slo_specs,
                slow_ms=args.slow_ms,
                flight_capacity=args.flight_capacity,
                update_queue=args.update_queue,
                update_coalesce=args.update_coalesce,
                update_flush_ms=args.update_flush_ms,
                # spawned workers boot the base graph and catch up by
                # replaying the epoch log — it must stay replayable
                retain_replay=args.autoscale,
            ),
        )
    # drain-time artifacts: written by Router.drain() while the
    # workers can still answer the final span-ring scrape
    router.flight_out = args.flight_out
    router.fleet_trace_out = args.trace_out
    # the router's --metrics-file is the FLEET export: every scraped
    # worker's series with a worker label, atomically, plus the full
    # fleet_metrics JSON beside it for `dpathsim fleet-stats`
    exporter = (
        obs.FleetTextfileExporter(
            args.metrics_file,
            router.metric_parts,
            interval_s=args.metrics_interval,
            snapshot_fn=lambda: router.fleet_metrics(refresh=False),
        )
        if args.metrics_file
        else None
    )
    autoscaler = None
    if args.autoscale and not partition_mode:
        from .autoscale import AutoscaleConfig, Autoscaler

        autoscaler = Autoscaler(
            router,
            # spawned replicas run the exact argv the seed fleet used
            # (the autoscaler always mints fresh w<N> ids)
            lambda wid: SubprocessTransport(
                wid, _worker_argv(args, int(wid[1:]))
            ),
            AutoscaleConfig(
                min_workers=args.workers,
                max_workers=args.max_workers or 2 * args.workers,
                eval_interval_s=args.autoscale_interval,
            ),
        )
    try:
        router.start()
        if exporter is not None:
            exporter.start()
        if autoscaler is not None:
            autoscaler.start()
        print(
            f"router: {args.workers} workers, routing={args.routing}, "
            f"n={router.n}; JSONL on stdin",
            file=sys.stderr,
        )
        return router_loop(router, sys.stdin, sys.stdout)
    finally:
        runtime_event("router_exit", echo=False)
        if autoscaler is not None:
            autoscaler.stop()
        # a loop that exited without drain (EOF already drains; an
        # exception might not) still owes the shutdown artifacts
        router._shutdown_dumps()
        router.close()
        if exporter is not None:
            exporter.stop()
        if installed:
            preemption_handler.uninstall()
            preemption_handler.reset()
        set_event_sink(None)
        logger.close()


def build_fleet_stats_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dpathsim fleet-stats",
        description="one-shot fleet summary (`top` for the router): "
        "worker table, merged fleet-exact latency per op, headline "
        "counters, SLO burn status",
    )
    p.add_argument(
        "snapshot", nargs="?", default="-",
        help="fleet metrics JSON: the file the router's --metrics-file "
        "exporter writes beside the .prom (<file>.json), or '-' to "
        "read a fleet_metrics response from stdin (e.g. piped from "
        "`echo '{\"op\":\"fleet_metrics\"}' | dpathsim router ...`)",
    )
    p.add_argument("--json", action="store_true",
                   help="re-emit the snapshot as JSON instead of the "
                   "rendered table (for tooling)")
    return p


def fleet_stats_main(argv: list[str] | None = None) -> int:
    from ..obs import render_fleet_stats

    args = build_fleet_stats_parser().parse_args(argv)
    if args.snapshot == "-":
        data = json.load(sys.stdin)
    else:
        with open(args.snapshot, encoding="utf-8") as f:
            data = json.load(f)
    # accept a raw fleet_metrics result OR a protocol response envelope
    if "merged" not in data and isinstance(data.get("result"), dict):
        data = data["result"]
    if args.json:
        json.dump(data, sys.stdout, indent=2)
        print()
    else:
        print(render_fleet_stats(data))
    return 0
