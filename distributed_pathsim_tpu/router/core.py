"""The router core: fan requests over worker replicas, lose nothing.

One :class:`Router` owns N worker transports and gives upstream clients
the same JSONL protocol one ``dpathsim serve`` process speaks — with
one process no longer being one failure domain. The design center is
*robustness*, wired through the existing resilience primitives:

- **Routing** (hashring.py): consistent-hash-by-row for cache affinity
  (the same row keeps hitting the replica whose tiers hold it), or
  contiguous row ranges; either yields a deterministic preference order
  that failover, hedging, and fencing all walk.
- **Failure detection**: per-worker heartbeats (``health`` op — pongs
  carry queue depth and the consistency token) catch *death* and
  *stalls* (miss limit exceeded → the worker is routed around and its
  in-flight work re-dispatched); transport EOF/broken-pipe catches
  death instantly. A stall-suspected worker that pongs again is
  readmitted — suspicion is not a death sentence.
- **Zero lost requests**: every admitted request lives in the pending
  table until exactly one response resolves it. A worker dying
  mid-batch re-dispatches its pending work to a surviving replica;
  retried work is idempotent (dedup by ``request_id`` at both ends —
  the worker replays mutation acks, the router keeps only the first
  answer).
- **Hedged requests**: a query in flight longer than the hedge
  threshold gets a duplicate sent to the next replica in preference
  order; first answer wins, the loser's arrival is counted and
  dropped. This bounds the p99 a stalled-but-not-dead replica causes.
- **Deadlines**: the protocol's ``deadline_ms`` budget is re-computed
  at every (re)dispatch — a failover or hedge never grants more time
  than the caller has left, and an expired budget fails fast instead
  of burning a replica (resilience.Deadline).
- **Admission**: the pending table is bounded; past it, submissions
  shed (:class:`RouterShed`) — and a worker that sheds locally pushes
  the request to the next replica, so the router only sheds when every
  replica is saturated.
- **Delta fencing**: ``update`` broadcasts carry the chained
  ``(base_fp, delta_seq)`` token. The router records each epoch's
  affected-row set; a replica that missed a broadcast is *fenced* —
  never handed a query for an affected row — until catch-up (ordered
  replay of the missed updates, idempotent by request id) brings its
  token to the head. No stale row can escape.

Chaos seams: ``heartbeat`` (a probe that never happened) and
``delta_broadcast`` (a worker missing an update) fire here;
``worker_dispatch`` fires in the worker (worker.py). See
tests/test_router.py and ``make chaos-router``.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from concurrent.futures import Future

from ..obs.metrics import get_registry
from ..resilience import Deadline, inject
from ..utils.logging import runtime_event
from .hashring import make_policy
from .transport import WorkerGone

ROUTED_OPS = frozenset({"topk", "scores"})

# worker statuses
UP = "up"
SUSPECT = "suspect"      # heartbeat-missed: routed around, resurrectable
DOWN = "down"            # transport-dead: gone for good


class RouterShed(RuntimeError):
    """Admission refused: the router's pending table is at its bound
    (or every replica is saturated)."""


@dataclasses.dataclass
class RouterConfig:
    routing: str = "hash"            # hash | range
    vnodes: int = 64
    max_inflight: int = 512          # admission bound on pending requests
    default_deadline_ms: float | None = None
    heartbeat_interval_s: float = 0.25
    heartbeat_miss_limit: int = 4    # unanswered intervals before SUSPECT
    hedge_ms: float | None = 100.0   # None disables hedged requests
    worker_queue_limit: int = 256    # per-replica saturation threshold
    max_attempts: int = 4            # distinct replicas tried per request
    update_timeout_s: float = 60.0
    drain_timeout_s: float = 30.0
    # how long a request may sit PARKED (no replica currently eligible:
    # every candidate suspected or fenced) before it fails; a transient
    # all-suspect blip — e.g. a stalled box starving every worker of
    # CPU for a second — must not turn into client-visible errors
    park_timeout_s: float = 10.0


class _WorkerState:
    __slots__ = (
        "wid", "transport", "status", "epoch", "queue_depth",
        "last_pong", "assigned", "catchup_active", "token",
        "last_health", "pong_seq",
    )

    def __init__(self, wid: str, transport):
        self.wid = wid
        self.transport = transport
        self.status = UP
        self.epoch = 0               # index into the router's epoch log
        self.queue_depth = 0
        self.last_pong = time.monotonic()
        self.assigned: set[str] = set()   # request ids in flight here
        self.catchup_active = False
        self.token: tuple[str, int] | None = None
        self.last_health: dict = {}
        self.pong_seq = 0


class _Pending:
    __slots__ = (
        "rid", "req", "key", "row", "future", "deadline", "tried",
        "assigned", "hedged", "hedge_sent", "t0", "failovers", "parked",
    )

    def __init__(self, rid: str, req: dict, key, row, future, deadline):
        self.rid = rid
        self.req = req
        self.key = key
        self.row = row
        self.future = future
        self.deadline = deadline
        self.tried: list[str] = []
        self.assigned: set[str] = set()
        self.hedged = False      # hedge CONSIDERED (one shot per request)
        self.hedge_sent = False  # hedge actually dispatched
        self.failovers = 0
        self.parked = False
        self.t0 = time.monotonic()


class _Epoch:
    """One entry of the delta log: the consistency token after this
    update, the wire request to replay for catch-up, and the rows it
    affected (None = all rows; epoch 0 is the base graph)."""

    __slots__ = ("token", "wire_req", "affected", "rid")

    def __init__(self, token, wire_req=None, affected=None, rid=None):
        self.token = tuple(token)
        self.wire_req = wire_req
        self.affected = affected
        self.rid = rid


class _UpdatePending:
    __slots__ = ("rid", "client_id", "future", "waiting", "acks",
                 "failures", "t0", "epoch_index", "first_result", "wire")

    def __init__(self, rid, client_id, future, waiting, wire):
        self.rid = rid
        self.client_id = client_id
        self.future = future
        self.waiting: set[str] = set(waiting)
        self.acks: dict[str, dict] = {}
        self.failures: dict[str, str] = {}
        self.t0 = time.monotonic()
        self.epoch_index: int | None = None
        self.first_result: dict | None = None
        self.wire = wire  # replayable request (catch-up; same request_id)


class Router:
    """Owns worker transports and the pending table. ``transports`` is
    ``{worker_id: transport}`` (not yet started); :meth:`start` brings
    them up, verifies they serve the same graph, and starts the
    heartbeat/hedge maintenance thread."""

    def __init__(self, transports: dict, config: RouterConfig | None = None):
        if not transports:
            raise ValueError("router needs at least one worker")
        self.config = config or RouterConfig()
        self._lock = threading.RLock()
        self.workers: dict[str, _WorkerState] = {
            wid: _WorkerState(wid, t) for wid, t in transports.items()
        }
        self._pending: dict[str, _Pending] = {}
        self._updates: dict[str, _UpdatePending] = {}
        self._epochs: list[_Epoch] = []
        self._epoch_by_token: dict[tuple, int] = {}
        self._compacted_to = 0
        self._rid_seq = itertools.count(1)
        self._hb_seq = itertools.count(1)
        self._update_seq = itertools.count(1)
        self._update_lock = threading.Lock()  # serializes broadcasts
        self._draining = False
        self._closed = threading.Event()
        self._maintenance: threading.Thread | None = None
        self.policy = None
        self.n = 0
        # counters (per-process registry; the router is one per process)
        reg = get_registry()
        self._m_requests = reg.counter(
            "dpathsim_router_requests_total",
            "router requests by outcome",
        )
        self._m_failovers = reg.counter(
            "dpathsim_router_failovers_total",
            "re-dispatches after worker death/stall/retriable failure",
        )
        self._m_hedges = reg.counter(
            "dpathsim_router_hedges_total", "hedged duplicate dispatches"
        ).labels()
        self._m_dups = reg.counter(
            "dpathsim_router_dup_responses_total",
            "late/duplicate worker responses dropped by request-id dedup",
        ).labels()
        self._m_fence_skips = reg.counter(
            "dpathsim_router_fence_skips_total",
            "routing decisions that skipped a fenced replica",
        ).labels()
        self._m_latency = reg.histogram(
            "dpathsim_router_request_seconds",
            "router submit-to-resolve latency by outcome",
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self, ready_timeout: float = 180.0) -> None:
        for w in self.workers.values():
            w.transport.start(self._on_message, self._on_death)
        tokens = {}
        for w in self.workers.values():
            info = w.transport.wait_ready(ready_timeout)
            tokens[w.wid] = (info.get("base_fp"), int(info.get("delta_seq", 0)))
            w.token = tokens[w.wid]
            self.n = int(info.get("n", self.n))
        base = next(iter(tokens.values()))
        if any(t != base for t in tokens.values()):
            raise ValueError(
                f"workers disagree on the base graph: {tokens} — every "
                "replica must serve the same dataset/config"
            )
        self._epochs.append(_Epoch(token=base))
        self._epoch_by_token[tuple(base)] = 0
        # pong clocks start NOW, not at construction: worker startup
        # (backend build + warmup) happens between __init__ and here,
        # and counting it as silence would mark every worker stalled
        # on the first probe
        now = time.monotonic()
        for w in self.workers.values():
            w.last_pong = now
        self.policy = make_policy(
            self.config.routing, list(self.workers), n_rows=max(self.n, 1),
            vnodes=self.config.vnodes,
        )
        self._maintenance = threading.Thread(
            target=self._maintenance_loop, name="pathsim-router-maint",
            daemon=True,
        )
        self._maintenance.start()
        runtime_event(
            "router_ready", workers=len(self.workers), n=self.n,
            routing=self.config.routing, fingerprint=base[0],
        )

    def close(self) -> None:
        self._closed.set()
        for w in self.workers.values():
            w.transport.close()

    def drain(self) -> bool:
        """Graceful stop: reject new work, resolve everything pending,
        drain the workers. True if everything flushed in time."""
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + self.config.drain_timeout_s
        clean = True
        while time.monotonic() < deadline:
            with self._lock:
                if not self._pending and not self._updates:
                    break
            time.sleep(0.005)
        else:
            clean = False
        for w in self.workers.values():
            if w.transport.alive:
                try:
                    w.transport.terminate()
                except Exception:
                    pass
        runtime_event(
            "router_drain", clean=clean,
            pending=len(self._pending), updates=len(self._updates),
        )
        return clean

    # -- submission --------------------------------------------------------

    def submit(self, req: dict) -> Future:
        """Admit one protocol request; returns a Future of the response
        dict. Raises :class:`RouterShed` at the admission bound."""
        op = req.get("op", "topk")
        fut: Future = Future()
        if self._draining:
            fut.set_result({
                "id": req.get("id"), "ok": False, "error": "draining",
                "draining": True,
            })
            return fut
        if op == "ping":
            fut.set_result({"id": req.get("id"), "ok": True,
                            "result": {"pong": True}})
            return fut
        if op in ("stats", "health"):
            fut.set_result({"id": req.get("id"), "ok": True,
                            "result": self.stats()})
            return fut
        if op == "update":
            return self._submit_update(req, fut)
        if op == "invalidate":
            return self._submit_invalidate(req, fut)
        if op not in ROUTED_OPS:
            fut.set_result({"id": req.get("id"), "ok": False,
                            "error": f"unknown op {op!r}"})
            return fut
        with self._lock:
            if len(self._pending) >= self.config.max_inflight:
                self._m_requests.inc(outcome="shed")
                runtime_event(
                    "router_shed", depth=self.config.max_inflight,
                    echo=False,
                )
                raise RouterShed(
                    f"router pending table at bound "
                    f"({self.config.max_inflight})"
                )
            rid = f"r{next(self._rid_seq)}"
            row = req.get("row")
            row = int(row) if row is not None else None
            key = row if row is not None else str(
                req.get("source") or req.get("source_id") or ""
            )
            deadline = Deadline.from_ms(
                req.get("deadline_ms", self.config.default_deadline_ms)
            )
            p = _Pending(rid, req, key, row, fut, deadline)
            self._pending[rid] = p
        verdict = self._dispatch(p)
        if verdict is not None:
            self._park_or_fail(p, verdict)
        return fut

    def request(self, req: dict, timeout: float = 60.0) -> dict:
        """Synchronous convenience: submit + wait."""
        return self.submit(req).result(timeout=timeout)

    # -- routing -----------------------------------------------------------

    def _eligible(self, p: _Pending, exclude) -> tuple[str | None, str]:
        """Pick the next replica for ``p`` under the lock. Returns
        (worker_id, reason-if-none)."""
        saturated = fenced = exhausted = 0
        for wid in self.policy.preference(p.key):
            w = self.workers[wid]
            if w.status != UP or not w.transport.alive:
                continue
            if wid in exclude:
                exhausted += 1  # alive, but this request already tried it
                continue
            if self._fenced(w, p.row):
                fenced += 1
                self._m_fence_skips.inc()
                continue
            if w.queue_depth >= self.config.worker_queue_limit:
                saturated += 1
                continue
            return wid, ""
        if saturated:
            return None, "saturated"
        if fenced:
            return None, "fenced"
        if exhausted:
            # every live replica already refused this request (shed /
            # transient failure): surface that, don't park — the client
            # retrying later IS the backoff
            return None, "exhausted"
        return None, "no live workers"

    def _fenced(self, w: _WorkerState, row: int | None) -> bool:
        """Is this replica forbidden from answering for ``row``? True
        when it missed a delta whose affected set could cover the query
        (unknown rows — label queries — only go to caught-up replicas
        while any fence is active)."""
        head = len(self._epochs) - 1
        if w.epoch >= head:
            return False
        for epoch in self._epochs[w.epoch + 1:]:
            if epoch.affected is None or row is None:
                return True
            if row in epoch.affected:
                return True
        return False

    def _dispatch(self, p: _Pending, exclude: set | None = None) -> str | None:
        """Send ``p`` to the best eligible replica. None on success, an
        error string when no replica can take it."""
        exclude = set(exclude or ())
        while True:
            if p.deadline is not None and p.deadline.expired:
                return "deadline exceeded"
            with self._lock:
                if p.rid not in self._pending:
                    return None  # already resolved (late failover race)
                if len(p.tried) >= self.config.max_attempts:
                    return "max attempts exhausted"
                wid, why = self._eligible(p, exclude | set(p.tried))
                if wid is None:
                    return why
                w = self.workers[wid]
                p.tried.append(wid)
                p.assigned.add(wid)
                w.assigned.add(p.rid)
            wire = dict(p.req)
            wire["id"] = p.rid
            wire["request_id"] = p.rid
            if p.deadline is not None:
                wire["deadline_ms"] = max(p.deadline.remaining_ms(), 0.0)
            try:
                w.transport.send(wire)
                return None
            except WorkerGone:
                with self._lock:
                    p.assigned.discard(wid)
                    w.assigned.discard(p.rid)
                self._mark_down(wid, DOWN, "send failed")
                exclude.add(wid)

    # -- resolution --------------------------------------------------------

    def _resolve(self, p: _Pending, resp: dict) -> None:
        elapsed = time.monotonic() - p.t0
        client_resp = dict(resp)
        client_resp["id"] = p.req.get("id")
        client_resp["request_id"] = p.rid
        outcome = "ok" if resp.get("ok") else "error"
        if p.failovers:
            client_resp["failovers"] = p.failovers
        if p.hedge_sent:
            client_resp["hedged"] = True
        self._m_requests.inc(outcome=outcome)
        self._m_latency.observe(elapsed, outcome=outcome)
        p.future.set_result(client_resp)

    def _park_or_fail(self, p: _Pending, verdict: str) -> None:
        """No replica can take ``p`` right now. Hard verdicts fail;
        saturation sheds (the ISSUE contract: when every replica is
        saturated the router says so immediately, it does not queue
        unboundedly); transient unavailability — every candidate
        suspected or fenced — PARKS the request for the maintenance
        loop to retry, because a worker coming back (pong) or catching
        up (delta replay) makes it dispatchable again."""
        if verdict in ("deadline exceeded", "max attempts exhausted"):
            self._fail(p, verdict)
            return
        if verdict == "saturated":
            self._fail(p, "all replicas saturated", shed=True)
            return
        if verdict == "exhausted":
            self._fail(p, "all replicas refused", shed=True)
            return
        with self._lock:
            recoverable = any(
                w.status in (UP, SUSPECT) and (
                    w.transport.alive or w.status == SUSPECT
                )
                for w in self.workers.values()
            )
            if recoverable and p.rid in self._pending:
                p.parked = True
                p.tried.clear()  # a resurrected replica gets a fresh try
                runtime_event("router_parked", rid=p.rid,
                              reason=verdict, echo=False)
                return
        self._fail(p, verdict)

    def _retry_parked(self, now: float) -> None:
        ready: list[_Pending] = []
        cfg = self.config
        with self._lock:
            for p in self._pending.values():
                if p.parked:
                    ready.append(p)
        for p in ready:
            if p.deadline is not None and p.deadline.expired:
                self._fail(p, "deadline exceeded")
                continue
            if (
                p.deadline is None
                and now - p.t0 > cfg.park_timeout_s
            ):
                self._fail(p, "no live workers")
                continue
            with self._lock:
                if p.rid not in self._pending:
                    continue
                p.parked = False
            verdict = self._dispatch(p)
            if verdict is not None:
                self._park_or_fail(p, verdict)

    def _fail(self, p: _Pending, error: str, **flags) -> None:
        with self._lock:
            if self._pending.pop(p.rid, None) is None:
                return
            for wid in p.assigned:
                self.workers[wid].assigned.discard(p.rid)
        resp = {"ok": False, "error": error, **flags}
        if error == "deadline exceeded":
            resp["deadline_exceeded"] = True
        if error in ("saturated", "shed"):
            resp["shed"] = True
        self._resolve(p, resp)

    def _on_message(self, wid: str, obj: dict) -> None:
        if "event" in obj:
            return  # ready/drained events: informational here
        rid = obj.get("id")
        if isinstance(rid, str) and rid.startswith("hb:"):
            self._on_pong(wid, obj)
            return
        if isinstance(rid, str) and rid.startswith(("up:", "cu:")):
            self._on_update_ack(wid, rid, obj)
            return
        if isinstance(rid, str) and rid.startswith("inv:"):
            return  # broadcast invalidate ack: fire-and-forget

        with self._lock:
            p = self._pending.get(rid) if isinstance(rid, str) else None
            if p is not None and obj.get("ok"):
                del self._pending[rid]
                for awid in p.assigned:
                    self.workers[awid].assigned.discard(rid)
        if p is None:
            # hedge loser, or a stall-suspected worker answering after
            # its work was already failed over — dedup: drop + count
            self._m_dups.inc()
            return
        if obj.get("ok"):
            self._resolve(p, obj)
            return
        # failed response: reroute retriable failures, surface the rest
        retriable = bool(
            obj.get("shed") or obj.get("draining") or obj.get("transient")
        ) and not obj.get("deadline_exceeded")
        if not retriable:
            with self._lock:
                if self._pending.pop(p.rid, None) is None:
                    return
                for awid in p.assigned:
                    self.workers[awid].assigned.discard(p.rid)
            self._resolve(p, obj)
            return
        with self._lock:
            p.assigned.discard(wid)
            self.workers[wid].assigned.discard(p.rid)
            if p.assigned:
                return  # a hedge is still in flight; let it race
        p.failovers += 1
        self._m_failovers.inc(reason="worker_error")
        verdict = self._dispatch(p)
        if verdict is not None:
            self._park_or_fail(p, verdict)

    def _on_death(self, wid: str, reason: str) -> None:
        self._mark_down(wid, DOWN, reason)

    def _mark_down(self, wid: str, status: str, reason: str) -> None:
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status == DOWN:
                return
            if w.status == status:
                return
            w.status = status
            orphans = [
                self._pending[rid]
                for rid in w.assigned
                if rid in self._pending
            ]
            w.assigned.clear()
            for p in orphans:
                p.assigned.discard(wid)
        runtime_event(
            "router_worker_down", worker_id=wid, status=status,
            reason=reason, orphaned=len(orphans),
        )
        get_registry().counter(
            "dpathsim_router_worker_down_total",
            "workers marked down/suspect, by cause",
        ).inc(status=status)
        for p in orphans:
            with self._lock:
                if p.rid not in self._pending or p.assigned:
                    continue  # resolved meanwhile, or hedged elsewhere
            p.failovers += 1
            self._m_failovers.inc(reason=reason.split(" ")[0] or "death")
            verdict = self._dispatch(p)
            if verdict is not None:
                self._park_or_fail(p, verdict)

    # -- heartbeats, stall detection, hedging ------------------------------

    def _maintenance_loop(self) -> None:
        cfg = self.config
        interval = cfg.heartbeat_interval_s
        hedge_s = (cfg.hedge_ms / 1e3) if cfg.hedge_ms else None
        tick = min(interval, (hedge_s / 4) if hedge_s else interval)
        tick = max(tick, 0.005)
        next_probe = 0.0
        while not self._closed.wait(tick):
            now = time.monotonic()
            if now >= next_probe:
                next_probe = now + interval
                self._probe_workers(now)
            if hedge_s is not None:
                self._hedge_scan(now, hedge_s)
            self._retry_parked(now)
            self._sweep_updates(now)

    def _probe_workers(self, now: float) -> None:
        cfg = self.config
        for w in list(self.workers.values()):
            if w.status == DOWN or not w.transport.alive:
                continue
            try:
                # the heartbeat seam: an injected error here is a probe
                # that never happened — enough of them and a healthy
                # worker goes SUSPECT (and comes back at the next pong)
                inject.fire("heartbeat")
                w.transport.send(
                    {"id": f"hb:{w.wid}:{next(self._hb_seq)}",
                     "op": "health"}
                )
            except inject.InjectedFault:
                pass
            except WorkerGone:
                self._mark_down(w.wid, DOWN, "heartbeat send failed")
                continue
            silence = now - w.last_pong
            if (
                w.status == UP
                and silence > cfg.heartbeat_interval_s * cfg.heartbeat_miss_limit
            ):
                self._mark_down(
                    w.wid, SUSPECT,
                    f"stall {silence * 1e3:.0f}ms without pong",
                )

    def _on_pong(self, wid: str, obj: dict) -> None:
        if not obj.get("ok"):
            return
        result = obj.get("result") or {}
        token = (result.get("base_fp"), int(result.get("delta_seq", 0)))
        catchup_from = None
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status == DOWN:
                return
            w.last_pong = time.monotonic()
            w.queue_depth = int(result.get("queue_depth", 0))
            w.token = token
            w.last_health = result
            w.pong_seq += 1
            if w.status == SUSPECT:
                # the stall cleared: readmit (its in-flight work was
                # already failed over; dedup absorbs any late answers)
                w.status = UP
                runtime_event("router_worker_up", worker_id=wid,
                              echo=False)
            epoch = self._epoch_of(token)
            if epoch is None:
                # a token outside our history: divergent replica —
                # fence it from everything (epoch −1 predates epoch 0)
                w.epoch = -1
            else:
                w.epoch = max(w.epoch, epoch)
            if (
                w.epoch < len(self._epochs) - 1
                and not w.catchup_active
            ):
                w.catchup_active = True
                catchup_from = w.epoch + 1
            self._compact_epochs()
        if catchup_from is not None:
            self._send_catchup(wid, catchup_from)

    def _epoch_of(self, token) -> int | None:
        return self._epoch_by_token.get(tuple(token))

    def _compact_epochs(self) -> None:
        """Drop the replay payload (and affected set) of epochs every
        live replica has passed — called under the lock after an epoch
        advance. Without this a long-lived router retains every delta's
        full edge lists forever. Compacted entries keep their token
        (the epoch index must stay stable) with ``affected=None``,
        which only a divergent (epoch −1) replica would ever consult —
        and None means "all rows", exactly the conservative fence such
        a replica already gets."""
        live = [
            w.epoch for w in self.workers.values()
            if w.status != DOWN and w.epoch >= 0
        ]
        if not live:
            return
        horizon = min(live)
        for i in range(max(self._compacted_to, 1), horizon + 1):
            self._epochs[i].wire_req = None
            self._epochs[i].affected = None
        self._compacted_to = max(self._compacted_to, horizon + 1)

    def _hedge_scan(self, now: float, hedge_s: float) -> None:
        stragglers: list[_Pending] = []
        with self._lock:
            for p in self._pending.values():
                if p.hedged or (now - p.t0) < hedge_s:
                    continue
                if p.deadline is not None and p.deadline.expired:
                    continue
                if len(p.assigned) != 1:
                    continue
                p.hedged = True  # one hedge attempt per request
                stragglers.append(p)
        for p in stragglers:
            # a failed hedge dispatch is not a request failure — the
            # original is still in flight; only a hedge that actually
            # went out is counted and flagged (a 1-replica router must
            # not fabricate hedge accounting)
            if self._dispatch(p, exclude=set(p.tried)) is None and (
                len(p.assigned) > 1
            ):
                p.hedge_sent = True
                self._m_hedges.inc()
                runtime_event(
                    "router_hedge", rid=p.rid, row=p.row,
                    waited_ms=round((now - p.t0) * 1e3, 1), echo=False,
                )

    # -- delta broadcast & fencing -----------------------------------------

    def _submit_update(self, req: dict, fut: Future) -> Future:
        with self._update_lock:
            seq = next(self._update_seq)
            urid = f"u{seq}"
            wire = dict(req)
            wire["request_id"] = urid
            wire["want_rows"] = True
            wire.pop("id", None)  # per-worker ids are stamped per send
            with self._lock:
                targets = [
                    w for w in self.workers.values()
                    if w.status == UP and w.transport.alive
                ]
                if not targets:
                    fut.set_result({"id": req.get("id"), "ok": False,
                                    "error": "no live workers"})
                    return fut
                up = _UpdatePending(
                    urid, req.get("id"), fut, [w.wid for w in targets],
                    wire,
                )
                self._updates[urid] = up
            for w in targets:
                per_wire = dict(wire)
                per_wire["id"] = f"up:{w.wid}:{seq}"
                try:
                    # the delta_broadcast seam: an injected error means
                    # THIS worker misses the update — it will lag the
                    # token head and be fenced until catch-up
                    inject.fire("delta_broadcast")
                    w.transport.send(per_wire)
                except (inject.InjectedFault, WorkerGone) as exc:
                    self._update_failure(urid, w.wid, repr(exc))
        return fut

    def _on_update_ack(self, wid: str, rid: str, obj: dict) -> None:
        """An ``update`` response — from the broadcast (``up:``) or a
        catch-up replay (``cu:``). Either way the ack's token tells us
        where this replica now stands in the epoch log."""
        urid = f"u{rid.rsplit(':', 1)[1]}"
        is_catchup = rid.startswith("cu:")
        if not obj.get("ok"):
            if is_catchup:
                with self._lock:
                    w = self.workers.get(wid)
                    if w is not None:
                        # drop the in-progress flag: the next pong
                        # showing lag retries the replay
                        w.catchup_active = False
                runtime_event(
                    "router_catchup_failed", worker_id=wid, rid=urid,
                    error=obj.get("error", "?"),
                )
            else:
                self._update_failure(urid, wid, obj.get("error", "?"))
            return
        result = obj.get("result") or {}
        token = (result.get("base_fp"), int(result.get("delta_seq", 0)))
        finished = None
        next_catchup = None
        with self._lock:
            up = self._updates.get(urid)
            if up is not None:
                if up.epoch_index is None:
                    # first ack defines the epoch: its token and
                    # affected set (None = rebuild = all rows). Later
                    # acks must agree — replicas are deterministic.
                    affected = result.get("affected_row_list")
                    self._epochs.append(_Epoch(
                        token=token,
                        wire_req=up.wire,
                        affected=(
                            frozenset(affected) if affected is not None
                            else None
                        ),
                        rid=urid,
                    ))
                    up.epoch_index = len(self._epochs) - 1
                    self._epoch_by_token[tuple(token)] = up.epoch_index
                    up.first_result = result
                elif tuple(token) != self._epochs[up.epoch_index].token:
                    runtime_event(
                        "router_token_divergence", worker_id=wid,
                        got=token,
                        expected=self._epochs[up.epoch_index].token,
                    )
            w = self.workers.get(wid)
            if w is not None:
                epoch = self._epoch_of(token)
                w.token = token
                w.epoch = epoch if epoch is not None else -1
                if is_catchup:
                    if 0 <= w.epoch < len(self._epochs) - 1:
                        next_catchup = w.epoch + 1  # keep replaying
                    else:
                        w.catchup_active = False
            if up is not None:
                up.waiting.discard(wid)
                up.acks[wid] = result
                # a replica that missed the broadcast but caught up
                # before the update finished has APPLIED it — it must
                # not be reported as both applied and lagging
                up.failures.pop(wid, None)
                if not up.waiting:
                    finished = self._updates.pop(urid)
            self._compact_epochs()
        if next_catchup is not None:
            self._send_catchup(wid, next_catchup)
        if finished is not None:
            self._finish_update(finished)

    def _update_failure(self, urid: str, wid: str, error: str) -> None:
        finished = None
        with self._lock:
            up = self._updates.get(urid)
            if up is None:
                return
            up.waiting.discard(wid)
            up.failures[wid] = error
            if not up.waiting:
                finished = self._updates.pop(urid)
        runtime_event(
            "router_update_miss", worker_id=wid, rid=urid, error=error,
        )
        if finished is not None:
            self._finish_update(finished)

    def _finish_update(self, up: _UpdatePending) -> None:
        ok = up.epoch_index is not None
        result = {
            "applied": sorted(up.acks),
            "missed": dict(up.failures),
            "lagging": sorted(up.failures),
        }
        if up.first_result is not None:
            result.update({
                k: up.first_result[k]
                for k in ("mode", "affected_rows", "delta_seq", "base_fp",
                          "fingerprint", "n")
                if k in up.first_result
            })
        runtime_event(
            "router_update", rid=up.rid, applied=len(up.acks),
            missed=len(up.failures), echo=False,
        )
        up.future.set_result({
            "id": up.client_id, "ok": ok,
            **({"result": result} if ok else
               {"error": "update applied on no replica", "detail": result}),
        })

    def _sweep_updates(self, now: float) -> None:
        expired: list[_UpdatePending] = []
        with self._lock:
            for urid, up in list(self._updates.items()):
                if now - up.t0 > self.config.update_timeout_s:
                    for wid in list(up.waiting):
                        up.failures[wid] = "ack timeout"
                    up.waiting.clear()
                    expired.append(self._updates.pop(urid))
        for up in expired:
            self._finish_update(up)

    def _send_catchup(self, wid: str, from_epoch: int) -> None:
        """Replay the FIRST missed update to a lagging replica; its ack
        advances the epoch and triggers the next replay (ordered — a
        delta chain applied out of order is a different graph)."""
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status != UP:
                if w is not None:
                    w.catchup_active = False
                return
            if from_epoch >= len(self._epochs) or from_epoch < 1:
                w.catchup_active = False
                return
            epoch = self._epochs[from_epoch]
            if epoch.wire_req is None:
                # nothing replayable (shouldn't happen: every epoch > 0
                # records its wire request) — leave the replica fenced
                w.catchup_active = False
                runtime_event(
                    "router_catchup_impossible", worker_id=wid,
                    epoch=from_epoch,
                )
                return
            wire = dict(epoch.wire_req)
            wire["id"] = f"cu:{wid}:{epoch.rid[1:]}"
        runtime_event(
            "router_catchup", worker_id=wid, epoch=from_epoch,
            rid=epoch.rid, echo=False,
        )
        try:
            w.transport.send(wire)
        except WorkerGone:
            self._mark_down(wid, DOWN, "catchup send failed")

    def _submit_invalidate(self, req: dict, fut: Future) -> Future:
        acked = 0
        for w in list(self.workers.values()):
            if w.status != UP or not w.transport.alive:
                continue
            try:
                w.transport.send({
                    "id": f"inv:{w.wid}", "op": "invalidate",
                })
                acked += 1
            except WorkerGone:
                self._mark_down(w.wid, DOWN, "send failed")
        fut.set_result({
            "id": req.get("id"), "ok": True,
            "result": {"invalidated": True, "workers": acked},
        })
        return fut

    # -- introspection -----------------------------------------------------

    def worker_health(self, wid: str, timeout: float = 10.0) -> dict:
        """A FRESH health snapshot from one worker: probe, wait for the
        pong (benches read compile counts around a measurement window,
        so a cached pong from before the window is not good enough)."""
        with self._lock:
            w = self.workers.get(wid)
            if w is None or w.status == DOWN:
                return {}
            seq0 = w.pong_seq
        try:
            w.transport.send(
                {"id": f"hb:{wid}:{next(self._hb_seq)}", "op": "health"}
            )
        except WorkerGone:
            return {}
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if w.pong_seq > seq0:
                    return dict(w.last_health)
            time.sleep(0.005)
        return {}

    def stats(self) -> dict:
        with self._lock:
            head = len(self._epochs) - 1
            return {
                "router": {
                    "workers": {
                        w.wid: {
                            "status": w.status,
                            "queue_depth": w.queue_depth,
                            "assigned": len(w.assigned),
                            "epoch": w.epoch,
                            "lag": head - w.epoch,
                            "token": list(w.token) if w.token else None,
                            # ANN index epoch from the last pong (None =
                            # exact-only replica): operators see which
                            # replicas hold a fresh candidate index;
                            # queries never NEED one — an ann request on
                            # an index-less replica answers exactly
                            "index": w.last_health.get("index"),
                        }
                        for w in self.workers.values()
                    },
                    "pending": len(self._pending),
                    "updates_pending": len(self._updates),
                    "epochs": head + 1,
                    "routing": self.config.routing,
                    "draining": self._draining,
                    "n": self.n,
                },
            }
